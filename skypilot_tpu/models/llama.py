"""Llama-family decoder-only transformer, TPU-first.

Replaces the reference's *recipe* approach (``llm/llama-3/llama3.yaml``
launches vLLM; ``examples/tpu/v6e/`` launches HF+PyTorch/XLA) with an in-tree
engine designed for XLA:

- Pure-functional: params are a pytree; every entry has a parallel tuple of
  logical axis names (``param_logical_axes``) mapped to mesh axes by
  ``skypilot_tpu.parallel.mesh`` rules — FSDP/TP/SP/EP are sharding rules,
  not code paths.
- ``lax.scan`` over stacked layer params: one compiled block regardless of
  depth (fast compiles, constant-size HLO), with optional per-layer
  rematerialization (``jax.checkpoint``) for training.
- bf16 activations/params, fp32 attention logits + softmax, fp32 norms —
  the standard TPU numerics recipe.
- GQA + RoPE + SwiGLU; MoE FFN is delegated to ``models.moe`` when
  ``cfg.is_moe`` (Mixtral-class, expert-parallel over the mesh).
"""
from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from skypilot_tpu.models.configs import ModelConfig
from skypilot_tpu.ops.attention import (attention, cached_attention,
                                        ring_decode_attention)

Params = Dict[str, Any]


# --------------------------------------------------------------------------
# Initialization
# --------------------------------------------------------------------------
def _dense_init(key, shape, dtype, fan_in):
    scale = fan_in ** -0.5
    return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)


def init_params(rng: jax.Array, cfg: ModelConfig) -> Params:
    """Initialize parameters. Layer params are stacked on a leading
    ``layers`` axis for lax.scan."""
    d, hd = cfg.dim, cfg.head_dim
    n_h, n_kv, f, L = cfg.n_heads, cfg.n_kv_heads, cfg.ffn_dim, cfg.n_layers
    keys = jax.random.split(rng, 8)

    def stack_init(key, shape, fan_in):
        ks = jax.random.split(key, L)
        return jnp.stack([_dense_init(k, shape, cfg.dtype, fan_in)
                          for k in ks])

    params: Params = {
        'embed': _dense_init(keys[0], (cfg.vocab_size, d), cfg.dtype, d),
        'final_norm': (jnp.zeros((d,), jnp.float32) if cfg.norm_plus_one
                       else jnp.ones((d,), jnp.float32)),
        'layers': {
            'attn_norm': (jnp.zeros((L, d), jnp.float32)
                          if cfg.norm_plus_one
                          else jnp.ones((L, d), jnp.float32)),
            'ffn_norm': (jnp.zeros((L, d), jnp.float32)
                         if cfg.norm_plus_one
                         else jnp.ones((L, d), jnp.float32)),
            'wq': stack_init(keys[2], (d, n_h, hd), d),
            'wk': stack_init(keys[3], (d, n_kv, hd), d),
            'wv': stack_init(keys[4], (d, n_kv, hd), d),
            'wo': stack_init(keys[5], (n_h, hd, d), n_h * hd),
        },
    }
    if cfg.qkv_bias:                    # Qwen2-family attention biases
        params['layers'].update({
            'bq': jnp.zeros((L, n_h, hd), jnp.float32),
            'bk': jnp.zeros((L, n_kv, hd), jnp.float32),
            'bv': jnp.zeros((L, n_kv, hd), jnp.float32),
        })
    if not cfg.tie_embeddings:
        params['unembed'] = _dense_init(keys[1], (d, cfg.vocab_size),
                                        cfg.dtype, d)
    if cfg.is_moe:
        from skypilot_tpu.models import moe
        params['layers'].update(moe.init_moe_params(keys[6], cfg))
    else:
        k1, k2, k3 = jax.random.split(keys[6], 3)
        params['layers'].update({
            'w_gate': stack_init(k1, (d, f), d),
            'w_up': stack_init(k2, (d, f), d),
            'w_down': stack_init(k3, (f, d), f),
        })
    if cfg.lora_enabled:
        from skypilot_tpu.models import lora
        params['layers']['lora'] = lora.init_lora_layers(keys[7], cfg)
    return params


def param_logical_axes(cfg: ModelConfig) -> Params:
    """Same structure as ``init_params``, with logical-axis tuples as leaves.

    The leading scan axis is 'layers' (never sharded)."""
    axes: Params = {
        'embed': ('vocab_in', 'embed'),
        'final_norm': ('norm',),
        'layers': {
            'attn_norm': ('layers', 'norm'),
            'ffn_norm': ('layers', 'norm'),
            'wq': ('layers', 'embed', 'heads', 'head_dim'),
            'wk': ('layers', 'embed', 'kv_heads', 'head_dim'),
            'wv': ('layers', 'embed', 'kv_heads', 'head_dim'),
            'wo': ('layers', 'heads', 'head_dim', 'embed'),
        },
    }
    if cfg.qkv_bias:
        axes['layers'].update({
            'bq': ('layers', 'heads', 'head_dim'),
            'bk': ('layers', 'kv_heads', 'head_dim'),
            'bv': ('layers', 'kv_heads', 'head_dim'),
        })
    if not cfg.tie_embeddings:
        axes['unembed'] = ('embed', 'vocab')
    if cfg.is_moe:
        from skypilot_tpu.models import moe
        axes['layers'].update(moe.moe_logical_axes(cfg))
    else:
        axes['layers'].update({
            'w_gate': ('layers', 'embed', 'mlp'),
            'w_up': ('layers', 'embed', 'mlp'),
            'w_down': ('layers', 'mlp', 'embed'),
        })
    if cfg.lora_enabled:
        from skypilot_tpu.models import lora
        axes['layers']['lora'] = lora.lora_logical_axes(cfg)
    return axes


# --------------------------------------------------------------------------
# KV cache
# --------------------------------------------------------------------------
class KVCache(NamedTuple):
    """Decode cache. k/v: [layers, batch, max_seq, kv_heads, head_dim];
    length: [batch] valid entries per sequence (supports continuous
    batching where sequences are at different positions).

    int8 mode (``create(..., quantized=True)`` — the engines' own
    ``kv_cache_dtype`` knob, independent of weight quantization): k/v
    are int8 with per-(layer, slot, position, head) fp32 absmax/127
    scales — halves the decode cache read (the second-largest HBM
    stream after the weights). The dequantizing convert+mul fuses into
    the attention matmul's operand read, like the weight-only int8
    path; no materialized bf16 KV copy ever hits HBM. Every write site
    (prefill scatter, chunked-prefill chunks, decode merges, spec
    verify commits) quantizes through :func:`quantize_kv_rows`."""
    k: jax.Array
    v: jax.Array
    length: jax.Array
    k_scale: Optional[jax.Array] = None    # [L, b, S, hkv, 1] fp32
    v_scale: Optional[jax.Array] = None

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def packed(self) -> bool:
        """int4 mode: k/v hold two nibble codes per byte (uint8,
        head_dim halved); scales ride the int8 layout unchanged."""
        return self.k.dtype == jnp.uint8

    @classmethod
    def create(cls, cfg: ModelConfig, batch: int, max_seq: int,
               quantized: bool = False,
               kv_dtype: Optional[str] = None) -> 'KVCache':
        if kv_dtype is None:
            kv_dtype = 'int8' if quantized else 'bf16'
        shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
        length = jnp.zeros((batch,), jnp.int32)
        if kv_dtype == 'int4':
            if cfg.head_dim % 2:
                raise ValueError('int4 KV needs an even head_dim')
            pshape = shape[:-1] + (cfg.head_dim // 2,)
            sshape = shape[:-1] + (1,)
            return cls(k=jnp.zeros(pshape, jnp.uint8),
                       v=jnp.zeros(pshape, jnp.uint8),
                       length=length,
                       k_scale=jnp.zeros(sshape, jnp.float32),
                       v_scale=jnp.zeros(sshape, jnp.float32))
        if kv_dtype == 'int8' or quantized:
            sshape = shape[:-1] + (1,)
            return cls(k=jnp.zeros(shape, jnp.int8),
                       v=jnp.zeros(shape, jnp.int8),
                       length=length,
                       k_scale=jnp.zeros(sshape, jnp.float32),
                       v_scale=jnp.zeros(sshape, jnp.float32))
        return cls(k=jnp.zeros(shape, cfg.dtype),
                   v=jnp.zeros(shape, cfg.dtype),
                   length=length)


def cache_logical_axes(quantized: bool = False) -> KVCache:
    kv = ('layers', 'batch', None, 'kv_heads', 'head_dim')
    if quantized:
        # fp32 scales ride the same layout; their unit head_dim is
        # replicated by the divisibility-aware spec mapping.
        return KVCache(k=kv, v=kv, length=('batch',),
                       k_scale=kv, v_scale=kv)
    return KVCache(k=kv, v=kv, length=('batch',))


def quantize_kv_rows(rows: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[..., d] bf16 rows -> (int8 rows, [..., 1] fp32 scales)."""
    rf = rows.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(rf), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(rf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def quantize_kv_rows4(rows: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[..., d] bf16 rows -> (packed uint8 [..., d//2] nibble rows,
    [..., 1] fp32 scales). Same absmax discipline as int8 at 4-bit
    range (absmax/7, clip +-7); packing rides
    :func:`quantization.pack_int4` along the HEAD_DIM axis so every
    token row stays self-contained — single-row appends (decode ring
    merges, spec commits) never straddle a byte boundary the way a
    page-axis packing would."""
    from skypilot_tpu.models import quantization
    rf = rows.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(rf), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 7.0
    q = jnp.clip(jnp.round(rf / scale), -7, 7).astype(jnp.int8)
    return quantization.pack_int4(q, axis=-1), scale


def merge_rows_into_cache(cache: KVCache, k_rows: jax.Array,
                          v_rows: jax.Array, starts: jax.Array,
                          new_length: jax.Array) -> KVCache:
    """Scatter new [L, b, n, hkv, d] KV rows into the cache at each
    batch row's ``starts`` offset, quantizing on the way in when the
    cache is int8. Shared by the prefill forward and the fused decode
    horizon."""

    def write(c, n, start):            # c [L,S,h,d] <- n [L,n,h,d] @ start
        return lax.dynamic_update_slice(c, n, (0, start, 0, 0))

    def scatter(c, rows):
        return jax.vmap(write, in_axes=(1, 1, 0), out_axes=1)(
            c, rows.astype(c.dtype), starts)

    if cache.quantized:
        quant = quantize_kv_rows4 if cache.packed else quantize_kv_rows
        kq, ks = quant(k_rows)
        vq, vs = quant(v_rows)
        return KVCache(k=scatter(cache.k, kq), v=scatter(cache.v, vq),
                       length=new_length,
                       k_scale=scatter(cache.k_scale, ks),
                       v_scale=scatter(cache.v_scale, vs))
    return KVCache(k=scatter(cache.k, k_rows),
                   v=scatter(cache.v, v_rows), length=new_length)


# --------------------------------------------------------------------------
# Building blocks
# --------------------------------------------------------------------------
def rms_norm(x: jax.Array, w: jax.Array, eps: float,
             plus_one: bool = False) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    scale = (1.0 + w) if plus_one else w
    return (xf * lax.rsqrt(var + eps) * scale).astype(x.dtype)


def _embed_tokens(params: Params, tokens: jax.Array,
                  cfg: ModelConfig) -> jax.Array:
    table = params['embed']
    if tokens.shape[1] > 1 and _in_multidevice_mesh():
        # Training/prefill under a mesh: a gather from the fsdp-sharded
        # table forces an involuntary full rematerialization in the SPMD
        # partitioner (gather output is embed-sharded, activations are
        # batch-sharded). A one-hot matmul partitions cleanly and rides
        # the MXU — the TPU-idiomatic embedding (MaxText's iota-embed).
        # Decode (s == 1) keeps the gather: a per-step one-hot would
        # stream the whole table instead of b rows.
        oh = jax.nn.one_hot(tokens, cfg.vocab_size, dtype=table.dtype)
        x = jnp.einsum('bsv,vd->bsd', oh, table)
    else:
        x = table[tokens]
    if cfg.scale_embeddings:                  # Gemma: sqrt(dim) input scale
        x = (x.astype(jnp.float32) * cfg.dim ** 0.5).astype(x.dtype)
    return x


def _unembed_logits(params: Params, x: jax.Array,
                    cfg: ModelConfig) -> jax.Array:
    if cfg.tie_embeddings:                    # Gemma: unembed = embed^T
        return jnp.einsum('bsd,vd->bsv', x, params['embed'],
                          preferred_element_type=jnp.float32)
    from skypilot_tpu.models.quantization import qeinsum
    return qeinsum('bsd,dv->bsv', x, params['unembed'],
                   out_dtype=jnp.float32)


# Constrained decoding masks to a large-negative, FINITE value: -inf
# would make a fully-masked row all-NaN under softmax and trip the
# nonfinite-token eviction guard on a healthy request, and masked
# positions must stay orderable under temperature scaling.
VOCAB_MASK_NEG = -1e9


def apply_vocab_mask(logits: jax.Array,
                     vocab_mask: Optional[jax.Array]) -> jax.Array:
    """Constrained-decoding vocab mask (True = token allowed) applied
    at a sampling point. ``vocab_mask`` is [b, vocab]; extra position
    axes of ``logits`` (the speculative [b, k+1, vocab] verify and the
    all-positions prefill) broadcast after the batch axis. None = no
    constraint (byte-identical logits)."""
    if vocab_mask is None:
        return logits
    while vocab_mask.ndim < logits.ndim:
        vocab_mask = vocab_mask[:, None]
    return jnp.where(vocab_mask, logits,
                     jnp.asarray(VOCAB_MASK_NEG, logits.dtype))


def filtered_logits(logits: jax.Array, temps: jax.Array,
                    topks: jax.Array, topps: jax.Array,
                    vocab_mask: Optional[jax.Array] = None) -> jax.Array:
    """Temperature-scaled, top-k/top-p-masked logits over the LAST axis:
    kept tokens carry their scaled value, filtered ones -inf, so
    ``jax.random.categorical`` over the result draws from exactly the
    engines' sampling distribution. ``temps``/``topks``/``topps``
    broadcast over ``logits.shape[:-1]`` — the single-position decode
    sampler ([b, vocab]) and the speculative multi-position verify
    ([b, k+1, vocab]) share this one implementation, which is what
    makes rejection-sampling acceptance distribution-preserving.

    Filter semantics (identical to the historical ``sample_tokens``):
    top-k <= 0 and top-p >= 1 disable their filters; nucleus keeps the
    smallest prefix of the sorted distribution whose mass reaches
    top_p (the top-1 token always survives). Rows with temp <= 0 are
    scaled by 1/1e-6 — callers take the greedy argmax for those rows
    instead of sampling. ``vocab_mask`` (constrained decoding) composes
    here, at the one shared sampling point, BEFORE temperature/top-k/
    top-p so the filters act on the constrained distribution."""
    logits = apply_vocab_mask(logits, vocab_mask)
    shape = logits.shape[:-1]
    temps = jnp.broadcast_to(temps, shape)[..., None]
    topks = jnp.broadcast_to(topks, shape)[..., None]
    topps = jnp.broadcast_to(topps, shape)[..., None]
    scaled = logits / jnp.maximum(temps, 1e-6)
    sorted_desc = -jnp.sort(-scaled, axis=-1)
    idx = jnp.clip(topks - 1, 0, logits.shape[-1] - 1)
    kth = jnp.take_along_axis(sorted_desc, idx, axis=-1)
    thr_k = jnp.where(topks > 0, kth, -jnp.inf)
    masked_sorted = jnp.where(sorted_desc >= thr_k, sorted_desc,
                              -jnp.inf)
    probs = jax.nn.softmax(masked_sorted.astype(jnp.float32), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < topps
    thr_p = jnp.min(jnp.where(keep, masked_sorted, jnp.inf), axis=-1,
                    keepdims=True)
    thr = jnp.maximum(thr_k, jnp.where(topps < 1.0,
                                       thr_p.astype(scaled.dtype),
                                       -jnp.inf))
    return jnp.where(scaled >= thr, scaled, -jnp.inf)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x: [b, s, h, d], positions: [b, s]."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [b, s, d/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


_probe_broken_warned = False


def _ambient_mesh():
    """The active mesh context's mesh, or None.

    Tries the PUBLIC accessor first (``jax.sharding.get_mesh`` sees
    ``jax.sharding.use_mesh``/``set_mesh`` contexts), then probes the
    private locations that back the legacy ``with mesh:`` context (no
    public accessor exists for it) and fails open (None → no
    constraint) so a jax upgrade degrades perf, not correctness — but
    warns ONCE when every probe RAISED (probe broken ≠ no mesh), since
    silently disabled pipelining/sharding constraints would otherwise
    degrade with no signal. ``tests/test_aux_subsystems.py::
    test_ambient_mesh_probe`` additionally turns probe breakage into a
    visible CI failure on the pinned jax."""
    global _probe_broken_warned
    try:
        from jax.sharding import get_mesh
        m = get_mesh()
        if isinstance(m, jax.sharding.Mesh) and not m.empty:
            return m
    except Exception:  # pylint: disable=broad-except
        pass
    probe_healthy = False
    for probe in ('jax._src.mesh', 'jax.interpreters.pxla'):
        try:
            import importlib
            mod = importlib.import_module(probe)
            m = mod.thread_resources.env.physical_mesh
            probe_healthy = True
            if not m.empty:
                return m
            break   # both probes back the SAME context; one healthy
                    # read of an empty mesh settles it (and skipping
                    # the pxla probe avoids its DeprecationWarning)
        except Exception:  # pylint: disable=broad-except
            continue
    if not probe_healthy and not _probe_broken_warned:
        _probe_broken_warned = True
        import warnings
        warnings.warn(
            'skypilot_tpu: ambient-mesh probe failed (jax internals '
            'changed?); mesh-context detection is DISABLED — pipeline '
            'parallelism and activation sharding constraints will '
            'silently not apply inside `with mesh:` contexts.')
    return None


def _in_mesh_context() -> bool:
    return _ambient_mesh() is not None


def _in_multidevice_mesh() -> bool:
    """True when the ambient mesh spans more than one device (the case
    where gather-vs-one-hot embedding choice matters)."""
    m = _ambient_mesh()
    return m is not None and m.size > 1


_pp_probe_warned = False


def _pp_mesh():
    """The ambient mesh iff its pp axis is > 1 (else None).

    Rides ``_ambient_mesh`` (public accessor first, then the private
    legacy-context probe — which itself warns once when broken); the
    probe-works-at-all guarantee is pinned by
    ``tests/test_aux_subsystems.py::test_ambient_mesh_probe``."""
    global _pp_probe_warned
    try:
        env_mesh = _ambient_mesh()
        if env_mesh is None:
            return None
        return env_mesh if env_mesh.shape.get('pp', 1) > 1 else None
    except Exception:  # pylint: disable=broad-except
        if not _pp_probe_warned:
            _pp_probe_warned = True
            import warnings
            warnings.warn(
                'skypilot_tpu: ambient-mesh probe failed (jax internals '
                'changed?); pipeline parallelism is DISABLED and pp-'
                'sharded params will be all-gathered every step.')
        return None


import threading as _threading

_manual_region = _threading.local()


def _shard(x: jax.Array, *logical_axes: Optional[str]) -> jax.Array:
    """Activation sharding constraint via logical axes; no-op outside a mesh
    context (pure single-device runs, CPU unit tests) and inside manual
    shard_map regions (the pipeline body — mixing with_sharding_constraint
    into a partially-manual region trips XLA internal checks)."""
    if getattr(_manual_region, 'active', False):
        return x
    if not _in_mesh_context():
        return x
    from skypilot_tpu.parallel.mesh import spec_for
    return lax.with_sharding_constraint(x, spec_for(logical_axes))


def _ffn(layer: Params, x: jax.Array, cfg: ModelConfig,
         mlora_idx: Optional[jax.Array] = None) -> jax.Array:
    from skypilot_tpu.models.quantization import qeinsum
    lo = layer.get('lora') if isinstance(layer, dict) else None
    ml = layer.get('mlora') if isinstance(layer, dict) else None
    if mlora_idx is None:
        ml = None
    gate = qeinsum('bsd,df->bsf', x, layer['w_gate'])
    up = qeinsum('bsd,df->bsf', x, layer['w_up'])
    if lo is not None:
        from skypilot_tpu.models import lora as lora_lib
        gate = gate + lora_lib.apply(lo, 'w_gate', x, cfg)
        up = up + lora_lib.apply(lo, 'w_up', x, cfg)
    if ml is not None:
        from skypilot_tpu.models import multilora
        gate = multilora.adjusted(ml, 'w_gate', x, gate, mlora_idx)
        up = multilora.adjusted(ml, 'w_up', x, up, mlora_idx)
    act = jax.nn.silu if cfg.activation == 'silu' else \
        functools.partial(jax.nn.gelu, approximate=True)
    h = act(gate.astype(jnp.float32)).astype(x.dtype) * up
    h = _shard(h, 'batch', 'seq', 'mlp')
    down = qeinsum('bsf,fd->bsd', h, layer['w_down'])
    if lo is not None:
        from skypilot_tpu.models import lora as lora_lib
        down = down + lora_lib.apply(lo, 'w_down', h, cfg)
    if ml is not None:
        down = multilora.adjusted(ml, 'w_down', h, down, mlora_idx)
    return down


def _layer_core(layer: Params, x: jax.Array, cfg: ModelConfig,
                positions: jax.Array, attn_fn,
                mlora_idx: Optional[jax.Array] = None):
    """One transformer layer, parameterized by the attention op so every
    path (training full-sequence, prefill/decode against a cache, the
    fused serving loop) shares ONE copy of the layer math. ``attn_fn``
    maps roped (q, k, v) to the attention output.

    ``mlora_idx`` ([b] int32, -1 = none) gathers per-row adapters from
    the ``layer['mlora']`` bank slice (multi-tenant serving); None (the
    default, and every training/eval path) leaves the math untouched.

    Returns (x, (k, v) new kv rows, moe aux loss)."""
    from jax.ad_checkpoint import checkpoint_name
    h = rms_norm(x, layer['attn_norm'], cfg.norm_eps,
                  cfg.norm_plus_one)
    from skypilot_tpu.models.quantization import qeinsum
    lo = layer.get('lora') if isinstance(layer, dict) else None
    ml = layer.get('mlora') if isinstance(layer, dict) else None
    if mlora_idx is None:
        ml = None
    q = qeinsum('bsd,dhk->bshk', h, layer['wq'])
    k = qeinsum('bsd,dhk->bshk', h, layer['wk'])
    v = qeinsum('bsd,dhk->bshk', h, layer['wv'])
    if lo is not None:
        from skypilot_tpu.models import lora as lora_lib
        q = q + lora_lib.apply(lo, 'wq', h, cfg)
        k = k + lora_lib.apply(lo, 'wk', h, cfg)
        v = v + lora_lib.apply(lo, 'wv', h, cfg)
    if ml is not None:
        from skypilot_tpu.models import multilora
        q = multilora.adjusted(ml, 'wq', h, q, mlora_idx)
        k = multilora.adjusted(ml, 'wk', h, k, mlora_idx)
        v = multilora.adjusted(ml, 'wv', h, v, mlora_idx)
    if cfg.qkv_bias:
        q = q + layer['bq'].astype(q.dtype)
        k = k + layer['bk'].astype(k.dtype)
        v = v + layer['bv'].astype(v.dtype)
    q = _shard(q, 'batch', 'seq', 'heads', 'head_dim')
    q = checkpoint_name(rope(q, positions, cfg.rope_theta), 'q_rope')
    k = checkpoint_name(rope(k, positions, cfg.rope_theta), 'k_rope')
    v = checkpoint_name(v, 'v_proj')
    out = attn_fn(q, k, v)
    # Named for selective remat (cfg.remat='attn'): saving the attention
    # output keeps the backward pass from re-running the whole attention
    # forward, at [b,s,h,d] bytes per layer.
    out = checkpoint_name(out, 'attn_out')
    out = _shard(out, 'batch', 'seq', 'heads', 'head_dim')
    proj = qeinsum('bshk,hkd->bsd', out, layer['wo'])
    if lo is not None:
        proj = proj + lora_lib.apply(lo, 'wo', out, cfg)
    if ml is not None:
        proj = multilora.adjusted(ml, 'wo', out, proj, mlora_idx)
    x = x + proj
    h = rms_norm(x, layer['ffn_norm'], cfg.norm_eps,
                 cfg.norm_plus_one)
    if cfg.is_moe:
        from skypilot_tpu.models import moe
        ffn_out, aux = moe.moe_ffn(layer, h, cfg)
    else:
        ffn_out = _ffn(layer, h, cfg, mlora_idx=mlora_idx)
        aux = jnp.zeros((), jnp.float32)
    x = x + ffn_out
    x = _shard(x, 'batch', 'seq', 'embed')
    return x, (k, v), aux


def _layer_fn(layer: Params, x: jax.Array, cfg: ModelConfig,
              positions: jax.Array,
              cache_kv, cache_len, attn_impl: str):
    if cache_kv is None:
        def attn_fn(q, k, v):
            return attention(q, k, v, causal=True, impl=attn_impl)
    else:
        # Two-block attention: the cache is read-only here (forward
        # scatters the new rows once, after the layer scan) — a decode
        # step's cache traffic is one streaming read + an s-token write,
        # not a full rewrite through scan carries. int8 caches arrive as
        # a 4-tuple of (codes, codes, k_scale, v_scale) and are
        # contracted in int8 (see cached_attention).
        if len(cache_kv) == 4:
            ck, cv, sk, sv = cache_kv
        else:
            (ck, cv), sk, sv = cache_kv, None, None

        def attn_fn(q, k, v):
            return cached_attention(q, k, v, ck, cv, cache_len,
                                    k_scale=sk, v_scale=sv)

    x, new_kv, aux = _layer_core(layer, x, cfg, positions, attn_fn)
    return x, (None if cache_kv is None else new_kv), aux


# --------------------------------------------------------------------------
# Forward
# --------------------------------------------------------------------------
def forward(
    params: Params,
    tokens: jax.Array,                 # [b, s] int32
    cfg: ModelConfig,
    *,
    cache: Optional[KVCache] = None,
    attn_impl: str = 'auto',
    return_aux: bool = False,
):
    """Run the model. Without a cache: training/eval full-sequence causal
    attention; positions are [0..s). With a cache: prefill/decode — tokens
    are appended at each sequence's current length and the cache is updated.

    Cache-capacity contract: callers must never append past ``max_seq`` —
    ``lax.dynamic_update_slice`` clamps rather than errors inside jit, so an
    overflow silently corrupts the last cache slot. The inference engine
    enforces this by construction (it evicts/rejects before overflow).

    Returns (logits [b, s, vocab], new_cache or None), plus the mean MoE
    load-balancing aux loss when ``return_aux`` (0 for dense models).
    """
    x = _embed_tokens(params, tokens, cfg)
    x = _shard(x, 'batch', 'seq', 'embed')
    b, s = tokens.shape

    if cache is None:
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        cache_len = None
    else:
        positions = cache.length[:, None] + jnp.arange(s)[None, :]
        cache_len = cache.length

    layer_params = params['layers']

    def make_body(positions, cache_len):
        """Per-layer body closing over a SPECIFIC positions/cache_len —
        a factory so the pp-decode path can rebuild it inside the
        shard_map region (closed-over tracers don't cross that
        boundary)."""

        def body(carry, layer_and_cache):
            x = carry
            layer, layer_cache = layer_and_cache
            return _layer_fn(layer, x, cfg, positions, layer_cache,
                             cache_len, attn_impl)

        if cfg.remat == 'block':
            body = jax.checkpoint(
                body, policy=jax.checkpoint_policies.nothing_saveable)
        elif cfg.remat == 'attn':
            # Selective remat: save roped q/k/v and the attention output
            # ([b,s,h,d] each — small next to the ffn intermediates), so
            # the backward pass never re-runs the attention forward;
            # everything else (norms, ffn) is recomputed. The MFU middle
            # ground between 'none' (OOM at ≥1B on one chip) and 'block'
            # (full re-forward).
            body = jax.checkpoint(
                body,
                policy=jax.checkpoint_policies.save_only_these_names(
                    'q_rope', 'k_rope', 'v_proj', 'attn_out'))
        elif cfg.remat == 'dots':
            # Keep all matmul outputs, recompute elementwise only.
            # Highest memory — viable for small models / many-chip FSDP
            # shards.
            body = jax.checkpoint(
                body,
                policy=jax.checkpoint_policies
                .dots_with_no_batch_dims_saveable)
        return body

    body = make_body(positions, cache_len)

    if cache is None:
        pp_mesh = _pp_mesh()
        if pp_mesh is not None:
            # Pipeline-parallel layer stack (bubble-skipping GPipe over
            # the pp axis); each stage scans its local layers. MoE aux
            # flows through the schedule (``with_aux``).
            from skypilot_tpu.parallel.pipeline import pipeline_layers

            def stage_fn(stage_params, x_mb):
                # Positions rebuilt at microbatch shape (the closed-over
                # `positions` is full-batch; rows are identical without a
                # cache).
                mb_pos = jnp.broadcast_to(jnp.arange(s)[None, :],
                                          (x_mb.shape[0], s))

                def layer_body(carry, layer):
                    _manual_region.active = True
                    try:
                        out, _, aux = _layer_fn(layer, carry, cfg, mb_pos,
                                                None, None, attn_impl)
                    finally:
                        _manual_region.active = False
                    return out, aux
                if cfg.remat == 'block':
                    layer_body = jax.checkpoint(
                        layer_body,
                        policy=jax.checkpoint_policies.nothing_saveable)
                out, auxs = lax.scan(layer_body, x_mb, stage_params)
                return out, jnp.mean(auxs)

            x, aux_mean = pipeline_layers(layer_params, x, stage_fn,
                                          pp_mesh, with_aux=True)
            aux_layers = aux_mean[None]
        else:
            def scan_body(carry, layer):
                out, _, aux = body(carry, (layer, None))
                return out, aux

            x, aux_layers = lax.scan(scan_body, x, layer_params)
        new_cache = None
    else:
        # The cache is a loop INVARIANT (closed over, indexed per layer),
        # not a scan input/output: routing it through xs/ys makes XLA
        # restack the entire [L, b, S, h, d] cache every call — for
        # decode that turns a ~MB token write into a ~GB cache rewrite.
        cache_k, cache_v = cache.k, cache.v
        k_scale, v_scale = cache.k_scale, cache.v_scale

        def local_scan(stack_params, ck_stack, cv_stack, ks_stack,
                       vs_stack, x0, scan_body_fn):
            """Scan a (possibly stage-local) layer stack against its
            cache stack; returns (x, (k_rows, v_rows), aux)."""
            n_local = jax.tree.leaves(stack_params)[0].shape[0]

            def scan_body(carry, layer_and_idx):
                layer, li = layer_and_idx
                ck = lax.dynamic_index_in_dim(ck_stack, li, axis=0,
                                              keepdims=False)
                cv = lax.dynamic_index_in_dim(cv_stack, li, axis=0,
                                              keepdims=False)
                if cache.quantized:
                    layer_cache = (
                        ck, cv,
                        lax.dynamic_index_in_dim(ks_stack, li, axis=0,
                                                 keepdims=False),
                        lax.dynamic_index_in_dim(vs_stack, li, axis=0,
                                                 keepdims=False))
                else:
                    layer_cache = (ck, cv)
                out, new_kv, aux = scan_body_fn(carry, (layer, layer_cache))
                return out, (new_kv, aux)

            x1, (kv_rows, auxs) = lax.scan(
                scan_body, x0, (stack_params, jnp.arange(n_local)))
            return x1, kv_rows, auxs

        pp_mesh = _pp_mesh()
        if pp_mesh is not None:
            # pp-sharded decode/prefill: each stage reads only its
            # local layer + cache shards; the token activation chains
            # through the stages (parallel/pipeline.py, round-3 gap
            # "decode ignores pp").
            from skypilot_tpu.parallel.pipeline import \
                pipeline_decode_layers
            caches = ((cache.k, cache.v, k_scale, v_scale)
                      if cache.quantized else (cache.k, cache.v))

            def stage_fn(stage_params, stage_caches, x_mb, extras):
                pos_x, clen_x = extras
                if cache.quantized:
                    ck_s, cv_s, ks_s, vs_s = stage_caches
                else:
                    (ck_s, cv_s), ks_s, vs_s = stage_caches, None, None
                _manual_region.active = True
                try:
                    x1, kv_rows, _ = local_scan(
                        stage_params, ck_s, cv_s, ks_s, vs_s, x_mb,
                        make_body(pos_x, clen_x))
                finally:
                    _manual_region.active = False
                return x1, kv_rows

            x, (k_rows, v_rows) = pipeline_decode_layers(
                layer_params, caches, x, stage_fn, pp_mesh,
                extras=(positions, cache_len))
            aux_layers = jnp.zeros((1,), jnp.float32)
        else:
            x, (k_rows, v_rows), aux_layers = local_scan(
                layer_params, cache_k, cache_v, k_scale, v_scale, x,
                body)
        # One scatter of the new token rows across all layers.
        # k_rows: [L, b, s, kv_heads, d]; per batch row, write the
        # [L, s, kv_heads, d] block at that sequence's offset.

        new_cache = merge_rows_into_cache(cache, k_rows, v_rows,
                                          cache.length, cache.length + s)

    x = rms_norm(x, params['final_norm'], cfg.norm_eps,
                 cfg.norm_plus_one)
    logits = _unembed_logits(params, x, cfg)
    logits = _shard(logits, 'batch', 'seq', 'vocab')
    if return_aux:
        return logits, new_cache, jnp.mean(aux_layers)
    return logits, new_cache


def prefill_rows(
    params: Params,
    tokens: jax.Array,                 # [n, bucket] padded prompts
    true_lens: jax.Array,              # [n] real prompt lengths
    cfg: ModelConfig,
    *,
    attn_impl: str = 'auto',
    quantize_rows=False,               # False | True (int8) | 'int4'
    w8a8: bool = False,
    cache_kv=None,                     # per-row cache stacks (chunked
                                       # prefill): ([L, n, S, hkv, d] k,
                                       # v) bf16 or (kq, vq, ks, vs)
                                       # int8 codes + scales
    cache_len: Optional[jax.Array] = None,   # [n] valid cache rows =
                                       # each row's chunk start offset
    all_logits: bool = False,          # return [n, bucket, vocab] logits
                                       # at EVERY position (speculative
                                       # verify; keep bucket ~k+1 tiny —
                                       # the full tensor is ~0.5 GB at
                                       # n=8 x bucket=512)
    mlora_idx: Optional[jax.Array] = None,  # [n] per-row adapter slot
                                       # (-1 = none): prefill rows gather
                                       # bank adapters exactly like
                                       # decode — chunked included
):
    """Prompt/chunk prefill for the slot engine. Without ``cache_kv``:
    plain causal attention over the padded bucket — flash-eligible on
    TPU (the forward-with-scratch-cache path it replaces ran
    ``cached_attention`` against a bucket of zero rows: an extra masked
    cache read per layer and no flash). With ``cache_kv``/``cache_len``
    the bucket is a prompt CHUNK attending over a NONZERO cache offset:
    positions start at ``cache_len`` per row, and each layer attends the
    gathered cache rows (masked to ``cache_len``) plus the causal chunk
    (``ops.chunk_attention`` — flash chunk kernel on TPU, two-block XLA
    softmax elsewhere). Returns only what admission needs:

    - ``last_logits`` [n, vocab] fp32 at each row's position
      ``true_lens - 1`` (the full [n, bucket, vocab] logits tensor is a
      ~0.5 GB transient at n=8 x bucket=512 — only one row is ever
      used; chunked callers pass the completing index + 1);
    - the per-layer KV rows, quantized INSIDE the layer scan when
      ``quantize_rows`` (the stacked bf16 [L, n, bucket] rows are the
      7B prefill's biggest transient — int8 halves it, doubling the
      admission wave the scratch budget admits):
      (k_rows, v_rows) bf16, or (kq, vq, ks, vs) int8 + scales.

    ``w8a8`` additionally quantizes activations per token inside the
    LAYER matmuls (prefill is compute-bound; the MXU's int8 path is 2x
    bf16 — see ``quantization.w8a8_region``). The unembed stays W8A16:
    logits feed sampling directly and are not worth the noise.
    """
    from skypilot_tpu.models import quantization
    from skypilot_tpu.ops.attention import chunk_attention
    x = _embed_tokens(params, tokens, cfg)
    x = _shard(x, 'batch', 'seq', 'embed')
    n, s = tokens.shape
    if cache_len is None:
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (n, s))
    else:
        positions = cache_len[:, None] + jnp.arange(s)[None, :]

    def emit_rows(k, v):
        if quantize_rows:
            quant = (quantize_kv_rows4 if quantize_rows == 'int4'
                     else quantize_kv_rows)
            kq, ks = quant(k)
            vq, vs = quant(v)
            return (kq, vq, ks, vs)
        return (k, v)

    if cache_kv is None:
        def body(carry, layer):
            def attn_fn(q, k, v):
                return attention(q, k, v, causal=True, impl=attn_impl)

            xc, (k, v), _ = _layer_core(layer, carry, cfg, positions,
                                        attn_fn, mlora_idx=mlora_idx)
            return xc, emit_rows(k, v)

        xs = params['layers']
    else:
        if len(cache_kv) == 4:
            ck_all, cv_all, ks_all, vs_all = cache_kv
        else:
            (ck_all, cv_all), ks_all, vs_all = cache_kv, None, None

        def body(carry, layer_and_idx):
            layer, li = layer_and_idx
            ck = lax.dynamic_index_in_dim(ck_all, li, 0, keepdims=False)
            cv = lax.dynamic_index_in_dim(cv_all, li, 0, keepdims=False)
            sk = (lax.dynamic_index_in_dim(ks_all, li, 0, keepdims=False)
                  if ks_all is not None else None)
            sv = (lax.dynamic_index_in_dim(vs_all, li, 0, keepdims=False)
                  if vs_all is not None else None)

            def attn_fn(q, k, v):
                return chunk_attention(q, k, v, ck, cv, cache_len,
                                       impl=attn_impl, k_scale=sk,
                                       v_scale=sv)

            xc, (k, v), _ = _layer_core(layer, carry, cfg, positions,
                                        attn_fn, mlora_idx=mlora_idx)
            return xc, emit_rows(k, v)

        xs = (params['layers'], jnp.arange(cfg.n_layers))

    import contextlib
    ctx = (quantization.w8a8_region() if w8a8
           else contextlib.nullcontext())
    with ctx:
        x, rows = lax.scan(body, x, xs)
    x = rms_norm(x, params['final_norm'], cfg.norm_eps,
                 cfg.norm_plus_one)
    if all_logits:
        # Multi-position logits for speculative verify: every position
        # of the (tiny) bucket is a next-token distribution the
        # acceptance test reads.
        return _unembed_logits(params, x, cfg), rows
    last_x = jnp.take_along_axis(x, (true_lens - 1)[:, None, None],
                                 axis=1)
    last_logits = _unembed_logits(params, last_x, cfg)[:, 0]
    return last_logits, rows


# Sentinel token emitted when a slot's logits row is non-finite
# (NaN/Inf — numerical blow-up, SDC, poisoned activations). Real token
# ids are >= 0, so the host readback can evict exactly the poisoned
# request while its co-batched neighbors continue untouched. The
# finiteness reduction runs ON DEVICE inside the already-compiled step
# and the sentinel rides the existing token readback: zero extra
# device->host transfers, zero new programs (jaxpr-audit-gated).
NONFINITE_TOKEN = -1


def mask_nonfinite_tokens(logits: jax.Array,
                          tokens: jax.Array) -> jax.Array:
    """Per-row finiteness guard at a sampling point: rows whose logits
    contain any NaN/Inf emit :data:`NONFINITE_TOKEN` instead of a
    sampled id (argmax over all-NaN logits returns 0 — a silently
    WRONG token that would stream to the client as real output)."""
    finite = jnp.all(jnp.isfinite(logits), axis=-1)
    return jnp.where(finite, tokens,
                     jnp.asarray(NONFINITE_TOKEN, tokens.dtype))


def decode_horizon(
    params: Params,
    cache: KVCache,
    tokens: jax.Array,                 # [b] current token per sequence
    cfg: ModelConfig,
    *,
    horizon: int,
    sample_fn=None,                    # (logits [b, vocab], rng) -> [b] int32
    rngs: Optional[jax.Array] = None,  # [horizon] keys when sample_fn set
    kv_bucket: Optional[int] = None,   # static: attention reads only the
                                       # first kv_bucket cache rows; caller
                                       # guarantees max(length)+horizon <=
                                       # kv_bucket (length-aware decode)
    mlora_idx: Optional[jax.Array] = None,  # [b] per-slot adapter slot
                                       # (-1 = none): multi-LoRA bank
                                       # gather inside the fused scan
    vocab_mask: Optional[jax.Array] = None,  # [b, vocab] bool, True =
                                       # allowed (constrained decoding);
                                       # applied at logits production so
                                       # greedy AND sampled rows obey it
):
    """``horizon`` fused autoregressive decode steps in one program.

    The perf-critical serving loop. The main cache is a loop INVARIANT:
    its attention mask depends only on the horizon-start lengths, so XLA
    streams it read-only each step instead of re-materializing it through
    the scan carry (which costs ~a full cache rewrite per step). Rows
    produced during the horizon live in a small [layers, b, horizon] ring
    written at a uniform offset (plain dynamic_update_slice, in-place);
    one scatter merges the ring into the cache at the end.

    Returns (tokens [b, horizon], new_cache with length = length+horizon);
    callers with inactive slots correct their lengths afterwards.
    """
    b = tokens.shape[0]
    n_layers, n_kv, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    len0 = cache.length
    full_k, full_v = cache.k, cache.v
    ks_full, vs_full = cache.k_scale, cache.v_scale
    if kv_bucket is not None and kv_bucket <= full_k.shape[2] // 2:
        # Decode is HBM-bound on the cache read; a static prefix slice
        # keeps per-step traffic proportional to the LIVE context, not
        # max_seq. (Rows >= kv_bucket are masked out anyway.) XLA
        # materializes the sliced prefix as a program temp (the scan
        # consumes it as a loop invariant), so slicing only pays when it
        # at least HALVES the read: a 512-of-576 slice allocated 4 GB of
        # temps to save 11% of traffic and OOM'd a 16 GB chip.
        cache_k = full_k[:, :, :kv_bucket]
        cache_v = full_v[:, :, :kv_bucket]
        k_scale = ks_full[:, :, :kv_bucket] if cache.quantized else None
        v_scale = vs_full[:, :, :kv_bucket] if cache.quantized else None
    else:
        cache_k, cache_v = full_k, full_v
        k_scale, v_scale = ks_full, vs_full
    layer_params = params['layers']
    # The ring (this horizon's rows) stays in model dtype — it is tiny
    # next to the main cache; only the main cache rides int8.
    ring_k = jnp.zeros((n_layers, b, horizon, n_kv, hd), cfg.dtype)
    ring_v = jnp.zeros_like(ring_k)
    if rngs is None:
        rngs = jnp.zeros((horizon, 2), jnp.uint32)      # unused filler

    def one_step(carry, step_in):
        ring_k, ring_v, tok = carry
        i, rng = step_in
        x = _embed_tokens(params, tok[:, None], cfg)    # [b, 1, d]
        positions = (len0 + i)[:, None]

        def layer_body(xc, layer_and_idx):
            layer, li = layer_and_idx
            ck = lax.dynamic_index_in_dim(cache_k, li, 0, keepdims=False)
            cv = lax.dynamic_index_in_dim(cache_v, li, 0, keepdims=False)
            if cache.quantized:
                # int8 codes stay int8 across HBM; the per-row scales
                # fold into logits/probs inside the attention op.
                sk = lax.dynamic_index_in_dim(k_scale, li, 0,
                                              keepdims=False)
                sv = lax.dynamic_index_in_dim(v_scale, li, 0,
                                              keepdims=False)
            else:
                sk = sv = None
            rk = lax.dynamic_index_in_dim(ring_k, li, 0, keepdims=False)
            rv = lax.dynamic_index_in_dim(ring_v, li, 0, keepdims=False)

            def attn_fn(q, k, v):
                return ring_decode_attention(q, k, v, ck, cv, len0,
                                             rk, rv, i, k_scale=sk,
                                             v_scale=sv)

            xc, new_kv, _ = _layer_core(layer, xc, cfg, positions,
                                        attn_fn, mlora_idx=mlora_idx)
            return xc, new_kv

        x, (k_rows, v_rows) = lax.scan(
            layer_body, x, (layer_params, jnp.arange(n_layers)))
        ring_k = lax.dynamic_update_slice(
            ring_k, k_rows.astype(ring_k.dtype), (0, 0, i, 0, 0))
        ring_v = lax.dynamic_update_slice(
            ring_v, v_rows.astype(ring_v.dtype), (0, 0, i, 0, 0))

        x = rms_norm(x, params['final_norm'], cfg.norm_eps,
                 cfg.norm_plus_one)
        logits = _unembed_logits(params, x, cfg)[:, 0]
        # Constrained decoding composes at logits PRODUCTION, not just
        # inside filtered_logits: the greedy branch takes a raw argmax.
        logits = apply_vocab_mask(logits, vocab_mask)
        if sample_fn is None:
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
        else:
            nxt = sample_fn(logits, rng)
        # NaN blast-radius isolation: a poisoned row emits the
        # sentinel; the host evicts that request at readback while the
        # other slots' tokens land normally. The sentinel also carries
        # into the next step's token (a wrapped embedding lookup —
        # deterministic garbage on an already-condemned slot).
        nxt = mask_nonfinite_tokens(logits, nxt)
        return (ring_k, ring_v, nxt), nxt

    (ring_k, ring_v, _), toks = lax.scan(
        one_step, (ring_k, ring_v, tokens),
        (jnp.arange(horizon), rngs))

    new_cache = merge_rows_into_cache(cache, ring_k, ring_v, len0,
                                      len0 + horizon)
    return toks.T, new_cache


@functools.partial(jax.jit, static_argnames=('cfg',))
def greedy_logits(params: Params, tokens: jax.Array,
                  cfg: ModelConfig) -> jax.Array:
    """Convenience: jitted logits-only forward (no cache)."""
    logits, _ = forward(params, tokens, cfg)
    return logits
