"""Llama-family decoder-only transformer, TPU-first.

Replaces the reference's *recipe* approach (``llm/llama-3/llama3.yaml``
launches vLLM; ``examples/tpu/v6e/`` launches HF+PyTorch/XLA) with an in-tree
engine designed for XLA:

- Pure-functional: params are a pytree; every entry has a parallel tuple of
  logical axis names (``param_logical_axes``) mapped to mesh axes by
  ``skypilot_tpu.parallel.mesh`` rules — FSDP/TP/SP/EP are sharding rules,
  not code paths.
- ``lax.scan`` over stacked layer params: one compiled block regardless of
  depth (fast compiles, constant-size HLO), with optional per-layer
  rematerialization (``jax.checkpoint``) for training.
- bf16 activations/params, fp32 attention logits + softmax, fp32 norms —
  the standard TPU numerics recipe.
- GQA + RoPE + SwiGLU; MoE FFN is delegated to ``models.moe`` when
  ``cfg.is_moe`` (Mixtral-class, expert-parallel over the mesh).
"""
from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from skypilot_tpu.models.configs import ModelConfig
from skypilot_tpu.ops.attention import attention

Params = Dict[str, Any]


# --------------------------------------------------------------------------
# Initialization
# --------------------------------------------------------------------------
def _dense_init(key, shape, dtype, fan_in):
    scale = fan_in ** -0.5
    return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)


def init_params(rng: jax.Array, cfg: ModelConfig) -> Params:
    """Initialize parameters. Layer params are stacked on a leading
    ``layers`` axis for lax.scan."""
    d, hd = cfg.dim, cfg.head_dim
    n_h, n_kv, f, L = cfg.n_heads, cfg.n_kv_heads, cfg.ffn_dim, cfg.n_layers
    keys = jax.random.split(rng, 8)

    def stack_init(key, shape, fan_in):
        ks = jax.random.split(key, L)
        return jnp.stack([_dense_init(k, shape, cfg.dtype, fan_in)
                          for k in ks])

    params: Params = {
        'embed': _dense_init(keys[0], (cfg.vocab_size, d), cfg.dtype, d),
        'unembed': _dense_init(keys[1], (d, cfg.vocab_size), cfg.dtype, d),
        'final_norm': jnp.ones((d,), jnp.float32),
        'layers': {
            'attn_norm': jnp.ones((L, d), jnp.float32),
            'ffn_norm': jnp.ones((L, d), jnp.float32),
            'wq': stack_init(keys[2], (d, n_h, hd), d),
            'wk': stack_init(keys[3], (d, n_kv, hd), d),
            'wv': stack_init(keys[4], (d, n_kv, hd), d),
            'wo': stack_init(keys[5], (n_h, hd, d), n_h * hd),
        },
    }
    if cfg.is_moe:
        from skypilot_tpu.models import moe
        params['layers'].update(moe.init_moe_params(keys[6], cfg))
    else:
        k1, k2, k3 = jax.random.split(keys[6], 3)
        params['layers'].update({
            'w_gate': stack_init(k1, (d, f), d),
            'w_up': stack_init(k2, (d, f), d),
            'w_down': stack_init(k3, (f, d), f),
        })
    return params


def param_logical_axes(cfg: ModelConfig) -> Params:
    """Same structure as ``init_params``, with logical-axis tuples as leaves.

    The leading scan axis is 'layers' (never sharded)."""
    axes: Params = {
        'embed': ('vocab', 'embed'),
        'unembed': ('embed', 'vocab'),
        'final_norm': ('norm',),
        'layers': {
            'attn_norm': ('layers', 'norm'),
            'ffn_norm': ('layers', 'norm'),
            'wq': ('layers', 'embed', 'heads', 'head_dim'),
            'wk': ('layers', 'embed', 'kv_heads', 'head_dim'),
            'wv': ('layers', 'embed', 'kv_heads', 'head_dim'),
            'wo': ('layers', 'heads', 'head_dim', 'embed'),
        },
    }
    if cfg.is_moe:
        from skypilot_tpu.models import moe
        axes['layers'].update(moe.moe_logical_axes(cfg))
    else:
        axes['layers'].update({
            'w_gate': ('layers', 'embed', 'mlp'),
            'w_up': ('layers', 'embed', 'mlp'),
            'w_down': ('layers', 'mlp', 'embed'),
        })
    return axes


# --------------------------------------------------------------------------
# KV cache
# --------------------------------------------------------------------------
class KVCache(NamedTuple):
    """Decode cache. k/v: [layers, batch, max_seq, kv_heads, head_dim];
    length: [batch] valid entries per sequence (supports continuous
    batching where sequences are at different positions)."""
    k: jax.Array
    v: jax.Array
    length: jax.Array

    @classmethod
    def create(cls, cfg: ModelConfig, batch: int, max_seq: int) -> 'KVCache':
        shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
        return cls(k=jnp.zeros(shape, cfg.dtype),
                   v=jnp.zeros(shape, cfg.dtype),
                   length=jnp.zeros((batch,), jnp.int32))


def cache_logical_axes() -> KVCache:
    return KVCache(k=('layers', 'batch', None, 'kv_heads', 'head_dim'),
                   v=('layers', 'batch', None, 'kv_heads', 'head_dim'),
                   length=('batch',))


def _write_kv(cache_k: jax.Array, new_k: jax.Array,
              start: jax.Array) -> jax.Array:
    """Insert new_k [b, s, h, d] into cache_k [b, S, h, d] at per-sequence
    offsets start [b]."""

    def one(c, n, s):
        return lax.dynamic_update_slice(c, n, (s, 0, 0))

    return jax.vmap(one)(cache_k, new_k, start)


# --------------------------------------------------------------------------
# Building blocks
# --------------------------------------------------------------------------
def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    return (xf * lax.rsqrt(var + eps) * w).astype(x.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x: [b, s, h, d], positions: [b, s]."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [b, s, d/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


def _in_mesh_context() -> bool:
    """True when a `with mesh:` context is active. jax has no public
    predicate for this; probe the known private locations and fail open
    (no constraint) so a jax upgrade degrades perf, not correctness."""
    try:
        from jax._src import mesh as mesh_src
        return not mesh_src.thread_resources.env.physical_mesh.empty
    except Exception:
        try:
            from jax.interpreters import pxla
            return not pxla.thread_resources.env.physical_mesh.empty
        except Exception:
            return False


def _shard(x: jax.Array, *logical_axes: Optional[str]) -> jax.Array:
    """Activation sharding constraint via logical axes; no-op outside a mesh
    context (pure single-device runs, CPU unit tests)."""
    if not _in_mesh_context():
        return x
    from skypilot_tpu.parallel.mesh import spec_for
    return lax.with_sharding_constraint(x, spec_for(logical_axes))


def _ffn(layer: Params, x: jax.Array, cfg: ModelConfig) -> jax.Array:
    gate = jnp.einsum('bsd,df->bsf', x, layer['w_gate'])
    up = jnp.einsum('bsd,df->bsf', x, layer['w_up'])
    h = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
    h = _shard(h, 'batch', 'seq', 'mlp')
    return jnp.einsum('bsf,fd->bsd', h, layer['w_down'])


def _attn_block(layer: Params, x: jax.Array, cfg: ModelConfig,
                positions: jax.Array,
                cache_kv: Optional[Tuple[jax.Array, jax.Array]],
                cache_len: Optional[jax.Array],
                attn_impl: str):
    """Returns (out, new_cache_kv). Cache arrays are per-layer [b,S,h,d]."""
    q = jnp.einsum('bsd,dhk->bshk', x, layer['wq'])
    k = jnp.einsum('bsd,dhk->bshk', x, layer['wk'])
    v = jnp.einsum('bsd,dhk->bshk', x, layer['wv'])
    q = _shard(q, 'batch', 'seq', 'heads', 'head_dim')
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)

    if cache_kv is None:
        out = attention(q, k, v, causal=True, impl=attn_impl)
        new_cache = None
    else:
        ck, cv = cache_kv
        ck = _write_kv(ck, k, cache_len)
        cv = _write_kv(cv, v, cache_len)
        new_len = cache_len + x.shape[1]
        out = attention(q, ck, cv, causal=True, q_offset=cache_len,
                        kv_len=new_len, impl=attn_impl)
        new_cache = (ck, cv)
    out = _shard(out, 'batch', 'seq', 'heads', 'head_dim')
    out = jnp.einsum('bshk,hkd->bsd', out, layer['wo'])
    return out, new_cache


def _layer_fn(layer: Params, x: jax.Array, cfg: ModelConfig,
              positions: jax.Array,
              cache_kv, cache_len, attn_impl: str):
    h = rms_norm(x, layer['attn_norm'], cfg.norm_eps)
    attn_out, new_cache = _attn_block(layer, h, cfg, positions, cache_kv,
                                      cache_len, attn_impl)
    x = x + attn_out
    h = rms_norm(x, layer['ffn_norm'], cfg.norm_eps)
    if cfg.is_moe:
        from skypilot_tpu.models import moe
        ffn_out, aux = moe.moe_ffn(layer, h, cfg)
    else:
        ffn_out = _ffn(layer, h, cfg)
        aux = jnp.zeros((), jnp.float32)
    x = x + ffn_out
    x = _shard(x, 'batch', 'seq', 'embed')
    return x, new_cache, aux


# --------------------------------------------------------------------------
# Forward
# --------------------------------------------------------------------------
def forward(
    params: Params,
    tokens: jax.Array,                 # [b, s] int32
    cfg: ModelConfig,
    *,
    cache: Optional[KVCache] = None,
    attn_impl: str = 'auto',
    return_aux: bool = False,
):
    """Run the model. Without a cache: training/eval full-sequence causal
    attention; positions are [0..s). With a cache: prefill/decode — tokens
    are appended at each sequence's current length and the cache is updated.

    Cache-capacity contract: callers must never append past ``max_seq`` —
    ``lax.dynamic_update_slice`` clamps rather than errors inside jit, so an
    overflow silently corrupts the last cache slot. The inference engine
    enforces this by construction (it evicts/rejects before overflow).

    Returns (logits [b, s, vocab], new_cache or None), plus the mean MoE
    load-balancing aux loss when ``return_aux`` (0 for dense models).
    """
    x = params['embed'][tokens]  # [b, s, d] - gather
    x = _shard(x, 'batch', 'seq', 'embed')
    b, s = tokens.shape

    if cache is None:
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        cache_len = None
    else:
        positions = cache.length[:, None] + jnp.arange(s)[None, :]
        cache_len = cache.length

    layer_params = params['layers']

    def body(carry, layer_and_cache):
        x = carry
        layer, layer_cache = layer_and_cache
        return _layer_fn(layer, x, cfg, positions, layer_cache, cache_len,
                         attn_impl)

    if cfg.remat == 'block':
        body = jax.checkpoint(body,
                              policy=jax.checkpoint_policies.nothing_saveable)

    if cache is None:
        def scan_body(carry, layer):
            out, _, aux = body(carry, (layer, None))
            return out, aux

        x, aux_layers = lax.scan(scan_body, x, layer_params)
        new_cache = None
    else:
        def scan_body(carry, layer_and_kv):
            layer, ck, cv = layer_and_kv
            out, new_kv, aux = body(carry, (layer, (ck, cv)))
            return out, (new_kv, aux)

        x, ((new_k, new_v), aux_layers) = lax.scan(
            scan_body, x, (layer_params, cache.k, cache.v))
        new_cache = KVCache(k=new_k, v=new_v, length=cache.length + s)

    x = rms_norm(x, params['final_norm'], cfg.norm_eps)
    logits = jnp.einsum('bsd,dv->bsv', x, params['unembed'],
                        preferred_element_type=jnp.float32)
    logits = _shard(logits, 'batch', 'seq', 'vocab')
    if return_aux:
        return logits, new_cache, jnp.mean(aux_layers)
    return logits, new_cache


@functools.partial(jax.jit, static_argnames=('cfg',))
def greedy_logits(params: Params, tokens: jax.Array,
                  cfg: ModelConfig) -> jax.Array:
    """Convenience: jitted logits-only forward (no cache)."""
    logits, _ = forward(params, tokens, cfg)
    return logits
