"""skypilot_tpu: a TPU-native orchestration + training/serving framework.

Capability parity with SkyPilot (see SURVEY.md), built TPU-first:
- Task/Resources/Dag spec with first-class TPU slice topology.
- Cost/availability optimizer ranking TPU slices against GPU VMs.
- GCP provisioner gang-launching slices (TPU + queued-resources APIs) with
  cross-zone/region failover.
- Host-side agent runtime (job queue, logs, autostop) launching the same
  program on every slice host with a jax.distributed bootstrap.
- Managed jobs with preemption recovery; autoscaled serving.
- In-tree JAX/pjit/Pallas model layer (train + inference engines).

Public SDK mirrors ``sky.*`` (reference ``sky/__init__.py``): imports are
lazy so `import skypilot_tpu` stays fast and never pulls jax.
"""
from typing import Any

__version__ = '0.1.0'

from skypilot_tpu.dag import Dag
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task

_LAZY_SDK = {
    # name -> (module, attr)
    'launch': ('skypilot_tpu.execution', 'launch'),
    'exec': ('skypilot_tpu.execution', 'exec_cmd'),
    'optimize': ('skypilot_tpu.optimizer', 'optimize'),
    'status': ('skypilot_tpu.core', 'status'),
    'start': ('skypilot_tpu.core', 'start'),
    'stop': ('skypilot_tpu.core', 'stop'),
    'down': ('skypilot_tpu.core', 'down'),
    'autostop': ('skypilot_tpu.core', 'autostop'),
    'queue': ('skypilot_tpu.core', 'queue'),
    'cancel': ('skypilot_tpu.core', 'cancel'),
    'tail_logs': ('skypilot_tpu.core', 'tail_logs'),
    'cost_report': ('skypilot_tpu.core', 'cost_report'),
    'jobs': ('skypilot_tpu.jobs', None),
    'serve': ('skypilot_tpu.serve', None),
}


def __getattr__(name: str) -> Any:
    if name in _LAZY_SDK:
        import importlib
        module_name, attr = _LAZY_SDK[name]
        module = importlib.import_module(module_name)
        value = module if attr is None else getattr(module, attr)
        globals()[name] = value
        return value
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')


__all__ = ['Dag', 'Resources', 'Task', '__version__'] + list(_LAZY_SDK)
