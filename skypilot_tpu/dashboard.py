"""Live dashboard: one HTML page over the state DBs.

Role of reference ``sky/jobs/dashboard/`` (a Flask app rendering the
managed-jobs table). Here one stdlib HTTP server renders clusters,
managed jobs, and services — everything the CLI tables show, auto-
refreshing, no extra dependencies.
"""
from __future__ import annotations

import html
import http.server
import json
import time
import urllib.parse
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import global_state
from skypilot_tpu import telemetry
from skypilot_tpu import tpu_logging

logger = tpu_logging.init_logger(__name__)

_PAGE = """<!doctype html>
<html><head><title>skytpu dashboard</title>
<meta http-equiv="refresh" content="5">
<style>
body {{ font-family: system-ui, sans-serif; margin: 2rem; }}
h1 {{ font-size: 1.3rem; }} h2 {{ font-size: 1.05rem; margin-top: 2rem; }}
table {{ border-collapse: collapse; min-width: 40rem; }}
th, td {{ text-align: left; padding: .35rem .8rem;
         border-bottom: 1px solid #ddd; font-size: .9rem; }}
th {{ background: #f5f5f5; }}
.ok {{ color: #0a7d36; }} .bad {{ color: #b00020; }}
.muted {{ color: #777; }}
</style></head><body>
<h1>skytpu dashboard</h1>
<div class="muted">refreshed {now}</div>
{sections}
</body></html>
"""

_GOOD = {'UP', 'RUNNING', 'SUCCEEDED', 'READY'}
_BAD = {'FAILED', 'FAILED_SETUP', 'FAILED_CONTROLLER', 'FAILED_NO_RESOURCE',
        'NOT_READY', 'INIT'}


def _table(headers: List[str], rows: List[List[str]]) -> str:
    if not rows:
        return '<div class="muted">none</div>'
    head = ''.join(f'<th>{html.escape(h)}</th>' for h in headers)
    body = []
    for row in rows:
        tds = []
        for cell in row:
            cls = ''
            if cell in _GOOD:
                cls = ' class="ok"'
            elif cell in _BAD:
                cls = ' class="bad"'
            tds.append(f'<td{cls}>{html.escape(str(cell))}</td>')
        body.append('<tr>' + ''.join(tds) + '</tr>')
    return (f'<table><tr>{head}</tr>' + ''.join(body) + '</table>')


def _age(ts: Optional[float]) -> str:
    if not ts:
        return '-'
    sec = max(0, int(time.time() - ts))
    if sec < 60:
        return f'{sec}s ago'
    if sec < 3600:
        return f'{sec // 60}m ago'
    return f'{sec // 3600}h {sec % 3600 // 60}m ago'


def _clusters() -> Tuple[str, List[Dict[str, Any]]]:
    records = global_state.get_clusters()
    rows = []
    for r in records:
        handle = r.get('handle')
        res = (str(handle.launched_resources) if handle is not None and
               getattr(handle, 'launched_resources', None) is not None
               else '-')
        rows.append([r['name'], res, r['status'].value,
                     _age(r.get('launched_at'))])
    return _table(['CLUSTER', 'RESOURCES', 'STATUS', 'LAUNCHED'],
                  rows), records


def _managed_jobs() -> str:
    try:
        from skypilot_tpu import jobs
        table = jobs.queue()
    except Exception as e:  # pylint: disable=broad-except
        logger.debug(f'jobs queue unavailable: {type(e).__name__}: {e}')
        return '<div class="muted">no jobs controller running</div>'
    rows = [[str(j['job_id']), j.get('name', '-'), j.get('status', '-'),
             str(j.get('recovery_count', 0)),
             _age(j.get('submitted_at'))] for j in table]
    return _table(['ID', 'NAME', 'STATUS', 'RECOVERIES', 'SUBMITTED'], rows)


def _services() -> str:
    try:
        from skypilot_tpu import serve
        svcs = serve.status()
    except Exception as e:  # pylint: disable=broad-except
        logger.debug(f'serve status unavailable: '
                     f'{type(e).__name__}: {e}')
        return '<div class="muted">no serve controller running</div>'
    rows = []
    for s in svcs:
        replicas = s.get('replicas') or []
        ready = sum(1 for r in replicas if r.get('status') == 'READY')
        rows.append([s['name'], s.get('status', '-'),
                     f'{ready}/{len(replicas)}',
                     str(s.get('version', '-'))])
    return _table(['SERVICE', 'STATUS', 'READY', 'VERSION'], rows)


def render_page() -> str:
    cluster_html, _ = _clusters()
    sections = (
        f'<h2>Clusters</h2>{cluster_html}'
        f'<h2>Managed jobs</h2>{_managed_jobs()}'
        f'<h2>Services</h2>{_services()}'
    )
    return _PAGE.format(now=time.strftime('%Y-%m-%d %H:%M:%S'),
                        sections=sections)


def _update_cluster_gauges() -> None:
    """Fold control-plane state into the process telemetry registry —
    the dashboard no longer keeps a private metrics dict; it renders
    the same registry the serve layer writes to."""
    _, clusters = _clusters()
    reg = telemetry.get_registry()
    reg.gauge('skytpu_clusters', 'Known clusters').set(len(clusters))
    reg.gauge('skytpu_clusters_up', 'Clusters in UP status').set(
        sum(1 for c in clusters if c['status'].value == 'UP'))


def _metrics_json() -> str:
    """Stable legacy keys (clusters / clusters_up / time) plus the full
    registry dump under ``telemetry`` — one source of truth."""
    _update_cluster_gauges()
    reg = telemetry.get_registry()
    return json.dumps({
        'clusters': int(reg.get('skytpu_clusters').value),
        'clusters_up': int(reg.get('skytpu_clusters_up').value),
        'time': time.time(),
        'telemetry': reg.render_json(),
    })


def _metrics_prometheus() -> str:
    _update_cluster_gauges()
    return telemetry.get_registry().render_prometheus()


class _Handler(http.server.BaseHTTPRequestHandler):
    # Socket-op timeout (graftcheck GC107): a stalled client must not
    # pin a dashboard thread forever.
    timeout = 60

    def log_message(self, *args):
        del args

    def do_GET(self):  # noqa: N802
        parsed = urllib.parse.urlparse(self.path)
        if parsed.path == '/metrics':
            query = urllib.parse.parse_qs(parsed.query)
            if query.get('format', [''])[0] == 'json':
                body = _metrics_json().encode()
                ctype = 'application/json'
            else:
                body = _metrics_prometheus().encode()
                ctype = 'text/plain; version=0.0.4; charset=utf-8'
        else:
            body = render_page().encode()
            ctype = 'text/html; charset=utf-8'
        self.send_response(200)
        self.send_header('Content-Type', ctype)
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def make_server(port: int) -> http.server.ThreadingHTTPServer:
    return http.server.ThreadingHTTPServer(('127.0.0.1', port), _Handler)


def serve_forever(port: int) -> None:
    make_server(port).serve_forever()
