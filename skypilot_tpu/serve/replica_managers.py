"""Replica manager: launches/terminates/probes replica clusters.

Role of reference ``SkyPilotReplicaManager``
(``sky/serve/replica_managers.py:608``): every replica is an ordinary
cluster launched through the full stack (``sky/serve/replica_managers.py:
58-170`` does ``sky.launch`` in a subprocess; here a thread —
``execution.launch`` is already process-safe via per-cluster locks).
Readiness probing (``:1026``) is an HTTP GET/POST against
``http://<head_ip>:<replica_port><readiness_path>``; preemption handling
(``:782``) maps cluster-gone to PREEMPTED so the autoscaler replaces it.

TPU-first: a replica is a whole slice; its head IP is the slice's worker-0
and the in-tree model server (multi-controller JAX) listens there. On the
local provider each replica gets its own port (many replicas share one
host) — injected as ``SKYTPU_REPLICA_PORT`` either way.

Environment seam (``serve/control_env.py``): every outside-world touch
— wall clock, sleeps, background tasks, replica HTTP, cluster
launch/teardown/status, row persistence, fault-injector resolution —
routes through the injected :class:`ControlPlaneEnv`. The default
:class:`LiveControlPlaneEnv` reproduces the pre-refactor behavior
verbatim; ``serve/sim/`` swaps in a virtual-clock environment so the
SAME launch/probe/drain/checkpoint/warmup/backfill state machines run
against 1000 simulated replicas at millions of requests per wall-second
(ROADMAP item 5's fleet-scale simulator).
"""
from __future__ import annotations

import hashlib
import json
import threading
import time
import typing
from typing import Any, Dict, List, Optional, Sequence

from skypilot_tpu import exceptions
from skypilot_tpu import telemetry
from skypilot_tpu import tpu_logging
from skypilot_tpu.serve import control_env
from skypilot_tpu.serve import faults as faults_lib
from skypilot_tpu.serve import serve_state
from skypilot_tpu.task import Task
from skypilot_tpu.utils import common_utils

if typing.TYPE_CHECKING:
    from skypilot_tpu.serve.service_spec import SkyServiceSpec

logger = tpu_logging.init_logger(__name__)

# Stable outcome label set of skytpu_replicas_adopted_total{outcome}:
# what restart reconciliation did with each persisted replica row /
# pending journal op it found (docs/robustness.md, controller failure
# domain).
ADOPT_OUTCOMES = ('adopted', 'probe_pending', 'drain_resumed',
                  'teardown_replayed', 'zombie_killed', 'preempted')

_PROBE_FAILURE_GRACE = 3          # consecutive probe failures → NOT_READY
_PROBE_FAILURE_TERMINATE = 9      # consecutive failures → replace replica
_MAX_RETAINED_FAILED = 3          # FAILED rows kept for debugging
_LAUNCH_BACKOFF_CAP = 300.0
# Launch-backoff jitter band: the delay is drawn uniformly from
# [JITTER_FRAC, 1.0] x the exponential target, so replicas that failed
# together don't relaunch together (a synchronized retry storm against
# the same exhausted zone/quota).
_BACKOFF_JITTER_FRAC = 0.5


def _launch_backoff_base() -> float:
    import os
    return float(os.environ.get('SKYTPU_SERVE_LAUNCH_BACKOFF', '5'))


def _drain_deadline_default() -> float:
    """Graceful-drain deadline before a draining replica is torn down
    regardless (in-flight requests past it fail over via the LB)."""
    import os
    return float(os.environ.get('SKYTPU_SERVE_DRAIN_S', '30'))


def _warmup_timeout() -> float:
    """Bound on the prefix-cache warmup POST against a freshly READY
    replica (a wedged warmup must not keep capacity out of rotation —
    past it the replica enters rotation cold)."""
    import os
    return float(os.environ.get('SKYTPU_SERVE_WARMUP_TIMEOUT', '30'))


def _gang_join_timeout() -> float:
    """Barrier bound shipped to every gang rank: unless all ranks join
    rank 0 within this window, the gang fails and is replaced as one
    unit."""
    import os
    return float(os.environ.get('SKYTPU_GANG_JOIN_TIMEOUT', '120'))


def _ckpt_ttl() -> float:
    """Checkpoint staleness bound: prefix KV older than this is not
    worth shipping to a recovered replica (the traffic that made those
    prefixes hot has moved on)."""
    import os
    return float(os.environ.get('SKYTPU_SERVE_CKPT_TTL', '3600'))


def _canary_interval() -> float:
    """Byzantine-detection canary cadence per replica (seconds on the
    env clock); 0 (the default) disables canary probing."""
    import os
    return float(os.environ.get('SKYTPU_CANARY_INTERVAL_S', '0'))


def _canary_prompt() -> List[int]:
    """The canary's greedy prompt (comma-separated token ids). Fixed
    and known, so every healthy replica of one model version answers
    with the SAME token sequence — the digest the manager compares."""
    import os
    raw = os.environ.get('SKYTPU_CANARY_PROMPT', '11,13,17,19')
    return [int(t) for t in raw.split(',') if t.strip()]


def _canary_max_tokens() -> int:
    import os
    return int(os.environ.get('SKYTPU_CANARY_TOKENS', '8'))


def canary_digest(tokens: Sequence[int]) -> str:
    """The canonical digest of a canary response's token list — what
    the manager compares across replicas (and what tests and the
    simulator compute on the other side)."""
    return hashlib.sha256(
        json.dumps([int(t) for t in tokens]).encode()).hexdigest()[:16]


def _probe_counter(outcome: str) -> 'telemetry.Counter':
    """Probe-outcome counters in the shared process registry (the
    controller's /metrics surface via the dashboard)."""
    return telemetry.get_registry().counter(
        'skytpu_replica_probe_total',
        'Replica readiness-probe outcomes', outcome=outcome)


def _transition_counter(to_status: str) -> 'telemetry.Counter':
    return telemetry.get_registry().counter(
        'skytpu_replica_transitions_total',
        'Replica status transitions observed by the probe loop',
        to=to_status)


class ReplicaInfo:
    """In-memory mirror of one replica row + probe bookkeeping."""

    def __init__(self, replica_id: int, cluster_name: str, version: int,
                 is_spot: bool, port: int, role: str = 'colocated',
                 gang_id: Optional[str] = None, gang_rank: int = 0,
                 gang_world: int = 1,
                 created_time: Optional[float] = None):
        self.replica_id = replica_id
        self.cluster_name = cluster_name
        self.version = version
        self.is_spot = is_spot
        self.port = port
        # Disaggregation phase role (prefill/decode/colocated) — the
        # pool this replica was launched to fill; rides the launch env
        # as SKYTPU_ROLE.
        self.role = role
        # Multi-host gang membership (serve/gang.py): members share a
        # gang_id and come up / drain / checkpoint / die TOGETHER.
        # Rank 0 owns the replica's one routable endpoint (probed,
        # routed, drained over HTTP); followers are tracked for health
        # accounting and cluster lifecycle only — never probed, never
        # in ready_urls. ``coordinator`` is rank 0's URL, set before a
        # follower launches (its SKYTPU_COORDINATOR env).
        self.gang_id = gang_id
        self.gang_rank = gang_rank
        self.gang_world = gang_world
        self.coordinator: Optional[str] = None
        self.status = serve_state.ReplicaStatus.PENDING
        self.url: Optional[str] = None
        self.consecutive_failures = 0
        self.first_probe_time: Optional[float] = None
        # Spot resilience bookkeeping: when the scale-up was issued
        # (provision-latency observation — the forecast autoscaler's
        # pre-scaling lead time learns from these; the manager stamps
        # its env clock so simulated fleets observe virtual latencies),
        # whether this replica's prefix cache was already checkpointed
        # on a preemption warning (idempotence under a racing drain),
        # and whether its replacement warmup already ran (once per
        # replica, BEFORE it first enters ready_urls).
        self.created_time = (created_time if created_time is not None
                             else time.time())
        self.checkpointed = False
        self.warmed = False
        # Byzantine-detection canary bookkeeping: when this replica
        # was last canaried (env clock; 0 = never).
        self.last_canary_t = 0.0
        # Lifecycle-journal bookkeeping (round 15): the pending
        # journal op ids this replica's in-flight launch / drain carry
        # (finished when the op acks), and a teardown-started latch so
        # a replica's cluster is never torn down twice — not by racing
        # scale_down calls, and not by a restarted controller
        # replaying an op the dying one already ran.
        self.launch_op: Optional[int] = None
        self.drain_op: Optional[int] = None
        self.teardown_started = False


class ReplicaManager:

    def __init__(self, service_name: str, spec: 'SkyServiceSpec',
                 task_config: dict, version: int = 1,
                 reserved_ports: Optional[set] = None,
                 env: Optional[control_env.ControlPlaneEnv] = None):
        self.service_name = service_name
        self.spec = spec
        self.task_config = task_config
        self.version = version
        self._reserved_ports = set(reserved_ports or ())
        # The simulator-or-live effect seam: every clock read, sleep,
        # background task, replica HTTP round-trip, cluster op and row
        # write below goes through this (control_env.py).
        self._env = control_env.resolve(env)
        self._replicas: Dict[int, ReplicaInfo] = {}
        self._next_replica_id = 1
        # RLock: _persist checks membership under the lock and is called
        # both with and without it held.
        self._lock = threading.RLock()
        # DB-serialization lock (graftcheck GC102): sqlite row writes/
        # deletes happen under THIS lock only, so probe sweeps and
        # scale decisions contending on the hot ``_lock`` never stall
        # behind disk I/O. Ordering: ``_db_lock`` is taken FIRST, then
        # ``_lock`` briefly for the membership check — the row write
        # then runs with only ``_db_lock`` held. A racing removal needs
        # ``_db_lock`` too, so check+write stay atomic with respect to
        # pop+delete and no phantom row can survive a removal.
        self._db_lock = threading.Lock()
        self._shutdown = False
        self._launch_failures = 0
        self._backoff_until = 0.0
        # Backoff jitter source (tests seed it for determinism; the
        # sim env hands out a scenario-seeded RNG).
        self._rng = self._env.rng()
        # Deterministic fault injection (serve/faults.py): resolved
        # once from the env (SKYTPU_FAULT_SPEC live; the scenario's
        # injector in sim); None = hooks are one attribute check.
        # Sites here: 'probe' (probe_timeout), 'preempt'
        # (preempt_signal — hard kill), 'preempt_warning'
        # (preempt_signal with advance notice — routes through drain),
        # 'spot_preemption' (counted per swept SPOT replica only —
        # seeded spot-kill schedules for chaos tests and the bench).
        self._faults = self._env.fault_injector()
        # Spot resilience: the latest prefix-cache checkpoint exported
        # by a preemption-warned replica (bytes + export wall time;
        # latest wins, TTL-bounded), landed into replacement replicas
        # via /kv/warmup BEFORE they enter ready_urls. _ckpt_lock
        # serializes the store against concurrent warnings; the HTTP
        # fetch itself runs outside every lock.
        self._ckpt_lock = threading.Lock()
        self._ckpt_bytes: Optional[bytes] = None
        self._ckpt_time: float = 0.0
        # Checkpoint-once dedupe, keyed by GANG (falling back to the
        # replica id for singles): a preemption warning re-delivered
        # to a *different rank* of the same gang must still checkpoint
        # exactly once — the per-ReplicaInfo flag alone can't see that
        # the gang already checkpointed through another member. Guarded
        # by the manager lock like the per-replica flag it generalizes.
        # BOUNDED: entries are evicted in ``_untrack`` when the replica
        # (or the last member of the gang) is torn down, so a
        # long-lived manager churning thousands of spot replicas holds
        # only live keys.
        self._ckpt_done: Dict[str, bool] = {}
        # Provision-latency observations (scale-up issued -> READY)
        # not yet consumed by the controller; the forecast autoscaler
        # learns its pre-scaling lead time from them.
        self._provision_obs: List[float] = []
        # Fleet-telemetry scrape hook: the controller installs its
        # FleetAggregator's ``ingest`` here; after each successful
        # readiness probe the manager pulls the replica's
        # ``/telemetry/summary`` (resuming from a per-replica trace
        # cursor) and feeds it through. Best-effort — a scrape failure
        # never fails the probe.
        self._telemetry_sink: Optional[Any] = None
        self._telemetry_cursors: Dict[str, int] = {}
        reg = telemetry.get_registry()
        self._m_spot_preempt = reg.counter(
            'skytpu_spot_preemptions_total',
            'Spot replica preemptions observed (advance warnings and '
            'hard cluster losses)')
        self._h_warmup = reg.histogram(
            'skytpu_prefix_warmup_seconds',
            'Prefix-cache warmup of a recovered replica: checkpoint '
            'POST to landed (s)',
            buckets=telemetry.registry.DEFAULT_SECONDS_BUCKETS)
        self._h_provision = reg.histogram(
            'skytpu_replica_provision_seconds',
            'Replica provision latency: scale-up issued to first '
            'READY (s)',
            buckets=telemetry.registry.DEFAULT_SECONDS_BUCKETS)
        # Byzantine-replica quarantine (round 13): the manager
        # periodically (env-clock-driven) probes each READY replica
        # with a canary greedy prompt whose answer digest is known; a
        # mismatch — silently corrupted replica, the SDC failure mode
        # clean-failure machinery can't see — moves the replica to
        # QUARANTINED: out of ready_urls immediately, drained, torn
        # down, replaced. The reference digest is either configured
        # (``expected_digest``) or learned from the first healthy
        # answer per spec version (blue-green rollovers reset it — a
        # new model version legitimately answers differently).
        self._canary_interval = _canary_interval()
        self._canary_prompt = _canary_prompt()
        self._canary_max_new = _canary_max_tokens()
        self._canary_expected: Optional[str] = None
        self._canary_learned: Optional[str] = None
        self.quarantined_count = 0
        self._m_quarantined = reg.counter(
            'skytpu_replicas_quarantined_total',
            'Replicas quarantined after a byzantine (wrong-digest) '
            'canary response')
        # Restart reconciliation (round 15): what the journal replay
        # did with each persisted row — registered at construction so
        # the series render as zeros from the first scrape.
        self._m_adopted = {
            outcome: reg.counter(
                'skytpu_replicas_adopted_total',
                'Persisted replicas handled by restart reconciliation '
                '(adopted = healthy and re-owned without relaunch)',
                outcome=outcome)
            for outcome in ADOPT_OUTCOMES}
        faults_lib.register_metrics()

    def configure_canary(self, interval_s: float,
                         prompt: Optional[List[int]] = None,
                         max_new_tokens: Optional[int] = None,
                         expected_digest: Optional[str] = None) -> None:
        """Enable/override byzantine canary probing (tests and the
        fleet simulator; live deployments use the SKYTPU_CANARY_*
        env)."""
        self._canary_interval = float(interval_s)
        if prompt is not None:
            self._canary_prompt = [int(t) for t in prompt]
        if max_new_tokens is not None:
            self._canary_max_new = int(max_new_tokens)
        self._canary_expected = expected_digest

    # ------------------------------------------------------------- update
    def update_version(self, spec: 'SkyServiceSpec', task_config: dict,
                       version: int) -> None:
        """Blue-green-lite (reference ``:1172``): new replicas launch with
        the new task; old-version replicas are drained by the controller
        once enough new-version replicas are ready."""
        old_version = self.version
        self.spec = spec
        self.task_config = task_config
        self.version = version
        # A new version may legitimately answer the canary differently
        # (new weights): relearn the reference digest from the first
        # healthy new-version replica. The persisted digest is keyed
        # by version, so the stale key is dropped and a restart mid-
        # rollover relearns exactly like the live path.
        self._canary_learned = None
        if version != old_version:
            self._del_note(f'canary_digest:v{old_version}')

    # ------------------------------------------------------------- launch
    def _replica_cluster_name(self, replica_id: int) -> str:
        return f'{self.service_name}-replica-{replica_id}'

    def _replica_task(self, info: ReplicaInfo) -> Task:
        task = Task.from_yaml_config(dict(self.task_config))
        envs = dict(task.envs or {})
        envs['SKYTPU_REPLICA_PORT'] = str(info.port)
        envs['SKYTPU_SERVE_REPLICA_ID'] = str(info.replica_id)
        envs['SKYTPU_SERVE_SERVICE'] = self.service_name
        # Adaptive-TP placement (serve/placement.py): the replica's
        # (tp, dp) mesh shape rides the launch env — the model server
        # reads SKYTPU_TP/SKYTPU_DP via serving_spec_from_env unless
        # overridden with explicit --tp/--dp.
        envs.update(self.parallelism_plan().as_env())
        # Disaggregation role (prefill/decode/colocated): same env
        # contract — the model server reads SKYTPU_ROLE unless started
        # with an explicit --role.
        envs['SKYTPU_ROLE'] = info.role
        # Multi-tenant LoRA (``adapters:`` spec block): bank size /
        # checkpoint dir / rank ride the same launch-env contract —
        # the model server reads SKYTPU_ADAPTER_* unless started with
        # explicit --adapter-* flags.
        if self.spec.adapter_slots > 0:
            envs['SKYTPU_ADAPTER_SLOTS'] = str(self.spec.adapter_slots)
            envs['SKYTPU_ADAPTER_RANK'] = str(self.spec.adapter_rank)
            if self.spec.adapter_dir:
                envs['SKYTPU_ADAPTER_DIR'] = self.spec.adapter_dir
        # Gang launch env (serve/gang.py): every rank gets the shared
        # gang identity; nonzero ranks additionally get rank 0's URL
        # as the coordinator (set by _launch_replica once rank 0's
        # address resolves).
        if info.gang_world > 1:
            envs['SKYTPU_GANG_ID'] = info.gang_id or ''
            envs['SKYTPU_RANK'] = str(info.gang_rank)
            envs['SKYTPU_WORLD'] = str(info.gang_world)
            envs['SKYTPU_GANG_JOIN_TIMEOUT'] = str(_gang_join_timeout())
            if info.gang_rank > 0 and info.coordinator:
                envs['SKYTPU_COORDINATOR'] = info.coordinator
        task.update_envs(envs)
        if info.is_spot:
            task.set_resources([r.copy(use_spot=True)
                                for r in task.resources])
        return task

    def parallelism_plan(self):
        """The (tp, dp) plan every replica of the current spec version
        launches with (serve/placement.py)."""
        from skypilot_tpu.serve import placement
        return placement.plan_for_spec(self.spec)

    def scale_up(self, use_spot: bool = False) -> Optional[int]:
        """Start one replica launch in the background; returns its id
        (None once the manager is shutting down). With
        ``parallelism: hosts: N`` in the spec, "one replica" is a
        GANG of N processes sharing a gang ID: rank 0 plus N-1
        followers, launched together and replaced together."""
        from skypilot_tpu.serve import placement
        world = max(1, int(self.parallelism_plan().hosts))
        with self._lock:
            if self._shutdown:
                return None
            replica_id = self._next_replica_id
            self._next_replica_id += 1
            port = self._pick_port(replica_id)
            # Disaggregation pool fill: count only replicas that are
            # not already leaving — a draining/failed prefill worker's
            # replacement must re-fill the prefill pool.
            live_roles = [r.role for r in self._replicas.values()
                          if r.gang_rank == 0
                          and not r.status.is_terminal()
                          and r.status not in (
                              serve_state.ReplicaStatus.SHUTTING_DOWN,
                              serve_state.ReplicaStatus.DRAINING)]
            role = placement.role_for_new_replica(self.spec, live_roles)
            gang_id = (f'{self.service_name}-gang-{replica_id}'
                       f'-v{self.version}' if world > 1 else None)
            info = ReplicaInfo(replica_id,
                               self._replica_cluster_name(replica_id),
                               self.version, use_spot, port, role=role,
                               gang_id=gang_id, gang_rank=0,
                               gang_world=world,
                               created_time=self._env.time())
            info.status = serve_state.ReplicaStatus.PROVISIONING
            self._replicas[replica_id] = info
            followers: List[ReplicaInfo] = []
            for rank in range(1, world):
                fid = self._next_replica_id
                self._next_replica_id += 1
                fport = self._pick_port(fid)
                finfo = ReplicaInfo(
                    fid, self._replica_cluster_name(fid),
                    self.version, use_spot, fport, role=role,
                    gang_id=gang_id, gang_rank=rank, gang_world=world,
                    created_time=self._env.time())
                finfo.status = serve_state.ReplicaStatus.PROVISIONING
                self._replicas[fid] = finfo
                followers.append(finfo)
        # Journal BEFORE persisting rows or spawning the launch: a
        # crash at any later point leaves a pending 'launch' op whose
        # payload carries the full descriptor (role/gang/port), so the
        # restarted controller can kill the zombie cluster — or adopt
        # the replica with its role and gang membership intact.
        for member in [info] + followers:
            member.launch_op = self._journal_start(
                'launch', member, payload=self._descriptor(member))
        self._persist(info)
        for finfo in followers:
            self._persist(finfo)
        # Rank 0 launches first: followers need its resolved address
        # as their SKYTPU_COORDINATOR (_launch_replica fans them out
        # once rank 0 reaches STARTING).
        self._env.spawn(self._launch_replica, info)
        return replica_id

    @staticmethod
    def _descriptor(info: ReplicaInfo) -> Dict[str, object]:
        """The journal payload that lets a restarted controller
        rebuild this replica's ReplicaInfo without guessing (live
        probes refine role/gang where the replica still answers)."""
        return {
            'cluster_name': info.cluster_name,
            'port': info.port,
            'is_spot': info.is_spot,
            'role': info.role,
            'gang_id': info.gang_id,
            'gang_rank': info.gang_rank,
            'gang_world': info.gang_world,
            'version': info.version,
        }

    def shutdown(self) -> None:
        """Refuse further scale_up; in-flight launches will self-clean."""
        with self._lock:
            self._shutdown = True

    def in_launch_backoff(self) -> bool:
        """True while recent launch failures put new launches on hold
        (exponential backoff so a persistent failure — quota, bad image —
        doesn't spin up a doomed launch every controller tick)."""
        with self._lock:
            return self._env.time() < self._backoff_until

    def backoff_remaining(self) -> float:
        """Seconds until launches resume (0 when not backing off) —
        the controller ships this to the LB as the Retry-After hint on
        the no-ready-replicas 503."""
        with self._lock:
            return max(0.0, self._backoff_until - self._env.time())

    def retry_after_hint(self) -> int:
        """Whole-second Retry-After for clients hitting the service
        while no replica is READY, from live replica state: the launch
        backoff remainder when backing off, a short probe-propagation
        interval while a replica is already starting/draining, and a
        provisioning-scale guess otherwise."""
        backoff = self.backoff_remaining()
        if backoff > 0:
            return max(1, int(backoff))
        with self._lock:
            statuses = {r.status for r in self._replicas.values()}
        if (serve_state.ReplicaStatus.STARTING in statuses
                or serve_state.ReplicaStatus.READY in statuses
                or serve_state.ReplicaStatus.DRAINING in statuses):
            # A replica exists and is (nearly) servable: the LB learns
            # about it at its next controller sync.
            return 5
        if serve_state.ReplicaStatus.PROVISIONING in statuses:
            return max(5, int(self.spec.initial_delay_seconds / 4))
        return 15

    def _pick_port(self, replica_id: int) -> int:
        """Fixed spec port on real clouds (distinct head IPs); a free local
        port per replica on the local provider (shared host). Ports
        recorded by OTHER services (allocated but possibly unbound) are
        excluded via the shared serve-state table."""
        cloud = (self.task_config.get('resources') or {}).get('cloud')
        if cloud != 'local':
            return self.spec.replica_port
        taken = self._reserved_ports | {
            r.port for r in self._replicas.values()}
        taken |= serve_state.allocated_ports()
        start = 10000
        while True:
            port = common_utils.find_free_port(start)
            if port not in taken:
                return port
            start = port + 1

    def _launch_replica(self, info: ReplicaInfo) -> None:
        task = self._replica_task(info)
        try:
            self._env.launch_cluster(task, info.cluster_name)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'Replica {info.replica_id} launch failed: '
                           f'{type(e).__name__}: {e}')
            self._record_launch_result(info, failed=True)
            return
        # A concurrent scale_down/shutdown may have removed this replica
        # while the launch was in flight; the fresh cluster is then
        # orphaned — tear it down instead of resurrecting the DB row.
        with self._lock:
            abandoned = (self._shutdown
                         or self._replicas.get(info.replica_id) is not info
                         or info.status !=
                         serve_state.ReplicaStatus.PROVISIONING)
        if abandoned:
            logger.info(f'Replica {info.replica_id} was removed during '
                        'launch; tearing its cluster down.')
            try:
                self._env.down_cluster(info.cluster_name)
            except Exception as e:  # pylint: disable=broad-except
                logger.warning(
                    f'Teardown of abandoned replica cluster '
                    f'{info.cluster_name} failed (it may leak): '
                    f'{type(e).__name__}: {e}')
            self._untrack(info.replica_id)
            self._journal_finish(info.launch_op)
            info.launch_op = None
            return
        head_ip = self._env.cluster_head_ip(info.cluster_name)
        if head_ip is None:
            self._record_launch_result(info, failed=True)
            return
        with self._lock:
            # Re-check under the lock: a scale_down between the abandoned
            # check above and here must not have its SHUTTING_DOWN status
            # clobbered back to STARTING.
            if info.status != serve_state.ReplicaStatus.PROVISIONING:
                return
            info.url = f'http://{head_ip}:{info.port}'
            info.status = serve_state.ReplicaStatus.STARTING
            info.first_probe_time = self._env.time()
            followers = ([r for r in self._replicas.values()
                          if info.gang_id is not None
                          and r.gang_id == info.gang_id
                          and r.gang_rank > 0
                          and r.status ==
                          serve_state.ReplicaStatus.PROVISIONING]
                         if info.gang_rank == 0 else [])
            for f in followers:
                # Rank 0's address is the gang bus every follower
                # syncs against; set before their tasks render env.
                f.coordinator = info.url
        self._persist(info)
        # Gang fan-out: rank 0 is up, launch the follower ranks (each
        # its own cluster, same gang ID). Readiness still waits on the
        # barrier — rank 0's /readiness stays 503 until every rank
        # joins within SKYTPU_GANG_JOIN_TIMEOUT.
        for f in followers:
            self._env.spawn(self._launch_replica, f)
        self._record_launch_result(info, failed=False)

    def _record_launch_result(self, info: ReplicaInfo, failed: bool) -> None:
        if not failed:
            # NOTE: launch success only clears the backoff once the
            # replica actually turns READY (probe_all) — a cluster that
            # provisions fine but whose app never answers must still
            # back off, or it churns whole slices forever.
            return
        info.status = serve_state.ReplicaStatus.FAILED
        self._persist(info)
        # The launch op is terminal either way: a FAILED row is kept
        # for debugging (pruned by _bump_backoff), not replayed.
        self._journal_finish(info.launch_op)
        info.launch_op = None
        try:      # a launch can fail after partially creating the cluster
            self._env.down_cluster(info.cluster_name)
        except exceptions.ClusterDoesNotExist:
            pass
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'Cleanup of failed replica '
                           f'{info.cluster_name} failed: {e}')
        self._bump_backoff()
        # Gang atomicity at launch: ONE rank failing to provision
        # fails the whole gang (a partial gang can never pass the
        # barrier anyway — tear it down now instead of burning the
        # join timeout).
        self.scale_down_gang(info.gang_id,
                             serve_state.ReplicaStatus.FAILED,
                             except_id=info.replica_id)

    def _bump_backoff(self) -> None:
        """One more replica died before ever serving: extend the
        exponential launch backoff (jittered — concurrent failures
        must not produce synchronized retry storms against the same
        exhausted zone/quota) and prune old FAILED rows."""
        with self._lock:
            self._launch_failures += 1
            delay = min(
                _launch_backoff_base() * (2 ** (self._launch_failures - 1)),
                _LAUNCH_BACKOFF_CAP)
            # Uniform over [_BACKOFF_JITTER_FRAC, 1.0] x delay: decorrelates
            # concurrent managers while keeping the exponential shape
            # (and the cap as a hard ceiling).
            delay *= (_BACKOFF_JITTER_FRAC
                      + (1.0 - _BACKOFF_JITTER_FRAC) * self._rng.random())
            self._backoff_until = self._env.time() + delay
            # Keep only the newest few FAILED rows (status/debugging);
            # older ones would otherwise accumulate one per retry forever.
            failed_ids = sorted(
                rid for rid, r in self._replicas.items()
                if r.status == serve_state.ReplicaStatus.FAILED)
            prune = failed_ids[:-_MAX_RETAINED_FAILED]
        for rid in prune:      # outside _lock: _untrack takes _db_lock
            self._untrack(rid)

    # --------------------------------------------------------------- gang
    def _gang_members_locked(self, gang_id: Optional[str]
                             ) -> List[ReplicaInfo]:
        """Every tracked member of ``gang_id`` (callers hold _lock)."""
        if gang_id is None:
            return []
        return [r for r in self._replicas.values()
                if r.gang_id == gang_id]

    def _gang_leader_locked(self, info: ReplicaInfo) -> ReplicaInfo:
        """The rank-0 member of ``info``'s gang (``info`` itself for
        singles/rank 0) — the one routable endpoint every HTTP-side
        lifecycle action (probe, drain, checkpoint) targets."""
        if info.gang_id is None or info.gang_rank == 0:
            return info
        for r in self._replicas.values():
            if r.gang_id == info.gang_id and r.gang_rank == 0:
                return r
        return info

    def _ckpt_key(self, info: ReplicaInfo) -> str:
        return info.gang_id or f'replica-{info.replica_id}'

    def scale_down_gang(self, gang_id: Optional[str],
                        status: Optional[serve_state.ReplicaStatus]
                        = None, *,
                        except_id: Optional[int] = None) -> None:
        """Tear down every member of a gang: one dead rank means the
        whole gang is dead — the controller then replaces the gang as
        one unit (its next tick sees all members terminal). No-op for
        ``gang_id=None`` (singles route through ``scale_down``)."""
        if gang_id is None:
            return
        with self._lock:
            member_ids = [r.replica_id for r in
                          self._gang_members_locked(gang_id)
                          if r.replica_id != except_id
                          and not r.status.is_terminal()
                          and r.status !=
                          serve_state.ReplicaStatus.SHUTTING_DOWN]
        for rid in member_ids:
            self._scale_down_one(rid, status)

    def replica_gangs(self) -> Dict[str, Dict[str, object]]:
        """rank0 url -> gang health block, for the LB sync payload:
        the policies use it to keep follower addresses out of probe
        sweeps while still accounting every rank's existence."""
        with self._lock:
            out: Dict[str, Dict[str, object]] = {}
            for r in self._replicas.values():
                if r.gang_id is None or r.gang_rank != 0 or not r.url:
                    continue
                members = self._gang_members_locked(r.gang_id)
                out[r.url] = {
                    'gang_id': r.gang_id,
                    'world': r.gang_world,
                    'follower_urls': [m.url for m in members
                                      if m.gang_rank > 0
                                      and m.url is not None],
                    'statuses': {str(m.gang_rank): m.status.value
                                 for m in members},
                }
            return out

    # -------------------------------------------------------------- drain
    def drain(self, replica_id: int,
              deadline_s: Optional[float] = None) -> bool:
        """Graceful scale-down: mark the replica DRAINING (it drops out
        of ``ready_urls`` — the LB removes it from rotation at its next
        sync), ask its model server to stop admitting and finish its
        in-flight requests, then tear the cluster down once drained or
        at the deadline. Idempotent; returns True when a drain was
        started (False: unknown replica or already leaving)."""
        with self._lock:
            info = self._replicas.get(replica_id)
            if info is not None:
                # Gang atomicity: a drain aimed at ANY member drains
                # the gang — through rank 0, its one HTTP endpoint
                # (rank 0's /drain fans out on the gang bus and
                # reports drained only once every rank acked).
                info = self._gang_leader_locked(info)
            if info is None or info.status in (
                    serve_state.ReplicaStatus.DRAINING,
                    serve_state.ReplicaStatus.SHUTTING_DOWN) or \
                    info.status.is_terminal():
                return False
            # A replica that never served (no URL yet) has nothing to
            # drain — plain scale_down below.
            drainable = (info.url is not None and info.status in (
                serve_state.ReplicaStatus.READY,
                serve_state.ReplicaStatus.NOT_READY))
            if drainable:
                info.status = serve_state.ReplicaStatus.DRAINING
                members = self._gang_members_locked(info.gang_id)
                for m in members:
                    if m.gang_rank > 0 and not m.status.is_terminal():
                        # Followers leave rotation bookkeeping with
                        # their leader (they were never routable, but
                        # health accounting must show the gang
                        # leaving as one unit).
                        m.status = serve_state.ReplicaStatus.DRAINING
        if not drainable:
            self.scale_down(replica_id)
            return False
        _transition_counter('DRAINING').inc()
        deadline_s = (float(deadline_s) if deadline_s is not None
                      else _drain_deadline_default())
        # Journal the drain with its ABSOLUTE deadline before the
        # first effect (the /drain POST): a controller that dies
        # mid-drain restarts and resumes the wait at the REMAINING
        # budget — in-flight requests get exactly the window they were
        # promised, not a fresh full deadline and not an instant kill.
        info.drain_op = self._journal_start(
            'drain', info, payload={'deadline_s': deadline_s},
            deadline_at=self._env.time() + deadline_s)
        self._persist(info)
        logger.info(f'Draining replica {info.replica_id}'
                    + (f' (gang {info.gang_id})' if info.gang_id
                       else '')
                    + f' (deadline {deadline_s:.0f}s).')
        self._env.spawn(self._drain_then_down, info, deadline_s)
        return True

    def _drain_then_down(self, info: ReplicaInfo,
                         deadline_s: float) -> None:
        try:
            self._await_replica_drain(info, deadline_s)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'Drain of replica {info.replica_id} failed '
                           f'({type(e).__name__}: {e}); tearing down '
                           'anyway')
        self.scale_down(info.replica_id)
        self._journal_finish(info.drain_op)
        info.drain_op = None

    def _await_replica_drain(self, info: ReplicaInfo,
                             deadline_s: float) -> None:
        """POST /drain to the replica's model server, then poll its
        drain status until drained or the deadline. A replica whose
        server doesn't implement the drain contract (no ``draining``
        key in the response) tears down immediately — there is nothing
        to wait for. Deadline stragglers (a replica that never reports
        ``drained``) are torn down at exactly the deadline; their
        in-flight requests fail over through the LB's recovery path."""
        assert info.url is not None
        deadline = self._env.monotonic() + deadline_s
        try:
            payload = self._env.http_json(
                info.url + '/drain', {'deadline_s': deadline_s},
                timeout=10)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'Drain request to replica '
                           f'{info.replica_id} failed '
                           f'({type(e).__name__}: {e}); skipping wait')
            return
        if not isinstance(payload, dict) or 'draining' not in payload:
            logger.info(f'Replica {info.replica_id} has no drain '
                        'support; tearing down immediately.')
            return
        while self._env.monotonic() < deadline:
            try:
                status = self._env.http_json(info.url + '/drain',
                                             timeout=10)
                if status.get('drained'):
                    logger.info(
                        f'Replica {info.replica_id} drained cleanly.')
                    return
            except Exception as e:  # pylint: disable=broad-except
                logger.warning(f'Drain poll of replica '
                               f'{info.replica_id} failed '
                               f'({type(e).__name__}: {e}); assuming '
                               'gone')
                return
            # Jittered poll (graftcheck GC112: no fixed-sleep loops),
            # bounded by the remaining deadline so the teardown lands
            # AT the deadline, not one poll interval past it.
            remaining = deadline - self._env.monotonic()
            if remaining <= 0:
                break
            self._env.sleep(min(remaining,
                                0.25 * (0.5 + self._rng.random())))
        logger.warning(f'Replica {info.replica_id} drain deadline '
                       f'({deadline_s:.0f}s) exceeded; tearing down '
                       '(stragglers fail over through the LB).')

    def handle_preemption_warning(
            self, replica_id: int,
            deadline_s: Optional[float] = None) -> bool:
        """Advance preemption notice (cloud spot warning / injected
        ``preempt_signal`` at the ``preempt_warning`` /
        ``spot_preemption`` sites): checkpoint the replica's hot
        prefix-cache chains FIRST (the KV is gone once the capacity
        is), then route through graceful drain so in-flight work
        finishes (or migrates) before the capacity disappears.

        Race-free with an in-flight drain AND re-delivery to another
        rank: the checkpoint step is guarded by a flag keyed by GANG
        ID (replica id for singles) under the manager lock, so a
        warning that lands while a drain is already running — or a
        warning re-delivered to a *different rank of the same gang* —
        still checkpoints exactly once and never double-drains."""
        logger.info(f'Preemption warning for replica {replica_id}; '
                    'checkpointing and draining ahead of it.')
        with self._lock:
            info = self._replicas.get(replica_id)
            if info is not None:
                # Gang-atomic: warnings to any rank checkpoint/drain
                # the gang through its rank-0 endpoint.
                info = self._gang_leader_locked(info)
            if info is not None and info.is_spot:
                self._m_spot_preempt.inc()
        if info is not None:
            self._checkpoint_replica(info)
            return self.drain(info.replica_id, deadline_s)
        return self.drain(replica_id, deadline_s)

    def _checkpoint_replica(self, info: ReplicaInfo) -> None:
        """Fetch the replica's prefix-cache checkpoint (``POST
        /checkpoint`` against the gang leader — the response body is
        the SKCK container; a gang leader's export completes only when
        every rank acked) and store it for replacement warmup. At most
        once per gang (flag keyed by gang ID under the lock);
        best-effort — a failure clears the flag so a later warning may
        retry, and the drain proceeds either way."""
        key = self._ckpt_key(info)
        with self._lock:
            if self._ckpt_done.get(key) or info.url is None:
                return
            self._ckpt_done[key] = True
            info.checkpointed = True
        # Persist the dedupe key: a controller that dies between the
        # checkpoint and the preemption must never double-checkpoint
        # the same gang after restart (re-delivered warnings included).
        self._put_note(f'ckpt_done:{key}', True)
        try:
            blob = self._env.http_post_bytes(
                info.url + '/checkpoint', b'{}',
                content_type='application/json', timeout=30)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'Checkpoint of replica {info.replica_id} '
                           f'failed ({type(e).__name__}: {e}); its '
                           'replacement will boot cold')
            with self._lock:
                self._ckpt_done[key] = False
                info.checkpointed = False
            self._del_note(f'ckpt_done:{key}')
            return
        if self._faults is not None:
            # Deterministic checkpoint corruption (site 'kv_wire', kind
            # kv_corruption): one byte of the fetched container flips —
            # the replacement's CRC-checked warmup must refuse it and
            # boot cold, never byte-wrong warm.
            rule = self._faults.fire('kv_wire')
            if rule is not None and rule.kind == 'kv_corruption':
                blob = faults_lib.corrupt_blob(blob, rule)
                logger.warning('injected kv_corruption on the stored '
                               'checkpoint (1 byte flipped)')
        with self._ckpt_lock:
            self._ckpt_bytes = blob
            self._ckpt_time = self._env.time()
        logger.info(f'Checkpointed replica {info.replica_id}: '
                    f'{len(blob)} byte(s) of prefix-cache state.')

    def checkpoint_for_warmup(self) -> Optional[bytes]:
        """The freshest stored checkpoint, or None (none taken yet, or
        stale past the TTL — cold traffic has moved on)."""
        with self._ckpt_lock:
            if self._ckpt_bytes is None:
                return None
            if self._env.time() - self._ckpt_time > _ckpt_ttl():
                return None
            return self._ckpt_bytes

    def _warm_replica(self, info: ReplicaInfo) -> None:
        """Land the stored checkpoint into a replica that just passed
        its first probe — BEFORE it is marked READY, so by the time
        the LB routes to it the prefix cache already holds the
        preempted replica's hot chains (near-warm recovery TTFT). At
        most once per replica; best-effort with a bounded timeout —
        a failed warmup costs only cold-cache latency."""
        if info.warmed:
            return
        info.warmed = True
        blob = self.checkpoint_for_warmup()
        if blob is None or info.url is None:
            return
        t0 = self._env.monotonic()
        try:
            import json as _json
            body = self._env.http_post_bytes(
                info.url + '/kv/warmup', blob,
                content_type='application/octet-stream',
                timeout=_warmup_timeout())
            payload = _json.loads(body)
        except Exception as e:  # pylint: disable=broad-except
            if '400' in str(e) or 'invalid_checkpoint' in str(e):
                # The warmup target REFUSED the container (malformed /
                # checksum mismatch): a corrupted checkpoint became a
                # cold boot instead of byte-wrong warmth.
                faults_lib.gray_failure_counter('kv_corruption').inc()
            logger.warning(f'Prefix warmup of replica '
                           f'{info.replica_id} failed '
                           f'({type(e).__name__}: {e}); entering '
                           'rotation cold')
            return
        dur = self._env.monotonic() - t0
        self._h_warmup.observe(dur)
        logger.info(
            f'Replica {info.replica_id} prefix-warmed in {dur:.2f}s: '
            f'{payload.get("warmed_rows", 0)} row(s) across '
            f'{payload.get("entries", 0)} entr(ies).')

    def pop_provision_observations(self) -> List[float]:
        """Drain the unconsumed provision-latency observations (the
        controller feeds them to the forecast autoscaler's lead-time
        EWMA each tick)."""
        with self._lock:
            obs, self._provision_obs = self._provision_obs, []
        return obs

    # ------------------------------------------------------------ teardown
    def scale_down(self, replica_id: int, status: Optional[
            serve_state.ReplicaStatus] = None) -> None:
        """Terminate a replica (async; cluster teardown is slow). A
        gang member's teardown tears the WHOLE gang down — one dead
        rank, dead gang, replaced as one unit."""
        with self._lock:
            info = self._replicas.get(replica_id)
            gang_id = info.gang_id if info is not None else None
        self._scale_down_one(replica_id, status)
        self.scale_down_gang(gang_id, status, except_id=replica_id)

    def _scale_down_one(self, replica_id: int, status: Optional[
            serve_state.ReplicaStatus] = None) -> None:
        with self._lock:
            info = self._replicas.get(replica_id)
            if info is None:
                return
            if info.teardown_started:
                # Exactly-once teardown: racing scale_down calls (a
                # drain deadline racing a probe escalation, re-issued
                # autoscaler decisions, journal replay after restart)
                # must never run a second down_cluster for the same
                # replica.
                return
            info.teardown_started = True
            info.status = status or serve_state.ReplicaStatus.SHUTTING_DOWN
        self._persist(info)
        op_id = self._journal_start('teardown', info)

        def _down():
            try:
                self._env.down_cluster(info.cluster_name)
            except exceptions.ClusterDoesNotExist:
                pass
            except Exception as e:  # pylint: disable=broad-except
                logger.warning(f'Teardown of {info.cluster_name} failed: '
                               f'{type(e).__name__}: {e}')
            self._untrack(replica_id)  # atomic vs _persist (see _db_lock)
            self._journal_finish(op_id)

        self._env.spawn(_down)

    def terminate_all(self) -> None:
        with self._lock:
            ids = list(self._replicas)
        fns = []
        for rid in ids:
            info = self._replicas.get(rid)
            if info is None:
                continue
            fns.append(lambda i=info: self._sync_down(i))
        self._env.run_parallel(fns)

    def _sync_down(self, info: ReplicaInfo) -> None:
        try:
            self._env.down_cluster(info.cluster_name)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'Teardown of {info.cluster_name} during '
                           f'terminate_all failed (it may leak): '
                           f'{type(e).__name__}: {e}')
        self._untrack(info.replica_id)

    # ------------------------------------------------------------- probing
    def _probe_one(self, info: ReplicaInfo) -> bool:
        assert info.url is not None
        if self._faults is not None:
            rule = self._faults.fire('probe')
            if rule is not None and rule.kind == 'probe_timeout':
                # Injected probe timeout: burn (a bounded slice of) the
                # timeout, then report failure — the consecutive-
                # failure escalation runs exactly as for a real one.
                self._env.sleep(min(rule.delay_s,
                                    self.spec.readiness_timeout_seconds))
                logger.warning(f'Probe of replica {info.replica_id} '
                               'failed (injected probe_timeout)')
                return False
        url = info.url + self.spec.readiness_path
        try:
            return self._env.probe_http(
                url, self.spec.post_data,
                self.spec.readiness_timeout_seconds)
        except Exception as e:  # pylint: disable=broad-except
            # Routine while a replica boots; the consecutive-failure
            # counters escalate, but the reason must stay observable.
            logger.debug(f'Probe of replica {info.replica_id} ({url}) '
                         f'failed: {type(e).__name__}: {e}')
            return False

    def _check_preempted(self, info: ReplicaInfo) -> bool:
        """Cluster-gone (or not UP) while we thought it was running =
        preemption (reference ``_handle_preemption`` ``:782``)."""
        if self._faults is not None:
            rule = self._faults.fire('preempt')
            if rule is not None and rule.kind == 'preempt_signal':
                logger.warning(f'Replica {info.replica_id} preempted '
                               '(injected preempt_signal)')
                return True
        return self._env.cluster_gone(info.cluster_name)

    def set_telemetry_sink(self, sink: Any) -> None:
        """Install the controller's fleet-telemetry ingest callable:
        ``sink(source, payload)`` receives each scraped
        ``/telemetry/summary`` body keyed by the replica's URL."""
        self._telemetry_sink = sink

    def _scrape_telemetry(self, info: ReplicaInfo) -> None:
        """Pull one replica's telemetry summary right after a
        successful readiness probe and hand it to the sink. The
        per-replica cursor makes completed traces ship at most once;
        any failure is logged at debug and otherwise ignored — the
        fleet plane must never destabilize the health plane."""
        if self._telemetry_sink is None or not info.url:
            return
        source = info.url.rstrip('/')
        since = self._telemetry_cursors.get(source, 0)
        try:
            payload = self._env.http_json(
                f'{source}/telemetry/summary?since={since}',
                timeout=self.spec.readiness_timeout_seconds)
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'telemetry scrape of replica '
                         f'{info.replica_id} failed: '
                         f'{type(e).__name__}: {e}')
            return
        if not isinstance(payload, dict):
            return
        cursor = payload.get('cursor')
        if isinstance(cursor, int):
            self._telemetry_cursors[source] = cursor
        try:
            self._telemetry_sink(source, payload)
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'telemetry ingest for replica '
                         f'{info.replica_id} failed: '
                         f'{type(e).__name__}: {e}')

    def probe_all(self) -> None:
        """One probe sweep (reference ``_probe_all_replicas`` ``:1026``)."""
        with self._lock:
            infos = list(self._replicas.values())
        for info in infos:
            if info.status not in (serve_state.ReplicaStatus.STARTING,
                                   serve_state.ReplicaStatus.READY,
                                   serve_state.ReplicaStatus.NOT_READY):
                continue
            if info.gang_rank > 0:
                # Follower ranks have no probe endpoint (rank 0 is the
                # gang's one routable URL; its readiness already
                # embeds the barrier and the gang bus covers process
                # health). Cluster existence is their only direct
                # signal — and a follower cluster gone means the WHOLE
                # gang is gone (scale_down is gang-atomic).
                if self._check_preempted(info):
                    logger.info(
                        f'Gang {info.gang_id}: follower rank '
                        f'{info.gang_rank} (replica '
                        f'{info.replica_id}) preempted; failing the '
                        'whole gang.')
                    if info.is_spot:
                        self._m_spot_preempt.inc()
                    _transition_counter('PREEMPTED').inc()
                    self.scale_down(info.replica_id,
                                    serve_state.ReplicaStatus.PREEMPTED)
                continue
            # Advance preemption warning (injected; cloud spot notices
            # would land here too): drain instead of hard-killing.
            if (self._faults is not None
                    and info.status == serve_state.ReplicaStatus.READY):
                rule = self._faults.fire('preempt_warning')
                if rule is not None and rule.kind == 'preempt_signal':
                    self.handle_preemption_warning(info.replica_id)
                    continue
                # Spot-targeted kill schedule: the site counter only
                # advances for SPOT replicas, so an `at`/`every` rule
                # deterministically names the Nth spot sweep — the
                # chaos/bench seeded spot-preemption path (checkpoint
                # + drain + teardown + backfill).
                if info.is_spot:
                    rule = self._faults.fire('spot_preemption')
                    if rule is not None and \
                            rule.kind == 'preempt_signal':
                        self.handle_preemption_warning(info.replica_id)
                        continue
            # Cluster existence is ground truth, checked BEFORE the HTTP
            # probe: a terminated replica's address can keep answering (IP
            # reuse on clouds; surviving process on the local provider).
            if self._check_preempted(info):
                logger.info(f'Replica {info.replica_id} preempted.')
                if info.is_spot:
                    # Hard loss (no advance warning): counted the same
                    # as a warned preemption; nothing to checkpoint —
                    # the capacity is already gone.
                    self._m_spot_preempt.inc()
                info.status = serve_state.ReplicaStatus.PREEMPTED
                _transition_counter('PREEMPTED').inc()
                self._persist(info)
                self.scale_down(info.replica_id,
                                serve_state.ReplicaStatus.PREEMPTED)
                continue
            if self._probe_one(info):
                _probe_counter('success').inc()
                info.consecutive_failures = 0
                if info.status != serve_state.ReplicaStatus.READY:
                    # First successful probe: prefix-warm from the
                    # latest preemption checkpoint BEFORE the replica
                    # is marked READY — it must never enter ready_urls
                    # (and thus LB rotation) cold when warm state
                    # exists.
                    self._warm_replica(info)
                    logger.info(f'Replica {info.replica_id} is READY at '
                                f'{info.url}.')
                    _transition_counter('READY').inc()
                    # The journaled launch op is acked: the replica
                    # served a probe — it is no longer a potential
                    # zombie for restart reconciliation to reap.
                    self._journal_finish(info.launch_op)
                    info.launch_op = None
                    self._h_provision.observe(
                        max(0.0, self._env.time() - info.created_time))
                    with self._lock:     # a replica serves: reset backoff
                        self._launch_failures = 0
                        self._backoff_until = 0.0
                        self._provision_obs.append(
                            max(0.0,
                                self._env.time() - info.created_time))
                info.status = serve_state.ReplicaStatus.READY
                self._persist(info)
                self._mirror_gang_ready(info)
                # Byzantine canary (env-clock cadence): a READY
                # replica that answers the known-digest greedy canary
                # WRONG is quarantined before it can serve a second
                # wrong response.
                self._canary_check(info)
                # Fleet-telemetry scrape rides the probe it just
                # passed (best-effort: never fails the sweep).
                self._scrape_telemetry(info)
                continue
            # Probe failed on a live cluster.
            _probe_counter('failure').inc()
            if info.status == serve_state.ReplicaStatus.STARTING:
                elapsed = self._env.time() - (info.first_probe_time or 0)
                if elapsed > self.spec.initial_delay_seconds:
                    logger.warning(
                        f'Replica {info.replica_id} failed to become ready '
                        f'within {self.spec.initial_delay_seconds}s.')
                    info.status = serve_state.ReplicaStatus.FAILED_PROBE
                    _transition_counter('FAILED_PROBE').inc()
                    self._persist(info)
                    self.scale_down(info.replica_id,
                                    serve_state.ReplicaStatus.FAILED_PROBE)
                    # The cluster came up but the app never served — the
                    # relaunch loop must back off, not churn slices.
                    self._bump_backoff()
                continue
            info.consecutive_failures += 1
            if info.consecutive_failures >= _PROBE_FAILURE_TERMINATE:
                # The app on a still-UP cluster is persistently dead
                # (crashed server, wedged process). NOT_READY is neither
                # ready nor terminal, so without this the autoscaler
                # counts it alive forever and never replaces it.
                logger.warning(
                    f'Replica {info.replica_id} failed '
                    f'{info.consecutive_failures} consecutive probes; '
                    'terminating it for replacement.')
                info.status = serve_state.ReplicaStatus.FAILED_PROBE
                _transition_counter('FAILED_PROBE').inc()
                self._persist(info)
                self.scale_down(info.replica_id,
                                serve_state.ReplicaStatus.FAILED_PROBE)
                self._bump_backoff()
            elif info.consecutive_failures >= _PROBE_FAILURE_GRACE:
                if info.status != serve_state.ReplicaStatus.NOT_READY:
                    _transition_counter('NOT_READY').inc()
                info.status = serve_state.ReplicaStatus.NOT_READY
                self._persist(info)

    # --------------------------------------------------------- quarantine
    def _canary_check(self, info: ReplicaInfo) -> bool:
        """One canary evaluation for a READY replica (no-op unless the
        cadence elapsed on the env clock). Greedy canary prompt ->
        digest of the returned tokens -> compare against the
        configured/learned reference. A mismatch quarantines; a
        transport failure is IGNORED here (liveness belongs to the
        readiness-probe escalation — the canary only judges replicas
        that answer). Returns True when the replica was quarantined."""
        if (self._canary_interval <= 0 or info.gang_rank > 0
                or info.url is None):
            return False
        now = self._env.time()
        if now - info.last_canary_t < self._canary_interval:
            return False
        info.last_canary_t = now
        forced = False
        if self._faults is not None:
            # Deterministic byzantine injection (site 'canary', kind
            # byzantine_response): this replica's answer is treated as
            # wrong-digest — the quarantine path runs exactly as for a
            # really-corrupted replica.
            rule = self._faults.fire('canary')
            if rule is not None and rule.kind == 'byzantine_response':
                forced = True
        if not forced:
            try:
                resp = self._env.http_json(
                    info.url + '/generate',
                    {'prompt': list(self._canary_prompt),
                     'max_new_tokens': self._canary_max_new,
                     'temperature': 0.0},
                    timeout=30)
                tokens = (resp or {}).get('tokens')
                if not isinstance(tokens, list):
                    return False
                digest = canary_digest(tokens)
            except Exception as e:  # pylint: disable=broad-except
                logger.debug(
                    f'Canary probe of replica {info.replica_id} '
                    f'failed ({type(e).__name__}: {e}); the readiness '
                    'probe escalation owns liveness')
                return False
            expected = self._canary_expected or self._canary_learned
            if expected is None:
                # Quorum-of-first: the reference digest is learned
                # from the first replica that answers (configure an
                # expected_digest to close the first-answerer-is-
                # byzantine window). Persisted keyed by version: a
                # restarted controller keeps judging canaries against
                # the SAME reference instead of relearning from a
                # possibly-byzantine first answerer.
                self._canary_learned = digest
                self._put_note(f'canary_digest:v{self.version}', digest)
                logger.info(
                    f'Canary reference digest learned from replica '
                    f'{info.replica_id}: {digest}')
                return False
            if digest == expected:
                return False
            logger.warning(
                f'Replica {info.replica_id} answered the canary with '
                f'digest {digest} != expected {expected} (byzantine '
                'response — silent data corruption).')
        else:
            logger.warning(
                f'Replica {info.replica_id} canary forced byzantine '
                '(injected byzantine_response).')
        return self.quarantine_replica(info.replica_id)

    def quarantine_replica(self, replica_id: int) -> bool:
        """Byzantine containment: move the replica (the WHOLE gang for
        gang members) to QUARANTINED — out of ``ready_urls``
        immediately, excluded by every LB policy at its next sync,
        then drained and torn down; the autoscaler replaces it
        (QUARANTINED is terminal). Idempotent; returns True when a
        quarantine was started."""
        with self._lock:
            info = self._replicas.get(replica_id)
            if info is not None:
                info = self._gang_leader_locked(info)
            if info is None or info.status.is_terminal() or \
                    info.status in (
                        serve_state.ReplicaStatus.SHUTTING_DOWN,):
                return False
            info.status = serve_state.ReplicaStatus.QUARANTINED
            for m in self._gang_members_locked(info.gang_id):
                if m.gang_rank > 0 and not m.status.is_terminal():
                    m.status = serve_state.ReplicaStatus.QUARANTINED
            self.quarantined_count += 1
        _transition_counter('QUARANTINED').inc()
        self._m_quarantined.inc()
        faults_lib.gray_failure_counter('byzantine_response').inc()
        self._persist(info)
        logger.warning(
            f'Replica {info.replica_id}'
            + (f' (gang {info.gang_id})' if info.gang_id else '')
            + ' QUARANTINED: out of rotation now, draining, then '
              'tearing down for replacement.')
        self._env.spawn(self._drain_then_down, info,
                        _drain_deadline_default())
        return True

    def _mirror_gang_ready(self, leader: ReplicaInfo) -> None:
        """Health accounting for follower ranks: rank 0 READY means
        the barrier completed, which means every rank is up — mirror
        the status onto the follower rows (they are never probed and
        never routable, but operators and the autoscaler must see the
        gang's full health picture)."""
        if leader.gang_id is None:
            return
        with self._lock:
            members = [m for m in
                       self._gang_members_locked(leader.gang_id)
                       if m.gang_rank > 0 and m.status in (
                           serve_state.ReplicaStatus.STARTING,
                           serve_state.ReplicaStatus.NOT_READY)]
            for m in members:
                m.status = serve_state.ReplicaStatus.READY
        for m in members:
            self._persist(m)

    # ------------------------------------------------------ reconciliation
    def reconcile(self) -> Dict[str, int]:
        """Rebuild the manager after a controller restart from the
        persisted rows + pending journal ops + controller notes, with
        live probes as ground truth (docs/robustness.md, controller
        failure domain). Per discovered replica, exactly one of:

        - **adopted** — healthy (cluster up, probe passes): re-owned
          in place, role/gang recovered from the journal descriptor
          and refined by live ``/metrics?format=json`` +
          ``/gang/status`` probes; never relaunched, never re-warmed.
        - **probe_pending** — cluster up but the app not answering:
          re-enters STARTING with a fresh grace window.
        - **drain_resumed** — an interrupted drain continues at its
          *remaining* deadline (the journal stored the absolute one).
        - **teardown_replayed** — an unacked teardown (or a terminal/
          SHUTTING_DOWN row) runs exactly once.
        - **zombie_killed** — a crash mid-launch leaked a cluster with
          no live owner: torn down, row cleared, the autoscaler
          relaunches fresh.
        - **preempted** — the cluster vanished during the outage:
          marked PREEMPTED and cleaned up like any hard loss.

        Also restores the checkpoint-dedupe keys (a preemption warning
        re-delivered after restart still checkpoints exactly once) and
        the learned canary digest for the current spec version, and
        seeds ``_next_replica_id`` / the reserved-port set from the
        persisted history so an adopted fleet never collides with new
        launches. Idempotent: an empty DB reconciles to a no-op."""
        rows = self._env.load_replica_rows(self.service_name)
        ops = self._env.pending_ops(self.service_name)
        notes = self._env.get_notes(self.service_name)
        stats = {outcome: 0 for outcome in ADOPT_OUTCOMES}
        now = self._env.time()
        # Durable facts first: dedupe keys + the canary reference.
        with self._lock:
            for key, val in notes.items():
                if key.startswith('ckpt_done:') and val:
                    self._ckpt_done[key[len('ckpt_done:'):]] = True
        digest = notes.get(f'canary_digest:v{self.version}')
        if isinstance(digest, str) and self._canary_learned is None:
            self._canary_learned = digest
        launch_ops = {op['replica_id']: op for op in ops
                      if op['kind'] == 'launch'}
        drain_ops = {op['replica_id']: op for op in ops
                     if op['kind'] == 'drain'}
        teardown_ops = {op['replica_id']: op for op in ops
                        if op['kind'] == 'teardown'}
        # Id/port seeding: the counter must clear every id the service
        # EVER persisted (rows and in-flight ops both), or an adopted
        # fleet gets a duplicate replica id on the first scale-up.
        max_id = max(
            [r['replica_id'] for r in rows]
            + [op['replica_id'] or 0 for op in ops] + [0])
        with self._lock:
            self._next_replica_id = max(self._next_replica_id,
                                        max_id + 1)
            self._reserved_ports |= {r['port'] for r in rows
                                     if r.get('port')}
        for row in sorted(rows, key=lambda r: r['replica_id']):
            rid = row['replica_id']
            self._reconcile_row(
                row, launch_ops.pop(rid, None),
                drain_ops.pop(rid, None), teardown_ops.pop(rid, None),
                stats, now)
        # Launch ops with no row: the controller died between the
        # journal write and the row write — the cluster (if the launch
        # thread got that far) is a zombie with no owner.
        for rid in sorted(launch_ops):
            op = launch_ops[rid]
            cluster = ((op.get('payload') or {}).get('cluster_name')
                       or self._replica_cluster_name(rid))
            logger.warning(f'Reconcile: journaled launch of replica '
                           f'{rid} has no row; reaping zombie cluster '
                           f'{cluster}.')
            self._env.spawn(self._reap_zombie, cluster, op['op_id'],
                            None)
            stats['zombie_killed'] += 1
        # Stray drain/teardown ops with no row: the op's teardown
        # completed but the finish ack was lost in the crash — done.
        for op in (list(drain_ops.values())
                   + list(teardown_ops.values())):
            self._journal_finish(op['op_id'])
        for outcome, n in stats.items():
            if n:
                self._m_adopted[outcome].inc(n)
        if any(stats.values()):
            logger.info(
                'Reconciled persisted state: '
                + ', '.join(f'{k}={v}' for k, v in sorted(stats.items())
                            if v))
        return stats

    def _reconcile_row(self, row: Dict[str, object],
                       launch_op: Optional[Dict[str, object]],
                       drain_op: Optional[Dict[str, object]],
                       teardown_op: Optional[Dict[str, object]],
                       stats: Dict[str, int], now: float) -> None:
        rid = int(row['replica_id'])
        payload = dict((launch_op or {}).get('payload') or {})
        info = ReplicaInfo(
            rid, str(row['cluster_name']), int(row['version']),
            bool(row['is_spot']),
            int(row.get('port') or self.spec.replica_port),
            role=str(payload.get('role') or 'colocated'),
            gang_id=payload.get('gang_id'),
            gang_rank=int(payload.get('gang_rank') or 0),
            gang_world=int(payload.get('gang_world') or 1),
            created_time=now)
        info.url = row.get('url')
        # Adopted replicas are already serving traffic: re-warming
        # them would clobber a hot prefix cache with a stale blob.
        info.warmed = True
        status = row['status']
        if (teardown_op is not None
                or status == serve_state.ReplicaStatus.SHUTTING_DOWN
                or status.is_terminal()):
            # Replay the unacked teardown exactly once (the row alone
            # is evidence enough: a terminal status only persists on
            # the way into scale_down).
            info.status = (status if status.is_terminal()
                           else serve_state.ReplicaStatus.SHUTTING_DOWN)
            info.teardown_started = True
            with self._lock:
                self._replicas[rid] = info
            op_id = (teardown_op['op_id'] if teardown_op
                     else self._journal_start('teardown', info))
            for op in (drain_op, launch_op):
                if op:
                    self._journal_finish(op['op_id'])
            self._env.spawn(self._reap_zombie, info.cluster_name,
                            op_id, rid)
            stats['teardown_replayed'] += 1
            return
        if status in (serve_state.ReplicaStatus.PENDING,
                      serve_state.ReplicaStatus.PROVISIONING):
            # Crash mid-launch: the launch thread died with the old
            # controller. Whatever the cloud built is a zombie — tear
            # it down and let the autoscaler relaunch fresh.
            info.status = serve_state.ReplicaStatus.SHUTTING_DOWN
            info.teardown_started = True
            with self._lock:
                self._replicas[rid] = info
            op_id = (launch_op['op_id'] if launch_op
                     else self._journal_start('teardown', info))
            self._env.spawn(self._reap_zombie, info.cluster_name,
                            op_id, rid)
            stats['zombie_killed'] += 1
            return
        # STARTING / READY / NOT_READY / DRAINING: the replica claims
        # to exist — cluster existence is ground truth, then the probe.
        if self._env.cluster_gone(info.cluster_name):
            logger.info(f'Reconcile: replica {rid} lost while the '
                        'controller was down (preempted).')
            if info.is_spot:
                self._m_spot_preempt.inc()
            info.status = serve_state.ReplicaStatus.PREEMPTED
            info.teardown_started = True
            with self._lock:
                self._replicas[rid] = info
            self._persist(info)
            op_id = self._journal_start('teardown', info)
            for op in (drain_op, launch_op):
                if op:
                    self._journal_finish(op['op_id'])
            self._env.spawn(self._reap_zombie, info.cluster_name,
                            op_id, rid)
            stats['preempted'] += 1
            return
        if status == serve_state.ReplicaStatus.DRAINING or \
                drain_op is not None:
            # Resume the interrupted drain at its REMAINING deadline.
            deadline_at = (drain_op or {}).get('deadline_at')
            remaining = max(0.0, float(deadline_at) - now) \
                if deadline_at is not None else 0.0
            info.status = serve_state.ReplicaStatus.DRAINING
            info.drain_op = (drain_op['op_id'] if drain_op
                             else self._journal_start(
                                 'drain', info, deadline_at=now))
            with self._lock:
                self._replicas[rid] = info
            self._persist(info)
            if launch_op:
                self._journal_finish(launch_op['op_id'])
            logger.info(f'Reconcile: resuming drain of replica {rid} '
                        f'with {remaining:.1f}s of its deadline left.')
            self._env.spawn(self._drain_then_down, info, remaining)
            stats['drain_resumed'] += 1
            return
        if info.gang_rank > 0:
            # Follower ranks serve no HTTP: their health is the
            # leader's barrier + cluster existence (checked above).
            info.status = status
            with self._lock:
                self._replicas[rid] = info
            stats['adopted' if status ==
                  serve_state.ReplicaStatus.READY else
                  'probe_pending'] += 1
            return
        healthy = info.url is not None and self._probe_one(info)
        if healthy:
            # ORPHAN ADOPTION: the replica is alive and serving — own
            # it again without relaunching (relaunching a healthy
            # fleet is the scale-to-zero failure mode this exists to
            # prevent). Role/mesh/gang re-read from the live replica.
            self._adopt_probe(info)
            info.status = serve_state.ReplicaStatus.READY
            info.consecutive_failures = 0
            with self._lock:
                self._replicas[rid] = info
            self._persist(info)
            if launch_op:
                self._journal_finish(launch_op['op_id'])
            logger.info(f'Reconcile: adopted healthy replica {rid} at '
                        f'{info.url} (role={info.role}'
                        + (f', gang={info.gang_id}' if info.gang_id
                           else '') + ').')
            stats['adopted'] += 1
            return
        # Cluster up, app not answering (booting, or it died with the
        # controller): STARTING with a fresh grace window — the normal
        # probe escalation replaces it if it never comes back.
        info.status = serve_state.ReplicaStatus.STARTING
        info.first_probe_time = now
        info.launch_op = launch_op['op_id'] if launch_op else None
        with self._lock:
            self._replicas[rid] = info
        self._persist(info)
        stats['probe_pending'] += 1

    def _adopt_probe(self, info: ReplicaInfo) -> None:
        """Refine an adopted replica's descriptor from the replica
        itself: disaggregation role from ``/metrics?format=json``,
        gang identity from ``/gang/status``. Best-effort — the journal
        descriptor already seeded both."""
        assert info.url is not None
        try:
            payload = self._env.http_json(
                info.url + '/metrics?format=json', timeout=10)
            role = (payload.get('disagg') or {}).get('role') \
                if isinstance(payload, dict) else None
            if role:
                info.role = str(role)
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'Adopt probe (/metrics) of replica '
                         f'{info.replica_id} failed: '
                         f'{type(e).__name__}: {e}')
        try:
            payload = self._env.http_json(info.url + '/gang/status',
                                          timeout=10)
            if isinstance(payload, dict) and payload.get('gang_id'):
                info.gang_id = str(payload['gang_id'])
                info.gang_world = int(payload.get('world',
                                                  info.gang_world))
                info.gang_rank = int(payload.get('rank', 0))
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'Adopt probe (/gang/status) of replica '
                         f'{info.replica_id} failed: '
                         f'{type(e).__name__}: {e}')

    def _reap_zombie(self, cluster_name: str, op_id: Optional[int],
                     replica_id: Optional[int]) -> None:
        """Tear down a cluster the crashed controller left behind
        (zombie launch, unacked teardown) and clear its row + op."""
        try:
            self._env.down_cluster(cluster_name)
        except exceptions.ClusterDoesNotExist:
            pass
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'Reconcile teardown of {cluster_name} '
                           f'failed (it may leak): '
                           f'{type(e).__name__}: {e}')
        if replica_id is not None:
            self._untrack(replica_id)
        self._journal_finish(op_id)

    # ------------------------------------------------------------- queries
    def replicas(self) -> List[ReplicaInfo]:
        with self._lock:
            return list(self._replicas.values())

    def ready_urls(self) -> List[str]:
        """The routable endpoints: READY replicas' URLs — rank 0 only
        for gangs. A gang presents exactly ONE endpoint; follower
        URLs must never reach LB rotation or policy probe sweeps."""
        with self._lock:
            return [r.url for r in self._replicas.values()
                    if r.status == serve_state.ReplicaStatus.READY
                    and r.url is not None and r.gang_rank == 0]

    def replica_roles(self) -> Dict[str, str]:
        """url -> disaggregation role for every ROUTABLE replica with
        an address (gang followers excluded — they are not endpoints)
        — the LB sync payload (the phase-aware policy's cold-probe
        fallback)."""
        with self._lock:
            return {r.url: r.role for r in self._replicas.values()
                    if r.url is not None and r.gang_rank == 0}

    # ------------------------------------------- journaled persistence
    # THE sanctioned lifecycle-state writers (graftcheck GC120): every
    # replica-row write, journal op and controller note in this file
    # and controller.py goes through _persist/_untrack/_journal_start/
    # _journal_finish/_put_note/_del_note — nothing else may touch the
    # serve DB, so the journal can never drift from what the state
    # machines actually did.
    def _journal_start(self, kind: str, info: ReplicaInfo,
                       payload: Optional[Dict[str, object]] = None,
                       deadline_at: Optional[float] = None
                       ) -> Optional[int]:
        """Journal a multi-step lifecycle op BEFORE its first effect
        runs; returns the op id (None when the journal write failed —
        the op still runs, it just won't be resumable)."""
        body = dict(payload or {})
        body.setdefault('cluster_name', info.cluster_name)
        try:
            return self._env.journal_op_start(
                self.service_name, kind, info.replica_id,
                info.gang_id, body, deadline_at=deadline_at)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(
                f'journal write for {kind} of replica '
                f'{info.replica_id} failed ({type(e).__name__}: {e}); '
                'the op will not survive a controller restart')
            return None

    def _journal_finish(self, op_id: Optional[int]) -> None:
        if op_id is None:
            return
        try:
            self._env.journal_op_finish(self.service_name, op_id)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'journal finish of op {op_id} failed '
                           f'({type(e).__name__}: {e}); a restart may '
                           'replay it (replay is idempotent)')

    def _put_note(self, key: str, value: object) -> None:
        try:
            self._env.put_note(self.service_name, key, value)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'controller note {key!r} write failed '
                           f'({type(e).__name__}: {e})')

    def _del_note(self, key: str) -> None:
        try:
            self._env.del_note(self.service_name, key)
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'controller note {key!r} delete failed '
                         f'({type(e).__name__}: {e})')

    def _persist(self, info: ReplicaInfo) -> None:
        """Write the replica row — only while the replica is still
        tracked. ``_db_lock`` serializes this check+write against
        ``_untrack``'s pop+delete, so a concurrent scale_down can't
        leave a phantom row for an untracked replica; the hot ``_lock``
        is held only for the in-memory membership check, never across
        the sqlite write."""
        with self._db_lock:
            with self._lock:
                if self._replicas.get(info.replica_id) is not info:
                    return
            self._env.persist_replica(
                self.service_name, info.replica_id, info.cluster_name,
                info.status, info.url, info.version, info.is_spot,
                port=info.port)

    def _untrack(self, replica_id: int) -> None:
        """Atomically drop a replica from the in-memory table AND its
        DB row (the removal half of the ``_persist`` protocol). Also
        evicts the checkpoint-dedupe key once the replica — or the
        LAST member of its gang — is gone, so ``_ckpt_done`` stays
        bounded by the number of LIVE replicas/gangs no matter how
        many thousands churn through a long-lived manager."""
        dead_key: Optional[str] = None
        with self._db_lock:
            with self._lock:
                info = self._replicas.pop(replica_id, None)
                if info is not None:
                    if info.gang_id is None:
                        key = f'replica-{replica_id}'
                        if self._ckpt_done.pop(key, None) is not None:
                            dead_key = key
                    elif not any(r.gang_id == info.gang_id
                                 for r in self._replicas.values()):
                        if self._ckpt_done.pop(info.gang_id,
                                               None) is not None:
                            dead_key = info.gang_id
            self._env.remove_replica(self.service_name, replica_id)
        if dead_key is not None:
            # The persisted dedupe mirror is bounded the same way the
            # in-memory dict is: evicted with the (last member of the)
            # replica/gang it keyed.
            self._del_note(f'ckpt_done:{dead_key}')
