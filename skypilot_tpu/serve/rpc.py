"""Serve RPC: runs on the serve-controller cluster's head, driven by the
client via the command runner (same fixed-command-surface pattern as
:mod:`skypilot_tpu.jobs.rpc`; replaces reference ``ServeCodeGen``
``sky/serve/serve_utils.py:951``).

Ops: up (register service + submit the service process to the agent),
status, down, update.
"""
from __future__ import annotations

import json
import random
import sys
import time
from typing import Any, Dict

from skypilot_tpu import tpu_logging
from skypilot_tpu.agent import job_lib as agent_job_lib
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import wire
from skypilot_tpu.utils import common_utils

logger = tpu_logging.init_logger(__name__)

PAYLOAD_PREFIX = 'SKYTPU_RPC_PAYLOAD:'


def _ok(**kwargs) -> Dict[str, Any]:
    return {'ok': True, **kwargs}


def _reconcile_dead_services() -> None:
    """A service process that died uncleanly leaves its row non-terminal;
    map the agent job's terminal state back (reference: skylet's
    ``ServiceUpdateEvent``, ``sky/skylet/events.py:81``)."""
    services = [s for s in serve_state.get_services()
                if not s['status'].is_terminal()
                and s['status'] != serve_state.ServiceStatus.SHUTTING_DOWN]
    if not services:
        return
    agent_jobs = {j['name']: j for j in agent_job_lib.get_jobs()}
    for svc in services:
        job = agent_jobs.get(f'service-{svc["name"]}')
        if job is None:
            continue
        if job['status'].is_terminal() and \
                job['status'].value != 'SUCCEEDED':
            serve_state.set_service_status(
                svc['name'], serve_state.ServiceStatus.CONTROLLER_FAILED,
                failure_reason=(f'service process ended with '
                                f'{job["status"].value}'))


def _force_down(svc: Dict[str, Any]) -> None:
    """Clean up a service whose controller process is unreachable: stop
    the service agent job (so a hung controller stops relaunching
    replicas), tear down every replica cluster recorded in serve state,
    THEN drop the rows — never delete the only record of running
    clusters first."""
    from skypilot_tpu import core as sky_core
    name = svc['name']
    if svc.get('agent_job_id'):
        try:
            agent_job_lib.cancel_job(svc['agent_job_id'])
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'force-down {name}: cancel of controller '
                           f'job failed: {type(e).__name__}: {e}')
    for rep in serve_state.get_replicas(name):
        try:
            sky_core.down(rep['cluster_name'])
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'force-down {name}: teardown of '
                           f'{rep["cluster_name"]} failed (it may '
                           f'leak): {type(e).__name__}: {e}')
    serve_state.remove_service(name)


def handle(request: Dict[str, Any]) -> Dict[str, Any]:
    op = request.get('op')
    if op == 'up':
        name = request['service_name']
        task_config = request['task_config']
        # Allocate + record ports atomically under the serve-state lock:
        # a bind test alone can't see ports another 'up' just recorded
        # for a service process that hasn't started (and bound) yet.
        with serve_state.db_lock():
            taken = serve_state.allocated_ports()
            controller_port = common_utils.find_free_port(exclude=taken)
            lb_port = common_utils.find_free_port(
                start=controller_port + 1, exclude=taken)
            if not serve_state.add_service(name, task_config,
                                           controller_port, lb_port):
                return {'ok': False,
                        'error': f'Service {name!r} already exists.'}
        agent_job_id = agent_job_lib.add_job(
            name=f'service-{name}',
            username=request.get('username') or 'unknown',
            run_timestamp=request.get('run_timestamp') or
            common_utils.make_run_timestamp(),
            resources_str='serve-controller',
            spec={
                'run': (f'{sys.executable} -m skypilot_tpu.serve.service '
                        f'--service-name {name}'),
                'env': {},
                'workdir_target': None,
                # The service (controller+LB) process is control plane:
                # it must NOT get the accelerator-runtime env restored,
                # or it initializes the TPU runtime / claims the chip.
                'control_plane': True,
            })
        serve_state.set_service_agent_job(name, agent_job_id)
        agent_job_lib.schedule_step()
        return _ok(lb_port=lb_port, controller_port=controller_port,
                   agent_job_id=agent_job_id)
    if op == 'status':
        _reconcile_dead_services()
        services = []
        for svc in serve_state.get_services():
            replicas = serve_state.get_replicas(svc['name'])
            entry = serve_state.service_to_json(svc)
            entry['replicas'] = [serve_state.replica_to_json(r)
                                 for r in replicas]
            services.append(entry)
        names = request.get('service_names')
        if names:
            services = [s for s in services if s['name'] in names]
        return _ok(services=services)
    if op == 'update':
        name = request['service_name']
        svc = serve_state.get_service(name)
        if svc is None:
            return {'ok': False, 'error': f'Service {name!r} not found.'}
        from skypilot_tpu.serve.service_spec import SkyServiceSpec
        task_config = request['task_config']
        SkyServiceSpec.from_yaml_config(task_config['service'])  # validate
        version = serve_state.bump_service_version(name, task_config)
        if version is None:
            return {'ok': False, 'error': f'Service {name!r} not found.'}
        # The POST is only a NUDGE: the committed version is the source
        # of truth and the controller reconciles it every tick, so a
        # missed nudge must not be reported as a failed update (a retry
        # would double-bump the version).
        try:
            with wire.urlopen(
                    f'http://127.0.0.1:{svc["controller_port"]}'
                    '/controller/update', data=b'{}',
                    headers={'Content-Type': 'application/json'},
                    timeout=10):
                pass
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'update nudge to controller failed '
                         f'(reconciled next tick): '
                         f'{type(e).__name__}: {e}')
        return _ok(version=version)
    if op == 'down':
        name = request['service_name']
        svc = serve_state.get_service(name)
        if svc is None:
            return {'ok': False, 'error': f'Service {name!r} not found.'}
        # Ask the controller to terminate (it tears replicas down and
        # removes the service row); fall back to direct removal if the
        # controller is unreachable (e.g. it crashed).
        try:
            with wire.urlopen(
                    f'http://127.0.0.1:{svc["controller_port"]}'
                    '/controller/terminate', data=b'{}',
                    headers={'Content-Type': 'application/json'},
                    timeout=10):
                pass
            # Wait briefly for the row to disappear (terminate is
            # async). Jittered with mild backoff (graftcheck GC112):
            # many concurrent `serve down`s must not poll the DB in
            # lockstep.
            deadline = time.time() + float(request.get('timeout', 60))
            gap = 0.2
            while time.time() < deadline:
                if serve_state.get_service(name) is None:
                    break
                time.sleep(gap * (0.5 + random.random()))
                gap = min(gap * 1.5, 2.0)
            else:
                # Controller accepted the terminate but wedged mid-
                # teardown: escalate rather than reporting success with
                # replicas possibly still running.
                _force_down(svc)
        except Exception:  # pylint: disable=broad-except
            _force_down(svc)
        return _ok(terminated=True)
    raise ValueError(f'Unknown serve RPC op: {op!r}')


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == '--serve':
        # Persistent stdio channel (agent/channel.py): same wire
        # protocol as the agent RPC's --serve loop.
        from skypilot_tpu.agent import rpc as agent_rpc
        agent_rpc.serve(handle)
        return
    raw = sys.argv[1] if len(sys.argv) > 1 else sys.stdin.read()
    request = json.loads(raw)
    try:
        response = handle(request)
    except Exception as e:  # pylint: disable=broad-except
        response = {'ok': False, 'error': f'{type(e).__name__}: {e}'}
    print(PAYLOAD_PREFIX + json.dumps(response))


if __name__ == '__main__':
    main()
