"""Deterministic, seedable fault injection for the serve stack.

Replica crashes, probe timeouts, slow/partial HTTP responses,
engine-step stalls and spot-preemption signals are real failure modes
the serve layer must survive — and none of them used to be exercisable
in a test. This module turns each one into a *rule* that fires at an
exact, reproducible point (the Nth invocation of a named injection
site, or a seeded probability per invocation), so the chaos suite and
the bench's chaos block can replay the same failure on every run.

Configuration: the ``SKYTPU_FAULT_SPEC`` environment variable holds a
JSON spec (or ``@/path/to/spec.json``), e.g.::

    {"seed": 42,
     "rules": [
       {"kind": "replica_crash",  "site": "engine_step", "at": 120},
       {"kind": "probe_timeout",  "site": "probe", "every": 7},
       {"kind": "slow_response",  "site": "proxy", "prob": 0.05,
        "delay_s": 0.25},
       {"kind": "partial_response", "site": "proxy_stream",
        "at": 1, "after_events": 5},
       {"kind": "preempt_signal", "site": "preempt", "at": 3}]}

Each rule names a *kind* (what happens) and a *site* (where the hook
lives). Sites are the points where the serve stack already touches the
network or the hardware:

- ``engine_step`` — the model server's engine loop
  (``serve/server.py``), once per loop iteration with work. Kinds:
  ``engine_stall`` (sleep ``delay_s`` inside the loop), ``replica_crash``
  (raise :class:`InjectedFault` — the loop's ``_fatal`` path runs,
  readiness drops, every in-flight request fails over),
  ``wedged_step`` (the loop hangs inside the step region FOREVER —
  the wedge watchdog must detect it, flip readiness to degraded and
  fail in-flight work over), ``nan_logits`` (one live decoding
  request is evicted exactly as the device-side non-finite sentinel
  would evict it — a retryable per-request error while co-batched
  requests continue).
- ``probe`` — ``replica_managers._probe_one``. Kind ``probe_timeout``
  makes the readiness probe report failure (after ``delay_s``).
- ``preempt`` — ``replica_managers._check_preempted``. Kind
  ``preempt_signal`` reports the replica's cluster as preempted.
- ``preempt_warning`` — the probe sweep, once per swept replica. Kind
  ``preempt_signal`` here is the *advance warning* flavor: the replica
  is drained instead of hard-killed.
- ``spot_preemption`` — the probe sweep, once per swept SPOT replica
  only (on-demand replicas never count an invocation, so ``at``/
  ``every`` rules kill the Nth *spot* sweep deterministically — the
  chaos suite's and the bench's seeded spot-kill schedule). Kind
  ``preempt_signal`` routes through the full spot path: prefix-cache
  checkpoint, graceful drain, teardown, and autoscaler replacement/
  on-demand backfill.
- ``proxy`` — ``load_balancer._proxy`` before dispatch. Kinds:
  ``slow_response`` (sleep ``delay_s``), ``partial_response`` (the
  upstream connection "breaks" before the request is sent — exercises
  the retry path).
- ``proxy_stream`` — the LB's recoverable-stream forwarder, once per
  stream. Kind ``partial_response`` breaks the upstream stream after
  ``after_events`` token events — exercises mid-stream migration with
  a nonzero generated prefix, deterministically.
- ``handoff`` — a prefill replica's KV-handoff sender
  (``server.start_handoff``), once per attempted handoff. Kind
  ``partial_response`` makes the handoff POST "fail" before it is sent
  — exercises the colocated-fallback path a dead decode worker drives.
- ``gang_member_crash`` — a gang follower's sync loop
  (``serve/gang.py::GangFollower.run``), once per loop iteration.
  Kind ``replica_crash`` kills that rank's process mid-run — the
  leader loses its heartbeat, fails the WHOLE gang, and the LB's
  in-flight recovery resubmits to a surviving replica. Rules may be
  **rank-targeted**: ``{"rank": 1}`` fires only on rank 1 (counters
  advance per matching invocation regardless, so ``at``/``every``
  stay deterministic per site).
- ``gang_join_timeout`` — a gang follower's join path, once at
  startup. Kind ``replica_crash`` = the rank never joins (the
  leader's join deadline then fails the partial gang cleanly); kind
  ``engine_stall`` = the rank joins ``delay_s`` late.

Rule matching fields (all optional, combined with OR): ``at`` (fire on
exactly the Nth invocation of the site, 1-based), ``every`` (fire on
every Nth invocation), ``prob`` (fire with this probability per
invocation, drawn from the spec-seeded RNG — deterministic for a fixed
seed and invocation order). ``count`` caps total fires per rule
(default: unlimited; ``at`` naturally fires once).

Zero overhead when disabled: components resolve their injector ONCE at
construction (``get_injector()`` returns ``None`` when no spec is
configured) and every hook is behind an ``if self._faults is not
None`` — no parsing, no counters, no RNG on the hot path, and nothing
in the compute layer (``inference/``) references this module at all,
so the jaxpr-audit presets see byte-identical programs either way
(``tests/test_chaos.py::test_inference_layer_never_imports_faults``
pins that).

Telemetry: every fire increments
``skytpu_faults_injected_total{kind}``; :func:`register_metrics`
registers the full kind set up front so the series render as zeros
from the first scrape (the stable-schema contract).
"""
from __future__ import annotations

import dataclasses
import json
import os
import random
import threading
from typing import Any, Dict, List, Optional

from skypilot_tpu import telemetry
from skypilot_tpu import tpu_logging

logger = tpu_logging.init_logger(__name__)

FAULT_SPEC_ENV = 'SKYTPU_FAULT_SPEC'

# The stable label set of skytpu_faults_injected_total{kind}.
# 'zone_outage' and 'straggler' are the fleet-simulator storm kinds
# (serve/sim/): a zone outage kills every replica in a zone at once; a
# straggler degrades a replica's service rate without killing it.
# The gray-failure kinds (PR 13) model failures that do NOT announce
# themselves — the replica keeps answering HTTP while serving wrong
# bytes or nothing at all:
# - 'wedged_step': the engine loop hangs inside a step forever (a
#   stuck jitted call / dead accelerator) — the wedge watchdog must
#   flip readiness to degraded and fail in-flight work over.
# - 'nan_logits': one live request's logits go non-finite — the
#   on-device sentinel must evict exactly that request (retryable)
#   while its co-batched neighbors continue.
# - 'kv_corruption': one byte of an encoded KV container (handoff /
#   checkpoint) flips in transit — the CRC-checked decoder must refuse
#   it all-or-nothing (fallback-local / cold-boot, never wrong bytes).
# - 'byzantine_response': a replica answers the manager's known-digest
#   canary prompt WRONG — silent data corruption; the manager must
#   quarantine it before it serves a second wrong response.
# The controller-failure kinds (round 15) target the control plane
# itself:
# - 'controller_crash': the ServeController dies WITHOUT teardown —
#   replicas keep serving, the LB enters stale-while-revalidate, the
#   journal stays for the next boot.
# - 'controller_restart': a fresh controller boots with recover=True
#   and must reconcile the orphaned fleet (adopt, resume drains,
#   replay teardowns, reap zombies) instead of relaunching it.
FAULT_KINDS = ('replica_crash', 'probe_timeout', 'slow_response',
               'partial_response', 'engine_stall', 'preempt_signal',
               'zone_outage', 'straggler',
               'wedged_step', 'nan_logits', 'kv_corruption',
               'byzantine_response',
               'controller_crash', 'controller_restart', 'lb_crash')

# The stable label set of skytpu_gray_failures_total{kind}: detections
# by the gray-failure defense layer (watchdog fire, NaN eviction,
# checksum refusal, canary mismatch). Distinct from FAULT_KINDS —
# these count real DETECTIONS whether the cause was injected or not.
GRAY_FAILURE_KINDS = ('wedged_step', 'nan_logits', 'kv_corruption',
                      'byzantine_response')

# Injection sites (for spec validation; the hook call sites are the
# module docstring's list). The ``sim_*`` sites are fired by the fleet
# simulator's scenario clock (serve/sim/fleet.py), once per storm
# evaluation interval:
# - ``sim_storm`` — correlated spot-preemption storm: kind
#   ``preempt_signal`` with ``n`` kills the n most-recently-launched
#   SPOT replicas at once (the correlated-failure mode independent
#   per-replica rules can't express).
# - ``sim_zone_outage`` — kind ``zone_outage`` with ``zone`` kills
#   every replica placed in that zone in the same instant.
# - ``sim_straggler`` — kind ``straggler`` with ``factor`` multiplies
#   a replica's service time (slow HBM, noisy neighbor) without
#   killing it — the failure mode load-aware routing must absorb.
# - ``sim_gang_churn`` — kind ``replica_crash`` kills one gang
#   FOLLOWER cluster (rank picked by ``rank``, default 1) — the
#   one-dead-rank-dead-gang path at fleet scale.
# - ``kv_wire`` — fired wherever an encoded KV container leaves a
#   process (the prefill worker's handoff POST, the manager's
#   checkpoint fetch), once per transfer. Kind ``kv_corruption`` flips
#   one byte of the blob (offset ``n % len``) — the receiver's CRC
#   layer must refuse it.
# - ``canary`` — the manager's byzantine-detection canary probe, once
#   per canaried replica. Kind ``byzantine_response`` forces the
#   response digest to mismatch — the quarantine path runs exactly as
#   for a really-corrupt replica.
# - ``sim_gray`` — the fleet simulator's gray-failure storm site:
#   kinds ``wedged_step`` (replica accepts work, never finishes,
#   readiness degrades), ``nan_logits`` (evicts ``n`` in-flight
#   requests with retryable errors), ``byzantine_response`` (replica
#   answers canaries wrong until quarantined), ``kv_corruption``
#   (replica's next checkpoint export is garbage — its replacement
#   must boot cold, not byte-wrong).
# - ``controller_tick`` — the live controller's autoscaler loop, once
#   per iteration. Kind ``controller_crash`` stops the loop + HTTP API
#   dead (no teardown, no row writes) — the deterministic in-process
#   stand-in for a controller process crash.
# - ``sim_controller`` — the fleet simulator's storm clock. Kind
#   ``controller_crash`` halts the simulated controller's env (its
#   background tasks unwind, persistence stops landing);
#   ``controller_restart`` boots a fresh controller over the same
#   world with recover=True and reconciles.
# - ``sim_lb_crash`` — the fleet simulator's storm clock, horizontal
#   LB tier. Kind ``lb_crash`` kills one live load-balancer process
#   (highest index first): its policy state — probe caches, sticky
#   sessions, idempotency keys — is gone; the deterministic
#   client-side re-pick routes its sessions to the survivors, who must
#   lose ZERO requests (affinity re-forms from the replicas'
#   advertised digests).
FAULT_SITES = ('engine_step', 'probe', 'preempt', 'preempt_warning',
               'proxy', 'proxy_stream', 'http_response', 'handoff',
               'spot_preemption', 'gang_member_crash',
               'gang_join_timeout', 'sim_storm', 'sim_zone_outage',
               'sim_straggler', 'sim_gang_churn', 'kv_wire', 'canary',
               'sim_gray', 'controller_tick', 'sim_controller',
               'sim_lb_crash')

# Outcomes of skytpu_requests_migrated_total{outcome}: a migrated
# request either completed on a surviving replica or exhausted every
# replica and got the retryable error.
MIGRATION_OUTCOMES = ('completed', 'failed')


class InjectedFault(RuntimeError):
    """Raised by a ``replica_crash`` rule: the component's normal
    fatal-error path runs, exactly as a real crash would drive it."""


# Every key a rule dict may carry. Parse-time strictness matters more
# here than anywhere else in the repo: a chaos spec with a typo'd
# trigger field ("att": 3) would otherwise parse into a rule that
# SILENTLY never fires — the test then passes because nothing was
# injected, which is the exact false confidence a chaos suite exists
# to kill.
_RULE_FIELDS = ('kind', 'site', 'at', 'every', 'prob', 'count',
                'delay_s', 'after_events', 'rank', 'n', 'zone',
                'factor')
# Top-level spec keys.
_SPEC_FIELDS = ('seed', 'rules')


@dataclasses.dataclass
class FaultRule:
    kind: str
    site: str
    at: Optional[int] = None          # fire on the Nth invocation
    every: Optional[int] = None       # fire on every Nth invocation
    prob: float = 0.0                 # fire with seeded probability
    count: Optional[int] = None       # max total fires (None = no cap)
    delay_s: float = 0.25             # stall/slow-response duration
    after_events: int = 0             # proxy_stream: break after N events
    rank: Optional[int] = None        # gang sites: target this rank only
    n: int = 1                        # sim_storm: replicas per storm
    zone: Optional[str] = None        # sim_zone_outage: zone to kill
    factor: float = 4.0               # straggler: service-time multiplier
    fired: int = 0                    # bookkeeping (not a spec field)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> 'FaultRule':
        kind = d.get('kind')
        site = d.get('site')
        if kind not in FAULT_KINDS:
            raise ValueError(f'unknown fault kind {kind!r}; supported: '
                             f'{FAULT_KINDS}')
        if site not in FAULT_SITES:
            raise ValueError(f'unknown fault site {site!r}; supported: '
                             f'{FAULT_SITES}')
        unknown = sorted(set(d) - set(_RULE_FIELDS))
        if unknown:
            raise ValueError(
                f'unknown fault-rule field(s) {unknown} in rule '
                f'{{kind={kind!r}, site={site!r}}}; supported: '
                f'{_RULE_FIELDS} (a typo here would otherwise make '
                'the rule silently never fire)')
        def _opt_int(key: str) -> Optional[int]:
            # Presence-based (not truthiness): an explicit 0 must hit
            # the range validation below, not silently become "unset".
            return (int(d[key]) if key in d and d[key] is not None
                    else None)

        rule = cls(kind=kind, site=site,
                   at=_opt_int('at'),
                   every=_opt_int('every'),
                   prob=float(d.get('prob', 0.0)),
                   count=_opt_int('count'),
                   delay_s=float(d.get('delay_s', 0.25)),
                   after_events=int(d.get('after_events', 0)),
                   rank=(int(d['rank']) if 'rank' in d
                         and d['rank'] is not None else None),
                   n=max(1, int(d.get('n', 1))),
                   zone=(str(d['zone']) if d.get('zone') is not None
                         else None),
                   factor=float(d.get('factor', 4.0)))
        if rule.at is None and rule.every is None and rule.prob <= 0.0:
            raise ValueError(
                f'fault rule {{kind={kind!r}, site={site!r}}} has no '
                "trigger: set at least one of 'at' (Nth invocation), "
                "'every' (every Nth) or 'prob' (seeded probability) — "
                'a trigger-less rule never fires')
        if not 0.0 <= rule.prob <= 1.0:
            raise ValueError(f'prob must be in [0, 1], got {rule.prob}')
        if rule.at is not None and rule.at < 1:
            raise ValueError(f'at is 1-based, got {rule.at}')
        if rule.every is not None and rule.every < 1:
            raise ValueError(f'every must be >= 1, got {rule.every}')
        return rule


class FaultInjector:
    """Evaluates the fault spec at each instrumented site. Thread-safe:
    the LB's handler threads, the probe loop and the engine loop all
    fire through one injector. Deterministic for a fixed spec: site
    invocation counters drive ``at``/``every`` and a spec-seeded RNG
    drives ``prob``."""

    def __init__(self, spec: Dict[str, Any]):
        unknown = sorted(set(spec) - set(_SPEC_FIELDS))
        if unknown:
            raise ValueError(
                f'unknown fault-spec key(s) {unknown}; supported: '
                f'{_SPEC_FIELDS}')
        self.seed = int(spec.get('seed', 0))
        self._rng = random.Random(self.seed)
        self._rules: List[FaultRule] = [
            FaultRule.from_dict(r) for r in spec.get('rules', [])]
        self._site_counts: Dict[str, int] = {}
        self._lock = threading.Lock()
        reg = telemetry.get_registry()
        self._counters = {
            kind: reg.counter(
                'skytpu_faults_injected_total',
                'Faults injected by the deterministic fault-injection '
                'subsystem', kind=kind) for kind in FAULT_KINDS}

    def fire(self, site: str,
             rank: Optional[int] = None) -> Optional[FaultRule]:
        """Count one invocation of ``site``; return the first rule
        that fires there (and record it in telemetry), else None.
        ``rank`` (the gang sites) scopes rank-targeted rules: a rule
        with ``rank`` set only fires on that rank's invocations. An
        UNSCOPED invocation (``rank=None`` — e.g. the fleet
        simulator's storm clock, which picks the victim rank FROM the
        rule) matches every rule; only a caller that declares its own
        rank filters rank-targeted rules."""
        with self._lock:
            n = self._site_counts.get(site, 0) + 1
            self._site_counts[site] = n
            for rule in self._rules:
                if rule.site != site:
                    continue
                if (rule.rank is not None and rank is not None
                        and rank != rule.rank):
                    continue
                if rule.count is not None and rule.fired >= rule.count:
                    continue
                hit = ((rule.at is not None and n == rule.at)
                       or (rule.every is not None
                           and n % rule.every == 0)
                       or (rule.prob > 0.0
                           and self._rng.random() < rule.prob))
                if not hit:
                    continue
                rule.fired += 1
                self._counters[rule.kind].inc()
                logger.warning(
                    f'fault injected: kind={rule.kind} site={site} '
                    f'invocation={n} (fire #{rule.fired})')
                return rule
        return None

    def site_count(self, site: str) -> int:
        with self._lock:
            return self._site_counts.get(site, 0)


def parse_spec(raw: str) -> Dict[str, Any]:
    """Parse a fault spec: a JSON object, or ``@/path`` to a JSON
    file."""
    if raw.startswith('@'):
        with open(raw[1:], encoding='utf-8') as f:
            raw = f.read()
    spec = json.loads(raw)
    if not isinstance(spec, dict):
        raise ValueError('fault spec must be a JSON object')
    return spec


def make_injector(spec: Optional[Any] = None) -> Optional[FaultInjector]:
    """Build an injector from an explicit spec (dict or JSON string),
    falling back to ``SKYTPU_FAULT_SPEC``; None when neither is set —
    the hooks then cost one attribute check."""
    if spec is None:
        raw = os.environ.get(FAULT_SPEC_ENV)
        if not raw:
            return None
        spec = parse_spec(raw)
    elif isinstance(spec, str):
        spec = parse_spec(spec)
    return FaultInjector(spec)


def get_injector() -> Optional[FaultInjector]:
    """Alias of :func:`make_injector` with no explicit spec — the
    spelling env-configured components resolve at construction."""
    return make_injector(None)


def gray_failure_counter(kind: str) -> 'telemetry.Counter':
    """The gray-failure DETECTION counter for ``kind`` (one of
    :data:`GRAY_FAILURE_KINDS`) — ticked by the watchdog, the NaN
    eviction path, the checksum refusal paths and the canary
    quarantine, injected or real alike."""
    return telemetry.get_registry().counter(
        'skytpu_gray_failures_total',
        'Gray failures detected by the data-plane defense layer',
        kind=kind)


def corrupt_blob(blob: bytes, rule: 'FaultRule') -> bytes:
    """Deterministically flip one byte of an encoded container (the
    ``kv_corruption`` kind at the ``kv_wire`` site): byte at offset
    ``rule.n % len(blob)`` XOR 0xff — the receiver's CRC layer must
    turn this into a loud, retryable refusal."""
    if not blob:
        return blob
    off = rule.n % len(blob)
    out = bytearray(blob)
    out[off] ^= 0xff
    return bytes(out)


def register_metrics() -> None:
    """Register the robustness series up front — zeros from the first
    scrape whether or not any fault, drain or migration ever happens
    (the stable-schema contract ``tests/test_telemetry.py`` pins):

    - ``skytpu_faults_injected_total{kind}`` for every kind,
    - ``skytpu_gray_failures_total{kind}`` for every gray kind,
    - ``skytpu_requests_migrated_total{outcome}`` for every outcome,
    - ``skytpu_replica_drain_seconds`` (drain start -> idle),
    - ``skytpu_replica_recovery_seconds`` (failure detected -> stream
      resumed on a surviving replica).
    """
    reg = telemetry.get_registry()
    for kind in FAULT_KINDS:
        reg.counter('skytpu_faults_injected_total',
                    'Faults injected by the deterministic '
                    'fault-injection subsystem', kind=kind)
    for kind in GRAY_FAILURE_KINDS:
        gray_failure_counter(kind)
    for outcome in MIGRATION_OUTCOMES:
        reg.counter('skytpu_requests_migrated_total',
                    'In-flight requests migrated off a failed replica',
                    outcome=outcome)
    reg.histogram('skytpu_replica_drain_seconds',
                  'Graceful-drain duration: drain start to idle (s)',
                  buckets=telemetry.registry.DEFAULT_SECONDS_BUCKETS)
    reg.histogram('skytpu_replica_recovery_seconds',
                  'Mid-stream migration: replica failure detected to '
                  'stream resumed on a surviving replica (s)',
                  buckets=telemetry.registry.DEFAULT_SECONDS_BUCKETS)
