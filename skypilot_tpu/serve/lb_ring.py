"""Consistent-hash ring for the horizontal LB tier.

N load-balancer processes share the controller's sync feed; ownership
of session/idempotency keys is decided by THIS ring so every LB agrees
on which peer records a key — with no coordination beyond the shared
membership list the controller ships on every sync. Classic
consistent hashing (sha1 points, ``VNODES`` virtual nodes per member):

- **Stability**: a key's owner never changes while membership holds.
- **Bounded movement**: adding or removing one LB remaps only ~1/N of
  the key space — every other key keeps its owner, which is exactly
  what lets session affinity survive an LB crash or a scale-out
  (the surviving owners never saw their keys move).

Pure-Python, deterministic (sha1, no RNG, no wall clock), and lock-free
for readers: ``set_members`` swaps a fully-built ring atomically, so
``owner()`` can run on the request path with no lock at all."""
from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Optional, Tuple

# Virtual nodes per member: smooths ownership to within a few percent
# of uniform for small N (the LB tier is single digits, not hundreds).
VNODES = 64


def _point(data: str) -> int:
    return int.from_bytes(
        hashlib.sha1(data.encode()).digest()[:8], 'big')


class HashRing:
    """Members are ``{name: url}``; ``owner(key)`` returns the member
    name owning ``key`` (None on an empty ring). ``set_members``
    rebuilds and atomically swaps the sorted point table."""

    def __init__(self, vnodes: int = VNODES) -> None:
        self._vnodes = max(1, int(vnodes))
        # (sorted points, parallel member names, members dict) — one
        # tuple swap keeps readers consistent without a lock.
        self._table: Tuple[List[int], List[str], Dict[str, str]] = (
            [], [], {})

    def set_members(self, members: Optional[Dict[str, str]]) -> None:
        members = dict(members or {})
        pts: List[Tuple[int, str]] = []
        for name in members:
            for v in range(self._vnodes):
                pts.append((_point(f'{name}#{v}'), name))
        pts.sort()
        self._table = ([p for p, _ in pts], [n for _, n in pts],
                       members)

    @property
    def members(self) -> Dict[str, str]:
        return dict(self._table[2])

    def __len__(self) -> int:
        return len(self._table[2])

    def owner(self, key: str) -> Optional[str]:
        points, names, _ = self._table
        if not points:
            return None
        i = bisect.bisect_right(points, _point(key)) % len(points)
        return names[i]

    def owner_url(self, key: str) -> Tuple[Optional[str],
                                           Optional[str]]:
        """(owner name, owner url) for ``key`` — None, None when the
        ring is empty."""
        points, names, members = self._table
        if not points:
            return None, None
        i = bisect.bisect_right(points, _point(key)) % len(points)
        name = names[i]
        return name, members.get(name)
