"""Multi-host gang serving: a replica is a *gang* of processes that
launch, drain, checkpoint, and die together.

The source repo's core value proposition is the gang-scheduling
contract — stable node ranks, a coordinator address, env vars — that
any multi-host framework needs (PAPER.md). This module is that
contract for the serving stack: one replica = ``SKYTPU_WORLD``
processes sharing a ``SKYTPU_GANG_ID``. Rank 0 owns the HTTP front
end, the SLO scheduler, and the request stream; nonzero ranks run
:class:`GangFollower` loops that execute the same engine steps on
their shards of the serving mesh.

Launch-env contract (mirroring SKYTPU_TP/SKYTPU_DP):

- ``SKYTPU_COORDINATOR`` — rank 0's base URL (the gang bus: followers
  POST ``/gang/sync`` against it). Absent on rank 0 itself.
- ``SKYTPU_RANK`` / ``SKYTPU_WORLD`` — this process's rank and the
  gang size. ``WORLD <= 1`` disables everything (the single-process
  server is byte-for-byte the pre-gang server).
- ``SKYTPU_GANG_ID`` — shared identity; the replica manager's unit of
  management (drain/checkpoint/teardown are keyed by it).
- ``SKYTPU_GANG_JOIN_TIMEOUT`` — barrier bound: unless every rank has
  joined rank 0's coordinator within this window, the whole gang
  fails (rank 0 ``_fatal``s; stragglers self-terminate) and the
  controller replaces it as one unit. Every distributed join in this
  module carries a timeout — graftcheck GC116 enforces that.
- ``SKYTPU_GANG_HEARTBEAT`` / ``SKYTPU_GANG_HEARTBEAT_TIMEOUT`` —
  follower sync cadence and the loss bound: a follower that misses
  heartbeats past the bound kills the gang (rank 0 ``_fatal``s), and
  a follower that cannot reach rank 0 past the bound self-terminates.
  One dead rank means the whole gang is dead — never a half-alive
  replica serving garbage.

Execution model (SPMD lockstep): rank 0 appends every engine mutation
to an ordered *op log* — ``add`` (request admission), ``step`` (one
fused step), ``cancel``, ``release_hold``, ``flush`` (pipeline drain
before a checkpoint export), ``warmup`` (prefix-cache checkpoint
landing). Followers pull the log through ``/gang/sync`` (their
heartbeat) and apply it in order to their local engine, so every rank
executes the same jitted steps in the same order — on a TPU pod these
are the per-process shards of one ``jax.distributed`` program
(``parallel/mesh.py::initialize_gang_distributed``); on CPU (tests,
bench) each rank holds a full replica of the model (the ``replicated``
data plane) and the lockstep contract is verified *byte-exactly*:
followers report a digest of every finished request's token stream,
and any mismatch fails the gang fast (cause ``divergence``).

Consistency fan-out: drain and checkpoint are *commands* carried on
the same bus. A command pins the op-log index at which it was issued;
a follower acks it only once it has applied every op up to that index,
so "gang drained" / "gang checkpointed" mean every rank reached the
same state, not just rank 0.

Telemetry (registered at construction; zeros from the first scrape):
``skytpu_gang_size``, ``skytpu_gang_join_seconds``,
``skytpu_gang_failures_total{cause}``,
``skytpu_gang_heartbeat_age_seconds``.
"""
from __future__ import annotations

import base64
import dataclasses
import hashlib
import json
import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from skypilot_tpu import telemetry
from skypilot_tpu import tpu_logging
from skypilot_tpu.serve import wire

logger = tpu_logging.init_logger(__name__)

# Launch-env contract (mirrors SKYTPU_TP/SKYTPU_DP).
ENV_COORDINATOR = 'SKYTPU_COORDINATOR'
ENV_RANK = 'SKYTPU_RANK'
ENV_WORLD = 'SKYTPU_WORLD'
ENV_GANG_ID = 'SKYTPU_GANG_ID'
ENV_JOIN_TIMEOUT = 'SKYTPU_GANG_JOIN_TIMEOUT'
ENV_HEARTBEAT = 'SKYTPU_GANG_HEARTBEAT'
ENV_HEARTBEAT_TIMEOUT = 'SKYTPU_GANG_HEARTBEAT_TIMEOUT'

# The stable label set of skytpu_gang_failures_total{cause}.
FAILURE_CAUSES = ('join_timeout', 'heartbeat_lost', 'member_crash',
                  'divergence', 'coordinator_lost')

# Finished-request digests kept for cross-rank verification (bounded:
# a follower lagging further than this behind rank 0's finish stream
# is already heartbeat-dead).
_MAX_FINISHED_DIGESTS = 512
# Ops returned per sync (bounds one response; a fresh follower catches
# up over a few heartbeats).
_MAX_OPS_PER_SYNC = 256
# HTTP timeout for one sync POST (bounded — GC116: no unbounded joins).
_SYNC_HTTP_TIMEOUT = 10.0


def register_metrics() -> None:
    """Register the gang series up front — zeros from the first scrape
    whether or not this process ever joins a gang (the stable-schema
    contract ``tests/test_telemetry.py`` pins)."""
    reg = telemetry.get_registry()
    reg.gauge('skytpu_gang_size',
              'Processes in this replica\'s gang (0 = not a gang)')
    reg.histogram('skytpu_gang_join_seconds',
                  'Gang barrier: coordinator start to all ranks '
                  'joined (s)',
                  buckets=telemetry.registry.DEFAULT_SECONDS_BUCKETS)
    for cause in FAILURE_CAUSES:
        reg.counter('skytpu_gang_failures_total',
                    'Whole-gang failures by cause (one dead rank '
                    'fails the gang)', cause=cause)
    reg.gauge('skytpu_gang_heartbeat_age_seconds',
              'Oldest follower heartbeat age (0 until a gang forms)')


@dataclasses.dataclass(frozen=True)
class GangSpec:
    """One process's identity inside a gang. ``world <= 1`` means not
    a gang at all — every hook is a no-op and the server behaves
    exactly as before."""
    gang_id: str = ''
    rank: int = 0
    world: int = 1
    coordinator: Optional[str] = None
    join_timeout_s: float = 120.0
    heartbeat_s: float = 0.5
    heartbeat_timeout_s: float = 5.0

    @property
    def is_gang(self) -> bool:
        return self.world > 1

    @property
    def is_leader(self) -> bool:
        return self.rank == 0

    @classmethod
    def from_env(cls, *, rank: Optional[int] = None,
                 world: Optional[int] = None,
                 coordinator: Optional[str] = None,
                 gang_id: Optional[str] = None) -> 'GangSpec':
        """The launch-env contract, with explicit args (CLI flags)
        winning over the env — mirroring ``serving_spec_from_env``."""
        if rank is None:
            rank = int(os.environ.get(ENV_RANK, '0') or 0)
        if world is None:
            world = int(os.environ.get(ENV_WORLD, '1') or 1)
        if coordinator is None:
            coordinator = os.environ.get(ENV_COORDINATOR) or None
        if gang_id is None:
            gang_id = os.environ.get(ENV_GANG_ID, '') or ''
        heartbeat = float(os.environ.get(ENV_HEARTBEAT, '0.5') or 0.5)
        hb_timeout = float(os.environ.get(ENV_HEARTBEAT_TIMEOUT,
                                          str(10 * heartbeat))
                           or 10 * heartbeat)
        spec = cls(
            gang_id=gang_id, rank=rank, world=world,
            coordinator=coordinator,
            join_timeout_s=float(os.environ.get(ENV_JOIN_TIMEOUT, '120')
                                 or 120),
            heartbeat_s=heartbeat,
            heartbeat_timeout_s=hb_timeout)
        if spec.is_gang and spec.rank > 0 and not spec.coordinator:
            raise ValueError(
                f'gang rank {spec.rank} of {spec.world} needs '
                f'{ENV_COORDINATOR} (rank 0\'s base URL)')
        if not 0 <= spec.rank < max(1, spec.world):
            raise ValueError(f'gang rank {spec.rank} out of range for '
                             f'world {spec.world}')
        return spec


def finished_digest(prompt: List[int], output: List[int]) -> str:
    """Digest of one finished request's full token stream — the unit
    of cross-rank byte-identity verification. Prompt is included so a
    rid collision across diverged admission orders cannot alias."""
    h = hashlib.sha256()
    h.update(json.dumps([list(map(int, prompt)),
                         list(map(int, output))]).encode())
    return h.hexdigest()[:16]


class GangDigest:
    """Per-rank accumulator of finished-request digests. Event *order*
    across requests is pipeline-timing dependent (the paged engine's
    eager drain), so lockstep is verified at request granularity — the
    full output stream of every finished request must match across
    ranks, which is timing-insensitive and byte-exact."""

    def __init__(self) -> None:
        self._streams: Dict[int, List[int]] = {}
        self.finished: 'Dict[int, str]' = {}

    def update(self, engine: Any,
               events: List[Tuple[int, int, bool]]) -> None:
        for rid, token, fin in events:
            self._streams.setdefault(rid, []).append(int(token))
            if fin:
                req = None
                if engine is not None:
                    # Finished request objects carry the authoritative
                    # (trimmed) output; fall back to the raw stream.
                    req = (engine._finished.get(rid)
                           if hasattr(engine, '_finished') else None)
                out = (list(req.output) if req is not None
                       else self._streams[rid])
                prompt = list(req.prompt) if req is not None else []
                self.finished[rid] = finished_digest(prompt, out)
                self._streams.pop(rid, None)
                while len(self.finished) > _MAX_FINISHED_DIGESTS:
                    self.finished.pop(next(iter(self.finished)))

    def drop(self, rid: int) -> None:
        """A cancelled request never finishes — forget its stream."""
        self._streams.pop(rid, None)


class GangFailure(RuntimeError):
    """A whole-gang failure: one dead/late/diverged rank fails the
    gang. ``cause`` is one of :data:`FAILURE_CAUSES`."""

    def __init__(self, cause: str, detail: str):
        super().__init__(detail)
        self.cause = cause


class _Member:
    __slots__ = ('rank', 'joined_at', 'last_seen', 'applied', 'acked')

    def __init__(self, rank: int, now: float):
        self.rank = rank
        self.joined_at = now
        self.last_seen = now
        self.applied = 0
        self.acked: set = set()


class GangCoordinator:
    """Rank 0's side of the gang bus: member registry + barrier, op
    log, command fan-out, heartbeat ages, divergence detection. Lives
    inside the leader's model-server process; followers reach it via
    ``POST /gang/sync`` on the same HTTP front end. Thread-safe (HTTP
    handler threads, the engine loop, and the monitor thread all
    touch it)."""

    def __init__(self, spec: GangSpec, *,
                 clock: Callable[[], float] = time.monotonic):
        self.spec = spec
        self._clock = clock
        self._lock = threading.Lock()
        self._started = clock()
        self._members: Dict[int, _Member] = {}
        self._joined = threading.Event()
        self._join_seconds: Optional[float] = None
        # Op log: ops[i] has global index _base + i. Trimmed up to the
        # slowest member's applied index.
        self._ops: List[Dict[str, Any]] = []
        self._base = 0
        self._commands: List[Dict[str, Any]] = []
        self._next_cid = 1
        self._acked_events: Dict[int, threading.Event] = {}
        self._failed: Optional[str] = None
        self._diverged: Optional[str] = None
        self.digest = GangDigest()
        reg = telemetry.get_registry()
        register_metrics()
        reg.gauge('skytpu_gang_size',
                  'Processes in this replica\'s gang '
                  '(0 = not a gang)').set(spec.world)
        self._h_join = reg.histogram('skytpu_gang_join_seconds')
        self._g_hb_age = reg.gauge('skytpu_gang_heartbeat_age_seconds')
        self._c_fail = {
            c: reg.counter('skytpu_gang_failures_total', cause=c)
            for c in FAILURE_CAUSES}

    # ------------------------------------------------------------ barrier
    @property
    def all_joined(self) -> bool:
        return self._joined.is_set()

    def barrier_wait(self, timeout: float) -> bool:
        """Bounded barrier wait (GC116: every distributed join carries
        a timeout)."""
        return self._joined.wait(timeout=timeout)

    @property
    def join_seconds(self) -> Optional[float]:
        return self._join_seconds

    # --------------------------------------------------------------- ops
    def append_op(self, op: Dict[str, Any]) -> int:
        """Append one engine op to the log; returns its global index.
        Called from the leader's engine loop (under the engine lock —
        this only takes the gang lock briefly)."""
        with self._lock:
            self._ops.append(op)
            return self._base + len(self._ops)

    @property
    def ops_len(self) -> int:
        with self._lock:
            return self._base + len(self._ops)

    # ----------------------------------------------------------- commands
    def command(self, kind: str,
                payload: Optional[Dict[str, Any]] = None) -> int:
        """Fan a control command (drain / checkpoint / shutdown /
        warmup) out to every follower; returns its command id. The
        command pins the CURRENT op-log index: followers ack only once
        they have applied every op up to it."""
        with self._lock:
            cid = self._next_cid
            self._next_cid += 1
            self._commands.append({
                'id': cid, 'kind': kind, 'payload': payload or {},
                'log_index': self._base + len(self._ops)})
            self._acked_events[cid] = threading.Event()
        return cid

    def acked(self, cid: int) -> bool:
        """True once every follower rank acked command ``cid``."""
        with self._lock:
            ranks = set(range(1, self.spec.world))
            return all(r in self._members
                       and cid in self._members[r].acked
                       for r in ranks)

    def wait_acked(self, cid: int, timeout: float) -> bool:
        """Bounded wait for all-rank ack (GC116)."""
        ev = self._acked_events.get(cid)
        if ev is None:
            return self.acked(cid)
        ev.wait(timeout=timeout)
        return self.acked(cid)

    # --------------------------------------------------------------- sync
    def sync(self, rank: int, applied: int, acks: List[int],
             finished: Dict[str, str]) -> Dict[str, Any]:
        """One follower heartbeat: register/refresh the member, verify
        its finished-request digests against rank 0's, hand back the
        op-log tail and pending commands. The response for a failed
        gang carries ``failed`` — the follower self-terminates."""
        now = self._clock()
        if not 1 <= rank < self.spec.world:
            return {'failed': f'rank {rank} out of range for world '
                              f'{self.spec.world}'}
        with self._lock:
            if self._failed is not None:
                return {'failed': self._failed}
            m = self._members.get(rank)
            if m is None:
                m = self._members[rank] = _Member(rank, now)
                logger.info(f'gang {self.spec.gang_id or "?"}: rank '
                            f'{rank} joined '
                            f'({len(self._members) + 1}/'
                            f'{self.spec.world})')
                if len(self._members) == self.spec.world - 1:
                    self._join_seconds = now - self._started
                    self._h_join.observe(self._join_seconds)
                    self._joined.set()
                    logger.info(
                        f'gang {self.spec.gang_id or "?"}: barrier '
                        f'complete in {self._join_seconds:.2f}s')
            m.last_seen = now
            m.applied = max(m.applied, int(applied))
            for cid in acks:
                cid = int(cid)
                m.acked.add(cid)
                ev = self._acked_events.get(cid)
                if (ev is not None
                        and len(self._members) == self.spec.world - 1
                        and all(cid in mm.acked
                                for mm in self._members.values())):
                    ev.set()
            # Cross-rank byte-identity: every finished request's token
            # stream must match rank 0's. A mismatch is the
            # half-alive-replica failure mode — fail the gang fast.
            for rid_s, dg in (finished or {}).items():
                mine = self.digest.finished.get(int(rid_s))
                if mine is not None and mine != dg:
                    self._diverged = (
                        f'rank {rank} diverged on request {rid_s}: '
                        f'{dg} != leader {mine}')
                    return {'failed': self._diverged}
            start = max(0, int(applied) - self._base)
            ops = self._ops[start:start + _MAX_OPS_PER_SYNC]
            # The response base MUST be captured before the trim:
            # _trim_locked advances self._base, and a base inflated by
            # the just-dropped prefix would make the follower skip
            # exactly that many ops — silent divergence.
            base = self._base + start
            cmds = [c for c in self._commands
                    if c['id'] not in m.acked]
            self._trim_locked()
            return {'ok': True, 'ops': ops, 'base': base,
                    'commands': cmds,
                    'heartbeat_s': self.spec.heartbeat_s}

    def _trim_locked(self) -> None:
        if len(self._members) < self.spec.world - 1:
            return
        low = min(m.applied for m in self._members.values())
        drop = min(max(0, low - self._base), len(self._ops))
        if drop:
            del self._ops[:drop]
            self._base += drop

    # ------------------------------------------------------------ failure
    def fail(self, error: str) -> None:
        """Mark the gang failed: every subsequent follower sync gets
        the error and self-terminates (the leader's ``_fatal`` calls
        this — one dead rank, whole gang dead)."""
        with self._lock:
            if self._failed is None:
                self._failed = error

    @property
    def failed(self) -> Optional[str]:
        with self._lock:
            return self._failed

    def count_failure(self, cause: str) -> None:
        self._c_fail[cause if cause in FAILURE_CAUSES
                     else 'member_crash'].inc()

    def check(self) -> None:
        """Health check, called by the leader's monitor thread: raises
        :class:`GangFailure` on join-deadline expiry, follower
        heartbeat loss, or digest divergence. Also refreshes the
        heartbeat-age gauge."""
        now = self._clock()
        with self._lock:
            diverged = self._diverged
            joined = self._joined.is_set()
            elapsed = now - self._started
            ages = {r: now - m.last_seen
                    for r, m in self._members.items()}
        if diverged:
            raise GangFailure('divergence', diverged)
        self._g_hb_age.set(max(ages.values()) if ages else 0.0)
        if not joined:
            if elapsed > self.spec.join_timeout_s:
                missing = sorted(set(range(1, self.spec.world))
                                 - set(ages))
                raise GangFailure(
                    'join_timeout',
                    f'gang join timeout after {elapsed:.1f}s '
                    f'(> {self.spec.join_timeout_s:.1f}s); missing '
                    f'rank(s) {missing}')
            return
        for rank, age in ages.items():
            if age > self.spec.heartbeat_timeout_s:
                raise GangFailure(
                    'heartbeat_lost',
                    f'gang member rank {rank} heartbeat lost '
                    f'({age:.1f}s > '
                    f'{self.spec.heartbeat_timeout_s:.1f}s)')

    # ------------------------------------------------------------- status
    def status(self) -> Dict[str, Any]:
        now = self._clock()
        with self._lock:
            return {
                'gang_id': self.spec.gang_id,
                'world': self.spec.world,
                'barrier': self._joined.is_set(),
                'join_seconds': self._join_seconds,
                'ops': self._base + len(self._ops),
                'failed': self._failed,
                'members': {
                    str(r): {'applied': m.applied,
                             'heartbeat_age_s': round(
                                 now - m.last_seen, 3)}
                    for r, m in self._members.items()},
            }


def apply_warmup(engine: Any, blob: bytes) -> int:
    """Land a checkpoint container into an engine's prefix cache —
    the follower-side twin of the server's ``warm_from_checkpoint``
    (same entry order, same capacity-stop semantics, so every rank's
    cache state stays identical). Returns rows warmed."""
    from skypilot_tpu.inference import kv_transfer
    entries = kv_transfer.decode_checkpoint(blob)
    warmed = 0
    for entry in entries:
        try:
            warmed += engine.warm_prefix(entry)
        except kv_transfer.HandoffCapacityError:
            break
    return warmed


class GangFollower:
    """A nonzero rank's whole life: join rank 0's coordinator within
    the join timeout, then heartbeat/sync — applying the leader's op
    log to the local engine so every rank executes the same jitted
    steps in the same order — until shutdown, coordinator loss, or an
    injected crash. ``run()`` returns the exit cause; the process
    wrapper exits with it. Self-termination on coordinator loss is
    the follower half of the one-dead-all-dead contract."""

    def __init__(self, spec: GangSpec, engine: Any, *,
                 faults: Optional[Any] = None,
                 stop: Optional[threading.Event] = None,
                 rng: Optional[random.Random] = None):
        if not spec.is_gang or spec.rank == 0:
            raise ValueError('GangFollower needs a nonzero gang rank')
        self.spec = spec
        self.engine = engine
        self._faults = faults
        self._stop = stop or threading.Event()
        self._rng = rng or random.Random()
        self._applied = 0
        self._acks: List[int] = []
        self._done_acks: set = set()
        self.digest = GangDigest()
        self._new_finished: Dict[int, str] = {}
        self.exit_cause: Optional[str] = None
        self.ops_applied = 0

    # ------------------------------------------------------------ protocol
    def _sync_once(self) -> Optional[Dict[str, Any]]:
        payload = {
            'rank': self.spec.rank,
            'gang_id': self.spec.gang_id,
            'applied': self._applied,
            'acks': list(self._acks),
            'finished': {str(r): d
                         for r, d in self._new_finished.items()},
        }
        out = wire.post_json(self.spec.coordinator + '/gang/sync',
                             payload, timeout=_SYNC_HTTP_TIMEOUT)
        self._new_finished.clear()
        self._acks.clear()        # delivered; coordinator recorded them
        return out

    def _note_events(self, events) -> None:
        before = set(self.digest.finished)
        self.digest.update(self.engine, events)
        for rid in set(self.digest.finished) - before:
            self._new_finished[rid] = self.digest.finished[rid]
        for rid, _tok, fin in events:
            if fin:
                self.engine.pop_finished(rid)

    def _apply_op(self, op: Dict[str, Any]) -> None:
        k = op.get('k')
        if k == 'add':
            rid = self.engine.add_request(
                op['prompt'], max_new_tokens=op['max_new_tokens'],
                temperature=op.get('temperature', 0.0),
                top_k=op.get('top_k', 0), top_p=op.get('top_p', 1.0),
                eos_id=op.get('eos_id'), stop=op.get('stop'),
                adapter=op.get('adapter'), tenant=op.get('tenant'),
                grammar=op.get('grammar'),
                priority=op.get('priority', 0))
            if rid != op['rid']:
                raise GangFailure(
                    'divergence',
                    f'rank {self.spec.rank} assigned request id {rid} '
                    f'where leader assigned {op["rid"]} — engine call '
                    'streams diverged')
            if op.get('trace_id') and hasattr(self.engine,
                                              'adopt_trace_context'):
                # Follower spans join the leader's fleet trace: the
                # op log is a replicated hop, tagged as such.
                self.engine.adopt_trace_context(
                    rid, trace_id=op['trace_id'],
                    parent_span='gang_oplog:rank0')
        elif k == 'step':
            self._note_events(self.engine.follower_step(
                op.get('h', 1), prepared=op.get('prepared', False)))
        elif k == 'cancel':
            self.engine.cancel(op['rid'])
            self.digest.drop(op['rid'])
        elif k == 'release_hold':
            self.engine.release_hold(op['rid'])
        elif k == 'flush':
            self._note_events(self.engine.drain_pipeline())
        elif k == 'warmup':
            apply_warmup(self.engine,
                         base64.b64decode(op['blob']))
        else:
            logger.warning(f'gang rank {self.spec.rank}: unknown op '
                           f'{k!r} skipped')
        self.ops_applied += 1

    def _handle_commands(self, cmds: List[Dict[str, Any]]) -> bool:
        """Ack every command whose pinned op-log index we have reached
        (drain/checkpoint consistency: the ack MEANS 'my engine state
        includes everything up to your index'). Returns True on a
        shutdown command."""
        shutdown = False
        for c in cmds:
            cid = int(c['id'])
            if cid in self._done_acks:
                continue
            if self._applied < int(c.get('log_index', 0)):
                continue          # not caught up yet; ack next sync
            if c.get('kind') == 'shutdown':
                shutdown = True
            self._done_acks.add(cid)
            if cid not in self._acks:
                self._acks.append(cid)
        return shutdown

    # ----------------------------------------------------------------- run
    def run(self) -> str:
        """The follower loop. Returns the exit cause:
        ``shutdown`` (clean), ``coordinator_lost`` (rank 0 gone past
        the heartbeat timeout — self-terminate), ``coordinator_failed``
        (rank 0 reported the gang failed), ``join_timeout`` (never got
        through the barrier window), or ``stopped`` (local stop event).
        An injected ``gang_member_crash`` raises — the process dies
        exactly as a real crash would."""
        from skypilot_tpu.serve import faults as faults_lib
        spec = self.spec
        if self._faults is not None:
            # Deterministic partial-gang failures: a rank that never
            # joins (replica_crash) or joins late (engine_stall) at
            # the gang_join_timeout site, rank-targeted.
            rule = self._faults.fire('gang_join_timeout',
                                    rank=spec.rank)
            if rule is not None:
                if rule.kind == 'replica_crash':
                    logger.warning(
                        f'gang rank {spec.rank}: injected join '
                        'failure; never joining')
                    return self._exit('join_timeout')
                if rule.kind == 'engine_stall':
                    time.sleep(rule.delay_s)
        join_deadline = time.monotonic() + spec.join_timeout_s
        joined = False
        last_ok = time.monotonic()
        while not self._stop.is_set():
            if self._faults is not None:
                rule = self._faults.fire('gang_member_crash',
                                        rank=spec.rank)
                if rule is not None and rule.kind == 'replica_crash':
                    raise faults_lib.InjectedFault(
                        f'injected gang_member_crash on rank '
                        f'{spec.rank}')
            try:
                resp = self._sync_once()
            except Exception as e:  # pylint: disable=broad-except
                now = time.monotonic()
                logger.debug(f'gang rank {spec.rank}: sync failed '
                             f'({type(e).__name__}: {e})')
                if not joined and now > join_deadline:
                    return self._exit('join_timeout')
                if joined and now - last_ok > spec.heartbeat_timeout_s:
                    logger.warning(
                        f'gang rank {spec.rank}: coordinator lost '
                        f'({now - last_ok:.1f}s > '
                        f'{spec.heartbeat_timeout_s:.1f}s); '
                        'self-terminating (one dead rank = dead gang)')
                    return self._exit('coordinator_lost')
                self._sleep()
                continue
            last_ok = time.monotonic()
            if resp is None or resp.get('failed'):
                logger.warning(
                    f'gang rank {spec.rank}: coordinator reports gang '
                    f'failed ({(resp or {}).get("failed")}); '
                    'self-terminating')
                return self._exit('coordinator_failed')
            joined = True
            ops = resp.get('ops') or []
            base = int(resp.get('base', self._applied))
            for i, op in enumerate(ops):
                if base + i < self._applied:
                    continue          # already applied (resync overlap)
                self._apply_op(op)
                self._applied = base + i + 1
            if self._handle_commands(resp.get('commands') or []):
                # Flush the final acks so rank 0 sees the shutdown ack.
                try:
                    self._sync_once()
                except Exception:  # pylint: disable=broad-except
                    logger.debug('gang final ack sync failed '
                                 '(coordinator already gone)')
                return self._exit('shutdown')
            if not ops:
                self._sleep()
        return self._exit('stopped')

    def _exit(self, cause: str) -> str:
        self.exit_cause = cause
        return cause

    def _sleep(self) -> None:
        # Jittered idle poll (graftcheck GC112: no fixed-sleep loops).
        self._stop.wait(timeout=self.spec.heartbeat_s
                        * (0.5 + self._rng.random()))
