"""Replica-side model server: HTTP front end on the in-tree
InferenceEngine (the piece the reference delegates to vLLM/JetStream
recipes — here it ships in-tree, SURVEY §7 step 8).

Endpoints:
- ``GET /readiness`` — 200 once the engine has compiled its first step
  (the serve readiness-probe target).
- ``POST /generate`` — ``{"prompt": [ids...], "max_new_tokens": N,
  "temperature": t, "top_k": k, "top_p": p, "stop": [...]}`` →
  ``{"tokens": [...], "ttft_ms": ...}``. ``stop`` entries are strings
  (tokenized with the model tokenizer) or token-id lists; generation
  ends when the output ends with any entry, which is trimmed.
- ``GET /metrics`` — queue depth / active slots / counters.

One background thread drives ``engine.step()`` continuously (the engine
core is synchronous); HTTP handler threads enqueue requests and wait on
per-request events. Run on every replica slice via the service task's
``run`` command:  ``python -m skypilot_tpu.serve.server --model llama3-1b``.
"""
from __future__ import annotations

import argparse
import http.server
import json
import os
import threading
from typing import Any, Dict, Optional

from skypilot_tpu import tpu_logging

logger = tpu_logging.init_logger(__name__)


class ModelServer:

    def __init__(self, cfg_name: str = 'tiny', *, max_batch: int = 8,
                 max_seq: int = 1024, port: int = 8081,
                 model_path: Optional[str] = None,
                 quantize: Optional[str] = None,
                 kv_cache: str = 'slot', page_size: int = 128):
        self.cfg_name = cfg_name
        self.model_path = model_path  # HF checkpoint dir (real weights)
        self.quantize = quantize      # 'int8' => int8 weights + KV cache
        self.kv_cache = kv_cache      # 'slot' | 'paged' (prefix caching)
        self.page_size = page_size    # paged-cache page granularity
        self.max_batch = max_batch
        self.max_seq = max_seq
        self.port = port
        self.engine = None            # set once loaded
        self.tokenizer = None         # set once loaded
        self._error: Optional[str] = None   # fatal engine failure
        self._ready = threading.Event()
        self._work = threading.Event()
        self._lock = threading.Lock()  # engine mutation
        self._finished_events: Dict[int, threading.Event] = {}
        # Streaming requests: per-request token queues fed by the engine
        # loop; (token, finished) tuples, (None, True) on engine death.
        self._stream_queues: Dict[int, 'queue.Queue'] = {}
        self._requests_served = 0
        self._requests_aborted = 0
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None

    # ------------------------------------------------------------- engine
    def _load_engine(self) -> None:
        from skypilot_tpu.inference.engine import InferenceEngine
        from skypilot_tpu.inference.paged import PagedInferenceEngine
        from skypilot_tpu.models import configs
        from skypilot_tpu.models.tokenizer import load_tokenizer
        engine_cls = (PagedInferenceEngine if self.kv_cache == 'paged'
                      else InferenceEngine)
        extra = ({'page_size': self.page_size}
                 if self.kv_cache == 'paged' else {})
        if self.model_path:
            # Real weights: HF checkpoint dir (config.json + safetensors
            # [+ tokenizer.json]) — the reference serves such checkpoints
            # through vLLM/JetStream (llm/llama-3/llama3.yaml:109).
            engine = engine_cls.from_pretrained(
                self.model_path, max_batch=self.max_batch,
                max_seq=self.max_seq, quantize=self.quantize, **extra)
            self.cfg_name = engine.cfg.name
        else:
            cfg = configs.get_config(self.cfg_name)
            engine = engine_cls(cfg, max_batch=self.max_batch,
                                max_seq=self.max_seq,
                                quantize=self.quantize, **extra)
        self.tokenizer = load_tokenizer(
            self.model_path, model_vocab_size=engine.cfg.vocab_size)
        # Warmup: compile prefill+decode before declaring readiness.
        engine.add_request([1, 2, 3], max_new_tokens=2)
        engine.run_to_completion(horizon=4)
        self.engine = engine
        self._ready.set()
        logger.info(f'Engine ready: model={self.cfg_name} '
                    f'max_batch={self.max_batch} max_seq={self.max_seq}')

    def _engine_loop(self) -> None:
        try:
            self._load_engine()
        except Exception as e:  # pylint: disable=broad-except
            self._fatal(e)
            return
        while True:
            try:
                self._work.wait()
                with self._lock:
                    has_work = self.engine.has_work()
                    if has_work:
                        events = self.engine.step(horizon=8)
                    else:
                        self._work.clear()
                        events = []
                for rid, token, finished in events:
                    sq = self._stream_queues.get(rid)
                    if sq is not None:
                        sq.put((token, finished))
                    if finished and rid in self._finished_events:
                        self._finished_events[rid].set()
            except Exception as e:  # pylint: disable=broad-except
                self._fatal(e)
                return

    def _fatal(self, e: Exception) -> None:
        """Engine died: drop readiness (the serve probe then pulls this
        replica out of rotation) and wake every waiting request so handler
        threads return errors instead of blocking forever."""
        logger.exception(f'Engine loop died: {type(e).__name__}: {e}')
        self._error = f'{type(e).__name__}: {e}'
        self._ready.clear()
        with self._lock:
            for ev in self._finished_events.values():
                ev.set()
            for sq in self._stream_queues.values():
                sq.put((None, True))

    def submit(self, prompt, max_new_tokens: int, temperature: float,
               top_k: int, eos_id: Optional[int], top_p: float = 1.0,
               stop=None) -> Dict[str, Any]:
        if self._error is not None:
            raise RuntimeError(f'engine failed: {self._error}')
        done = threading.Event()
        with self._lock:
            rid = self.engine.add_request(
                prompt, max_new_tokens=max_new_tokens,
                temperature=temperature, top_k=top_k, top_p=top_p,
                eos_id=eos_id, stop=stop)
            self._finished_events[rid] = done
            # _fatal wakes events under this same lock; if the engine died
            # between the check above and this registration, the event
            # would never be set — re-check while still holding the lock.
            if self._error is not None:
                done.set()
        self._work.set()
        done.wait()
        if self._error is not None:   # woken by _fatal, not completion
            raise RuntimeError(f'engine failed: {self._error}')
        with self._lock:
            req = self.engine.pop_finished(rid)
            del self._finished_events[rid]
            self._requests_served += 1
        return {
            'request_id': rid,
            'tokens': req.output,
            'ttft_ms': req.ttft_ms,
        }

    def submit_stream(self, prompt, max_new_tokens: int, temperature: float,
                      top_k: int, eos_id: Optional[int],
                      top_p: float = 1.0, stop=None):
        """Register a streaming request; returns (request_id, token
        queue). The engine loop feeds (token, finished) tuples; callers
        must call finish_stream(rid) when done."""
        import queue as queue_mod
        if self._error is not None:
            raise RuntimeError(f'engine failed: {self._error}')
        sq: 'queue_mod.Queue' = queue_mod.Queue()
        with self._lock:
            rid = self.engine.add_request(
                prompt, max_new_tokens=max_new_tokens,
                temperature=temperature, top_k=top_k, top_p=top_p,
                eos_id=eos_id, stop=stop)
            self._stream_queues[rid] = sq
            if self._error is not None:
                sq.put((None, True))
        self._work.set()
        return rid, sq

    def finish_stream(self, rid: int) -> None:
        """Deregister a streaming request. If the client disconnected
        mid-stream (the request is not finished), cancel it engine-side
        so the slot stops generating tokens nobody will read — and count
        it as aborted, not served."""
        with self._lock:
            self._stream_queues.pop(rid, None)
            if self.engine.pop_finished(rid) is not None:
                self._requests_served += 1
            elif self.engine.cancel(rid):
                self._requests_aborted += 1

    # --------------------------------------------------------------- HTTP
    def _make_handler(server):  # noqa: N805
        class Handler(http.server.BaseHTTPRequestHandler):

            def log_message(self, *args):
                del args

            def _json(self, code: int, payload: Dict[str, Any]) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path == '/readiness':
                    if server._error is not None:
                        self._json(503, {'status': 'failed',
                                         'error': server._error})
                    elif server._ready.is_set():
                        self._json(200, {'status': 'ready',
                                         'model': server.cfg_name})
                    else:
                        self._json(503, {'status': 'loading'})
                elif self.path == '/metrics':
                    eng = server.engine
                    self._json(200, {
                        'requests_served': server._requests_served,
                        'requests_aborted': server._requests_aborted,
                        'active_slots': eng.num_active if eng else 0,
                        'max_batch': server.max_batch,
                    })
                else:
                    self._json(404, {'error': f'no route {self.path}'})

            def _stream_generate(self, prompt, is_text, kwargs) -> None:
                """Server-sent events: one ``data:`` line per token as
                the engine emits it, a final ``done`` event with the
                full sequence. Token streaming end to end — the LB
                passes text/event-stream responses through unbuffered."""
                tok = server.tokenizer
                rid, sq = server.submit_stream(prompt, **kwargs)
                tokens = []
                # Everything after registration lives under the finally:
                # even a client that drops before the headers flush must
                # reach finish_stream, or the slot decodes to
                # max_new_tokens for nobody.
                try:
                    self.send_response(200)
                    self.send_header('Content-Type', 'text/event-stream')
                    self.send_header('Cache-Control', 'no-cache')
                    self.send_header('Connection', 'close')
                    self.end_headers()
                    self._stream_loop(rid, sq, tokens, is_text, tok)
                except (BrokenPipeError, ConnectionResetError):
                    pass    # client vanished; finish_stream cancels
                finally:
                    server.finish_stream(rid)
                    self.close_connection = True

            def _stream_loop(self, rid, sq, tokens, is_text, tok) -> None:
                while True:
                    token, finished = sq.get(timeout=300)
                    if token is None:       # engine died
                        self.wfile.write(
                            b'data: {"error": "engine failed"}\n\n')
                        break
                    tokens.append(int(token))
                    event = {'token': int(token)}
                    if is_text:
                        event['text'] = tok.decode([int(token)])
                    self.wfile.write(
                        f'data: {json.dumps(event)}\n\n'.encode())
                    self.wfile.flush()
                    if finished:
                        done = {'done': True, 'request_id': rid,
                                'tokens': tokens}
                        if is_text:
                            done['text'] = tok.decode(tokens)
                        self.wfile.write(
                            f'data: {json.dumps(done)}\n\n'.encode())
                        break

            def do_POST(self):  # noqa: N802
                if self.path != '/generate':
                    self._json(404, {'error': f'no route {self.path}'})
                    return
                if not server._ready.is_set():
                    self._json(503, {'status': 'loading'})
                    return
                length = int(self.headers.get('Content-Length', 0))
                try:
                    payload = json.loads(self.rfile.read(length))
                    prompt = payload['prompt']
                    tok = server.tokenizer
                    is_text = isinstance(prompt, str)
                    if is_text:
                        prompt = tok.encode(prompt)
                    eos_id = payload.get('eos_id')
                    if eos_id is None and is_text:
                        eos_id = tok.eos_id
                    stop = payload.get('stop')
                    if stop is not None:
                        if isinstance(stop, (str, bytes)):
                            stop = [stop]
                        # bos=False: generated output never contains
                        # BOS, so a BOS-prefixed stop would never match.
                        stop = [tok.encode(s, bos=False)
                                if isinstance(s, str)
                                else [int(t) for t in s] for s in stop]
                    kwargs = dict(
                        max_new_tokens=int(
                            payload.get('max_new_tokens', 128)),
                        temperature=float(payload.get('temperature', 0.0)),
                        top_k=int(payload.get('top_k', 0)),
                        top_p=float(payload.get('top_p', 1.0)),
                        stop=stop,
                        eos_id=eos_id)
                    if payload.get('stream'):
                        self._stream_generate(prompt, is_text, kwargs)
                        return
                    result = server.submit(prompt, **kwargs)
                    if is_text:
                        result['text'] = tok.decode(result['tokens'])
                    self._json(200, result)
                except (KeyError, ValueError, TypeError,
                        json.JSONDecodeError) as e:
                    self._json(400, {'error': f'{type(e).__name__}: {e}'})
                except RuntimeError as e:
                    self._json(500, {'error': str(e)})

        return Handler

    def start(self, block: bool = True) -> None:
        threading.Thread(target=self._engine_loop, daemon=True).start()
        handler = self._make_handler()
        self._httpd = http.server.ThreadingHTTPServer(('0.0.0.0', self.port),
                                                      handler)
        logger.info(f'Model server listening on :{self.port}')
        if block:
            self._httpd.serve_forever()
        else:
            threading.Thread(target=self._httpd.serve_forever,
                             daemon=True).start()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='tiny',
                        help='preset config name (random weights)')
    parser.add_argument('--model-path', default=None,
                        help='HF checkpoint dir (real weights + tokenizer)')
    parser.add_argument('--quantize', default=None, choices=['int8'],
                        help='int8 weights + KV cache (2x decode)')
    parser.add_argument('--kv-cache', default='slot',
                        choices=['slot', 'paged'],
                        help='paged = shared page pool with prefix '
                             'caching + chunked prefill')
    parser.add_argument('--page-size', type=int, default=128,
                        help='paged-cache page granularity (tokens); '
                             'larger pages DMA more efficiently, '
                             'smaller pages cache prefixes finer')
    parser.add_argument('--max-batch', type=int, default=8)
    parser.add_argument('--max-seq', type=int, default=1024)
    parser.add_argument('--port', type=int,
                        default=int(os.environ.get('SKYTPU_REPLICA_PORT',
                                                   '8081')))
    args = parser.parse_args()
    if args.kv_cache != 'paged' and args.page_size != 128:
        parser.error('--page-size only applies with --kv-cache paged')
    server = ModelServer(args.model, max_batch=args.max_batch,
                         max_seq=args.max_seq, port=args.port,
                         model_path=args.model_path,
                         quantize=args.quantize,
                         kv_cache=args.kv_cache,
                         page_size=args.page_size)
    server.start(block=True)


if __name__ == '__main__':
    main()
