"""Replica-side model server: HTTP front end on the in-tree
InferenceEngine (the piece the reference delegates to vLLM/JetStream
recipes — here it ships in-tree, SURVEY §7 step 8).

Endpoints:
- ``GET /readiness`` — 200 once the engine has compiled its first step
  (the serve readiness-probe target).
- ``POST /generate`` — ``{"prompt": [ids...], "max_new_tokens": N,
  "temperature": t, "top_k": k, "top_p": p, "stop": [...],
  "slo_tier": "latency"|"throughput"}`` →
  ``{"tokens": [...], "ttft_ms": ...}``. ``stop`` entries are strings
  (tokenized with the model tokenizer) or token-id lists; generation
  ends when the output ends with any entry, which is trimmed.
- ``GET /metrics`` — the process telemetry registry in Prometheus text
  exposition format (TTFT/TPOT/queue-wait histograms — aggregate AND
  per SLO tier, engine step-phase timings, speculation gauges,
  scheduler queue/shed series —
  ``skytpu_sched_queue_tokens{tier=...}``,
  ``skytpu_sched_shed_total{tier,reason}`` — and KV pool
  capacity/pressure).
  ``GET /metrics?format=json`` keeps the PR-3 stable-schema JSON gauge
  block for existing scrapers (every key always present, zeros never
  omitted; the scheduler adds a ``sched.tiers`` block with the same
  guarantee).
- ``GET /debug/requests`` — the bounded ring of completed request
  timelines (queue → prefill chunks → decode → spec rounds), newest
  first; ``?limit=N`` caps the count.

Every number comes from the single telemetry registry
(``skypilot_tpu.telemetry``) — the server keeps no private metrics
dicts; the rolling TTFT/TPOT/queue-wait median/p90 ride the registry
histograms' bounded windows (ONE windowed-quantile implementation).

Request flow (round 6): handler threads submit into the
:class:`skypilot_tpu.serve.scheduler.RequestScheduler` — the SLO-aware
admission core that owns per-tier bounded queues, the priority +
shortest-remaining-work admission order, load shedding (HTTP 429 with
a telemetry-derived ``Retry-After`` instead of silent queue growth)
and the per-request outboxes handlers stream from. One background
thread drives ``engine.step()`` continuously (the engine core is
synchronous); each iteration it tops the engine up from the scheduler
and routes the step's token events to the outboxes — the step never
blocks on a slow client. Run on every replica slice via the service
task's ``run`` command:
``python -m skypilot_tpu.serve.server --model llama3-1b``.
"""
from __future__ import annotations

import argparse
import collections
import http.server
import json
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

from skypilot_tpu import telemetry
from skypilot_tpu import tpu_logging
from skypilot_tpu.inference import kv_transfer
from skypilot_tpu.models.tokenizer import sanitize_text
from skypilot_tpu.serve import disagg as disagg_lib
from skypilot_tpu.serve import faults as faults_lib
from skypilot_tpu.serve import gang as gang_lib
from skypilot_tpu.serve import scheduler as scheduler_lib
from skypilot_tpu.serve import wire
from skypilot_tpu.telemetry import tracing

logger = tpu_logging.init_logger(__name__)


def build_engine(cfg_name: str, *, max_batch: int, max_seq: int,
                 model_path: Optional[str] = None,
                 quantize: Optional[str] = None,
                 kv_cache: str = 'paged',
                 kv_cache_dtype: Optional[str] = None,
                 page_size: Optional[int] = None,
                 decode_impl: Optional[str] = None,
                 prefill_w8a8: bool = False,
                 prefill_chunk_tokens: Optional[int] = None,
                 decode_priority_ratio: Optional[float] = None,
                 decode_steps_per_call: Optional[int] = None,
                 speculate_k: int = 0,
                 adapter_slots: int = 0,
                 adapter_dir: Optional[str] = None,
                 adapter_rank: int = 8,
                 tp: int = 1, dp: int = 1,
                 gang: Optional['gang_lib.GangSpec'] = None):
    """Construct AND warm one inference engine — the single engine
    recipe every gang rank shares. Followers must build a
    byte-identical engine to rank 0's (same config, same warmup
    request, so request-id counters, prefix-cache state, and compiled
    programs all align) — which is why this lives outside the
    ModelServer: rank 0's ``_load_engine`` and the rank-N follower
    entry both call exactly this."""
    from skypilot_tpu.inference.engine import InferenceEngine
    from skypilot_tpu.inference.paged import PagedInferenceEngine
    from skypilot_tpu.models import configs
    if gang is not None and gang.is_gang:
        # Multi-host data plane: on a pod-capable backend the gang
        # shares one jax.distributed program (the mesh then spans all
        # processes); on CPU (tests/bench) each rank keeps a full
        # model replica and lockstep is digest-verified by the gang
        # bus (the 'replicated' plane).
        import jax
        if jax.default_backend() == 'tpu' and gang.coordinator:
            from skypilot_tpu.parallel import mesh as mesh_lib
            mesh_lib.initialize_gang_distributed(
                gang.coordinator, gang.rank, gang.world,
                timeout_s=gang.join_timeout_s)
    engine_cls = (PagedInferenceEngine if kv_cache == 'paged'
                  else InferenceEngine)
    extra = {}
    if tp * dp > 1:
        from skypilot_tpu.parallel import mesh as mesh_lib
        extra['mesh'] = mesh_lib.serving_mesh(tp, dp)
    if kv_cache == 'paged' and page_size is not None:
        extra['page_size'] = page_size
    if kv_cache == 'paged' and decode_impl is not None:
        extra['decode_impl'] = decode_impl
    if prefill_chunk_tokens is not None:
        extra['prefill_chunk_tokens'] = prefill_chunk_tokens
    if decode_priority_ratio is not None:
        extra['decode_priority_ratio'] = decode_priority_ratio
    if decode_steps_per_call is not None:
        extra['decode_steps_per_call'] = decode_steps_per_call
    if kv_cache_dtype is not None:
        extra['kv_cache_dtype'] = kv_cache_dtype
    extra['prefill_w8a8'] = prefill_w8a8
    extra['speculate_k'] = speculate_k
    if adapter_slots:
        # Multi-tenant LoRA bank: slots rows of rank-r factors live in
        # params (re-uploaded on load/evict, never recompiled).
        extra['adapter_slots'] = adapter_slots
        extra['adapter_dir'] = adapter_dir
        extra['adapter_rank'] = adapter_rank
    if model_path:
        engine = engine_cls.from_pretrained(
            model_path, max_batch=max_batch, max_seq=max_seq,
            quantize=quantize, **extra)
    else:
        cfg = configs.get_config(cfg_name)
        engine = engine_cls(cfg, max_batch=max_batch, max_seq=max_seq,
                            quantize=quantize, **extra)
    # Warmup: compile prefill+decode before declaring readiness. Part
    # of the shared recipe — it advances the request-id counter and
    # (paged) registers prefix pages, so a follower that skipped it
    # would diverge on its very first replayed op.
    engine.add_request([1, 2, 3], max_new_tokens=2)
    engine.run_to_completion(horizon=4)
    return engine


class ModelServer:

    def __init__(self, cfg_name: str = 'tiny', *, max_batch: int = 8,
                 max_seq: int = 1024, port: int = 8081,
                 model_path: Optional[str] = None,
                 quantize: Optional[str] = None,
                 tp: Optional[int] = None,
                 dp: Optional[int] = None,
                 kv_cache: str = 'paged',
                 kv_cache_dtype: Optional[str] = None,
                 page_size: Optional[int] = None,
                 decode_impl: Optional[str] = None,
                 prefill_w8a8: bool = False,
                 prefill_chunk_tokens: Optional[int] = None,
                 decode_priority_ratio: Optional[float] = None,
                 decode_steps_per_call: Optional[int] = None,
                 speculate_k: int = 0,
                 adapter_slots: int = 0,
                 adapter_dir: Optional[str] = None,
                 adapter_rank: int = 8,
                 slo_tier_default: str = 'latency',
                 max_queue_tokens: Optional[int] = None,
                 latency_admit_frac: float = 0.7,
                 drain_deadline_s: float = 30.0,
                 fault_spec: Optional[Any] = None,
                 role: Optional[str] = None,
                 handoff_targets: Optional[List[str]] = None,
                 checkpoint_path: Optional[str] = None,
                 gang: Optional['gang_lib.GangSpec'] = None,
                 step_watchdog_s: Optional[float] = None,
                 watchdog_clock: Optional[Any] = None,
                 nan_alarm_threshold: Optional[int] = None):
        self.cfg_name = cfg_name
        self.model_path = model_path  # HF checkpoint dir (real weights)
        self.quantize = quantize      # 'int8' | 'int4' weights
        # Serving mesh shape: explicit args win, else the controller's
        # adaptive-TP placement env (SKYTPU_TP/SKYTPU_DP), else 1x1.
        # Resolved HERE (not at engine load) so the mesh gauges and the
        # JSON mesh block report the configured shape from the very
        # first scrape — the LB's replica view must not see a replica
        # flap from 1x1 to tp=2 mid-boot.
        from skypilot_tpu.parallel import mesh as mesh_lib
        self._mesh_spec = mesh_lib.serving_spec_from_env(tp=tp, dp=dp)
        self.tp = self._mesh_spec.tp
        self.dp = self._mesh_spec.dp
        self.kv_cache = kv_cache      # 'slot' | 'paged' (prefix caching)
        # KV storage dtype ('bf16' | 'int8'); None follows --quantize.
        # Decoupled: int8 KV over bf16 weights halves the dominant
        # decode HBM stream (and ~doubles pool capacity) on its own.
        self.kv_cache_dtype = kv_cache_dtype
        self.page_size = page_size    # paged granularity (None = auto)
        # Paged decode attention path ('gather' | 'pallas' |
        # 'cross_layer'); None = the engine's 'auto' pick. cross_layer
        # walks each slot's pages ONCE per step for all layers.
        self.decode_impl = decode_impl
        self.prefill_w8a8 = prefill_w8a8  # int8 activations on prefill
        # Chunked-prefill scheduler knobs (None = engine defaults):
        # chunk width and the decode share of the interleaved token
        # budget while prompts are mid-prefill.
        self.prefill_chunk_tokens = prefill_chunk_tokens
        self.decode_priority_ratio = decode_priority_ratio
        # Multi-step on-device decode: pin every decode call at
        # exactly k fused steps (dispatch/readback/sampling host work
        # amortizes k x). None = the loop's adaptive 8/32 horizon.
        self.decode_steps_per_call = decode_steps_per_call
        # Speculative decoding: n-gram/prompt-lookup proposer + batched
        # on-device verify (0 = off). Greedy outputs are identical to
        # vanilla decode; sampling keeps the output distribution.
        self.speculate_k = speculate_k or 0
        # Multi-tenant LoRA: bank capacity (0 = off), checkpoint dir
        # for on-demand load-by-name, and the bank's fixed rank.
        self.adapter_slots = adapter_slots
        self.adapter_dir = adapter_dir
        self.adapter_rank = adapter_rank
        self.max_batch = max_batch
        self.max_seq = max_seq
        self.port = port
        self.engine = None            # set once loaded
        self.tokenizer = None         # set once loaded
        self._error: Optional[str] = None   # fatal engine failure
        self._ready = threading.Event()
        self._work = threading.Event()
        self._lock = threading.Lock()  # engine mutation
        # The SLO-aware admission/scheduling core: per-tier bounded
        # queues, priority + shortest-remaining-work admission, load
        # shedding (429 + Retry-After), per-request outbox streaming.
        # Constructed UP FRONT so its /metrics schema is stable from
        # the first scrape; the engine binds once loaded.
        self.sched = scheduler_lib.RequestScheduler(
            self._lock, default_tier=slo_tier_default,
            max_queue_tokens=max_queue_tokens,
            latency_admit_frac=latency_admit_frac,
            wake=self._work.set)
        # Telemetry: every counter/gauge/histogram lives in the process
        # registry (rendered at /metrics in Prometheus format and as
        # the stable-schema JSON at /metrics?format=json). The request
        # latency histograms keep a bounded window for exact rolling
        # median/p90 — the one windowed-quantile implementation shared
        # by TTFT, TPOT, and queue-wait (the serve autoscaler and
        # operators watch these to see the scheduler holding its
        # latency SLO; bounded so a long-lived replica's quantiles
        # reflect CURRENT traffic, not its lifetime).
        reg = telemetry.get_registry()
        self._reg = reg
        self._m_served = reg.counter(
            'skytpu_requests_served_total',
            'Requests completed and returned to a client')
        self._m_aborted = reg.counter(
            'skytpu_requests_aborted_total',
            'Requests cancelled mid-stream (client disconnect)')
        self._h_ttft = reg.histogram(
            'skytpu_request_ttft_ms', 'Time to first token (ms)')
        self._h_tpot = reg.histogram(
            'skytpu_request_tpot_ms',
            'Mean time per output token after the first (ms)')
        self._h_queue_wait = reg.histogram(
            'skytpu_request_queue_wait_ms',
            'Time from submit to slot assignment (ms)')
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        self._stopping = False
        self._engine_thread: Optional[threading.Thread] = None
        # Fault injection (serve/faults.py): resolved ONCE here from
        # the explicit spec or SKYTPU_FAULT_SPEC; None (the default)
        # keeps the hooks at a single attribute check — zero overhead
        # on the engine loop, nothing in the compute layer.
        self._faults = faults_lib.make_injector(fault_spec)
        # Robustness series (faults/migrations/drain/recovery/gray)
        # register up front so they render as zeros from first scrape.
        faults_lib.register_metrics()
        # Gray-failure defense (round 13). Wedge watchdog: a
        # clock-injectable per-step deadline on the engine loop. The
        # loop arms a monotonic stamp before entering the step region
        # and clears it after; a stamp older than ``step_watchdog_s``
        # means a step is WEDGED (stuck jitted call, dead accelerator,
        # deadlocked readback) while the HTTP front end still answers
        # — the classic gray failure. The watchdog thread then flips
        # /readiness to a degraded 503 (the manager's probe machinery
        # fails the replica over) and fails in-flight requests with
        # retryable errors (the LB's existing in-flight recovery
        # resubmits them to surviving replicas). ``watchdog_clock`` is
        # injectable so tests drive virtual time; 0 disables.
        self.step_watchdog_s = (
            float(step_watchdog_s) if step_watchdog_s is not None
            else float(os.environ.get('SKYTPU_STEP_WATCHDOG_S', '120')))
        self._wd_clock = watchdog_clock or time.monotonic
        self._wd_lock = threading.Lock()
        self._step_started: Optional[float] = None
        # Degraded (gray-failed but process-alive) state: set by the
        # watchdog and the NaN-storm alarm. Readiness reports 503
        # status='degraded'; new submits get a retryable 503.
        self._degraded: Optional[str] = None
        # NaN blast-radius escalation: single poisoned requests are
        # evicted per-request (the device sentinel), but this many
        # total hits mean the REPLICA is sick (bad HBM, corrupted
        # weights) — escalate to the replica-level degraded alarm.
        self.nan_alarm_threshold = (
            int(nan_alarm_threshold) if nan_alarm_threshold is not None
            else int(os.environ.get('SKYTPU_NAN_ALARM', '8')))
        self._nan_seen = 0
        self._nan_evict_pending = False    # latched nan_logits inject
        self._g_wd_age = reg.gauge(
            'skytpu_engine_step_watchdog_age_seconds',
            'Age of the engine step currently in flight (0 when the '
            'loop is between steps); sustained growth = wedged step')
        self._h_drain = reg.histogram(
            'skytpu_replica_drain_seconds',
            'Graceful-drain duration: drain start to idle (s)',
            buckets=telemetry.registry.DEFAULT_SECONDS_BUCKETS)
        # Graceful drain: all drain attributes are written under
        # _drain_lock (begin_drain is idempotent and may race the
        # monitor thread and /drain handlers).
        self.drain_deadline_s = float(drain_deadline_s)
        self._drain_lock = threading.Lock()
        self._drain_started: Optional[float] = None
        self._drain_deadline: Optional[float] = None
        self._drained = threading.Event()
        # Idempotent request keys: a bounded map of completed
        # request_key -> result, so a retried request (the LB's hedged
        # retry / a client replay after a mid-stream migration) gets
        # the SAME answer instead of a second execution.
        self._keys_lock = threading.Lock()
        self._completed_keys: 'collections.OrderedDict[str, Dict]' = \
            collections.OrderedDict()
        self._max_completed_keys = 512
        # Disaggregated serving (serve/disagg.py): this replica's phase
        # role (flag > SKYTPU_ROLE launch env > colocated) plus the
        # static handoff peers a prefill worker may stream finished
        # KV to when no router supplied an X-Handoff-Target header.
        # The disagg telemetry series register at construction so the
        # /metrics schema is stable from the first scrape — zeros on
        # every outcome/direction whether or not a handoff ever runs.
        self.role = disagg_lib.resolve_role(role)
        self.handoff_targets = disagg_lib.static_targets(handoff_targets)
        disagg_lib.register_metrics(self.role)
        # Multi-host gang serving (serve/gang.py): explicit spec wins,
        # else the SKYTPU_COORDINATOR/SKYTPU_RANK/SKYTPU_WORLD launch
        # env; world <= 1 (the default) keeps every hook a None check.
        # Rank 0 hosts the GangCoordinator on this same HTTP front end
        # (/gang/sync); nonzero ranks never construct a ModelServer at
        # all (main() dispatches them to a GangFollower). Gang series
        # register unconditionally so the /metrics schema is stable
        # from the first scrape on gang and non-gang replicas alike.
        gang_lib.register_metrics()
        self.gang = gang if gang is not None else \
            gang_lib.GangSpec.from_env()
        self._gang: Optional[gang_lib.GangCoordinator] = None
        self._gang_boot_blob: Optional[bytes] = None
        self._gang_drain_cid: Optional[int] = None
        if self.gang.is_gang:
            if not self.gang.is_leader:
                raise ValueError(
                    'ModelServer is the rank-0 gang process; run '
                    'nonzero ranks through the follower entry '
                    '(python -m skypilot_tpu.serve.server '
                    '--gang-rank N)')
            if self.role != 'colocated':
                logger.warning(
                    f'gang serving forces role=colocated (was '
                    f'{self.role}): disaggregated handoff in/out of a '
                    'gang would desync follower engine state')
                self.role = 'colocated'
            self._gang = gang_lib.GangCoordinator(self.gang)
            # Op-log hooks: every admission/cancel the scheduler
            # performs is recorded (under the engine lock) so
            # followers replay the identical engine call stream.
            self.sched.on_admit = self._gang_record_admit
            self.sched.on_cancel = self._gang_record_cancel
        # Spot resilience: prefix-cache checkpoint/warmup. On a
        # preemption warning the controller POSTs /checkpoint (the
        # response is the SKCK container of hot prefix chains +
        # in-flight request snapshots) and lands it into the
        # replacement via /kv/warmup BEFORE it enters rotation. With a
        # local checkpoint_path (flag > SKYTPU_KV_CHECKPOINT_PATH
        # env), the server additionally persists a checkpoint when a
        # drain begins and warms itself from the file at boot — the
        # standalone / bench restart path. The warmup histogram is
        # registered at construction (stable schema); this process
        # observes it only for boot-from-file warmups — HTTP warmups
        # are observed end-to-end by the controller-side manager.
        self.checkpoint_path = (checkpoint_path
                                or os.environ.get(
                                    'SKYTPU_KV_CHECKPOINT_PATH')
                                or None)
        self._h_warmup = reg.histogram(
            'skytpu_prefix_warmup_seconds',
            'Prefix-cache warmup of a recovered replica: checkpoint '
            'POST to landed (s)',
            buckets=telemetry.registry.DEFAULT_SECONDS_BUCKETS)
        reg.counter(
            'skytpu_spot_preemptions_total',
            'Spot replica preemptions observed (advance warnings and '
            'hard cluster losses)')
        self._m_handoff = {o: disagg_lib.handoff_counter(o)
                           for o in disagg_lib.HANDOFF_OUTCOMES}
        self._m_kv_bytes = {d: disagg_lib.transfer_bytes_counter(d)
                            for d in disagg_lib.KV_TRANSFER_DIRECTIONS}
        self._h_kv_transfer = disagg_lib.transfer_seconds()

    # ------------------------------------------------------------- engine
    def _load_engine(self) -> None:
        from skypilot_tpu.models.tokenizer import load_tokenizer
        # The shared gang recipe: real weights come from an HF
        # checkpoint dir (config.json + safetensors [+ tokenizer.json])
        # — the reference serves such checkpoints through
        # vLLM/JetStream (llm/llama-3/llama3.yaml:109). The (tp, dp)
        # mesh keeps the zero-resharding contract the paged-tp
        # jaxpr-audit preset gates.
        engine = build_engine(
            self.cfg_name, max_batch=self.max_batch,
            max_seq=self.max_seq, model_path=self.model_path,
            quantize=self.quantize, kv_cache=self.kv_cache,
            kv_cache_dtype=self.kv_cache_dtype,
            page_size=self.page_size, decode_impl=self.decode_impl,
            prefill_w8a8=self.prefill_w8a8,
            prefill_chunk_tokens=self.prefill_chunk_tokens,
            decode_priority_ratio=self.decode_priority_ratio,
            decode_steps_per_call=self.decode_steps_per_call,
            speculate_k=self.speculate_k,
            adapter_slots=self.adapter_slots,
            adapter_dir=self.adapter_dir,
            adapter_rank=self.adapter_rank,
            tp=self.tp, dp=self.dp,
            gang=self.gang if self.gang.is_gang else None)
        if self.model_path:
            self.cfg_name = engine.cfg.name
        self.tokenizer = load_tokenizer(
            self.model_path, model_vocab_size=engine.cfg.vocab_size)
        self.engine = engine
        self.sched.bind_engine(engine)
        # Prefix-cache warm boot: land a local checkpoint file (written
        # by a prior drain/preemption) BEFORE readiness — the replica
        # never serves cold when warm state exists on disk. A gang
        # leader DEFERS the landing until the barrier completes and
        # routes it through the op log, so followers land the identical
        # entries in the identical order (a warm leader over cold
        # followers would diverge on prefix-cache hits).
        if self.checkpoint_path and os.path.exists(self.checkpoint_path):
            t0 = time.monotonic()
            try:
                with open(self.checkpoint_path, 'rb') as f:
                    blob = f.read()
                if self._gang is not None:
                    self._gang_boot_blob = blob
                else:
                    res = self.warm_from_checkpoint(blob)
                    self._h_warmup.observe(time.monotonic() - t0)
                    logger.info(
                        f'Warm boot from {self.checkpoint_path}: '
                        f'{res["warmed_rows"]} row(s) across '
                        f'{res["entries"]} entr(ies) in '
                        f'{time.monotonic() - t0:.2f}s')
            except Exception as e:  # pylint: disable=broad-except
                logger.warning(
                    f'Warm boot from {self.checkpoint_path} failed '
                    f'({type(e).__name__}: {e}); serving cold')
        self._ready.set()
        logger.info(f'Engine ready: model={self.cfg_name} '
                    f'max_batch={self.max_batch} max_seq={self.max_seq}')

    def _engine_loop(self) -> None:
        try:
            self._load_engine()
        except Exception as e:  # pylint: disable=broad-except
            self._fatal(e)
            return
        if self._stopping:
            # stop() raced the load: drop the just-loaded engine instead
            # of resurrecting the reference stop() exists to release.
            self.engine = None
            self._ready.clear()
            return

        while not self._stopping:
            try:
                self._work.wait()
                if self._stopping:
                    break
                if (self._gang is not None
                        and self._gang_boot_blob is not None
                        and self._gang.all_joined):
                    # Deferred gang warm boot: the barrier is complete,
                    # so the warmup op now reaches every rank in log
                    # order (warm_from_checkpoint appends it).
                    blob, self._gang_boot_blob = \
                        self._gang_boot_blob, None
                    try:
                        self.warm_from_checkpoint(blob)
                    except Exception as e:  # pylint: disable=broad-except
                        logger.warning(
                            f'gang warm boot failed '
                            f'({type(e).__name__}: {e}); serving cold')
                if self._faults is not None:
                    # Deterministic fault injection at the point the
                    # loop touches the hardware: a stall sleeps inside
                    # the loop (slow replica), a crash raises into the
                    # _fatal path (dead replica) — exactly the paths a
                    # real failure exercises.
                    rule = self._faults.fire('engine_step')
                    if rule is not None:
                        if rule.kind == 'engine_stall':
                            time.sleep(rule.delay_s)
                        elif rule.kind == 'replica_crash':
                            raise faults_lib.InjectedFault(
                                'injected replica_crash '
                                f'(engine_step #{self._faults.site_count("engine_step")})')
                        elif rule.kind == 'wedged_step':
                            # The gray failure a crash is not: the loop
                            # hangs INSIDE a step forever while the
                            # HTTP front end keeps answering. Arm the
                            # watchdog stamp exactly as a real step
                            # would, then never progress — detection
                            # and containment are the watchdog's job.
                            logger.warning(
                                'injected wedged_step: engine loop '
                                'hanging inside the step region')
                            self._wd_arm()
                            while (not self._stopping
                                   and self._degraded is None):
                                # This loop IS the injected hang (not
                                # a retry loop — nothing to back off).
                                time.sleep(0.01)  # graftcheck: disable=GC112
                            return     # a wedged step never returns
                        elif rule.kind == 'nan_logits':
                            # Evict one live decoding request exactly
                            # as the device-side non-finite sentinel
                            # would (deterministic stand-in for real
                            # NaN logits — the device reduction itself
                            # is unit-tested with poisoned params).
                            # LATCHED: if the loop iteration the rule
                            # lands on has no live request yet, the
                            # eviction applies to the NEXT one — the
                            # injection is deterministic under any
                            # arrival timing.
                            self._nan_evict_pending = True
                if self.speculate_k and self.engine is not None:
                    # Host-only n-gram matching for the next verify
                    # round, BEFORE taking the engine lock — handler
                    # threads must never queue behind proposer CPU
                    # work (graftcheck GC108 pins this discipline).
                    # Stale results (a slot turned over meanwhile) are
                    # revalidated and recomputed inside step().
                    self.engine.prepare_proposals()
                with self._lock:
                    # Top the engine up from the scheduler's tier
                    # queues (priority + SRW order, tier budget
                    # split), then step. The scheduler holds the
                    # backlog; the engine queue stays empty, so
                    # admission ORDER is decided here every step, not
                    # at submit time.
                    self.sched.fill_engine(self.engine)
                    # has_runnable_work: a prefill worker whose only
                    # live slots are HELD (awaiting their KV handoff)
                    # parks here instead of spinning — release_hold /
                    # submit / drain all set the wake event.
                    if self.engine.has_runnable_work():
                        # Adaptive fused horizon: long fused calls
                        # maximize throughput at saturation (dispatch
                        # is pipelined away, but per-call host work
                        # isn't), short ones keep streaming latency
                        # low when the batch is nearly idle.
                        sat = max(2, self.engine.max_batch // 2)
                        # The multi-step knob pins the fused horizon
                        # (the engine would override anyway — keeping
                        # the recorded gang op h consistent with what
                        # actually runs).
                        h = self.decode_steps_per_call or (
                            32 if self.engine.num_active >= sat else 8)
                        if self._gang is not None:
                            # Record the step BEFORE running it (op
                            # order == execution order; the engine
                            # lock serializes both) so followers run
                            # the identical fused horizon.
                            self._gang.append_op(
                                {'k': 'step', 'h': h,
                                 'prepared': bool(self.speculate_k)})
                        # Wedge watchdog window: the stamp covers
                        # exactly the device-step region — the part a
                        # stuck jitted call or dead accelerator wedges.
                        self._wd_arm()
                        try:
                            events = self.engine.step(horizon=h)
                        finally:
                            self._wd_clear()
                        if self._nan_evict_pending:
                            events = self._inject_nan_evict(events)
                        if self._gang is not None and events:
                            # Finished-request digests feed the
                            # cross-rank byte-identity check; must run
                            # before on_events pops the finished
                            # Request objects.
                            self._gang.digest.update(self.engine,
                                                     events)
                    else:
                        events = []
                        if not self.sched.backlog:
                            self._work.clear()
                            if self.sched.backlog:
                                # A submit raced the clear (its wake
                                # landed between the check and clear):
                                # re-arm or the request strands until
                                # the next arrival.
                                self._work.set()
                # Outbox routing runs OUTSIDE the lock: puts are
                # lock-free and a slow SSE consumer can never hold the
                # engine step hostage.
                self.sched.on_events(self.engine, events)
                # NaN blast-radius escalation: isolated poisoned
                # requests are evicted per-request above, but repeated
                # hits mean the REPLICA is sick (bad HBM, corrupted
                # weights, SDC) — escalate to the replica-level
                # degraded alarm so the manager replaces it.
                eng = self.engine
                if eng is not None \
                        and eng.nan_evictions > self._nan_seen:
                    self._nan_seen = eng.nan_evictions
                    if (self.nan_alarm_threshold > 0
                            and self._nan_seen
                            >= self.nan_alarm_threshold
                            and self._degraded is None):
                        self._gray_degrade(
                            'nan_logits',
                            f'{self._nan_seen} non-finite-logits '
                            'evictions (replica-level NaN storm)',
                            count=False)
                        return
            except Exception as e:  # pylint: disable=broad-except
                self._fatal(e)
                return
        # Clean stop: wake every waiter the way _fatal does — an
        # in-flight handler blocked on its outbox would otherwise hang
        # its client forever. The error sentinel is set BEFORE waking
        # (exactly like _fatal) so woken handlers report the stop.
        if self._error is None:
            self._error = 'server stopped'
        self.sched.fail_all(self._error)

    def _fatal(self, e: Exception) -> None:
        """Engine died: drop readiness (the serve probe then pulls this
        replica out of rotation) and fail every queued and in-flight
        request so handler threads return errors instead of blocking
        forever. On a gang leader this also fails the whole gang —
        every follower's next sync gets the error and self-terminates
        (one dead rank, dead gang; never a half-alive replica)."""
        logger.exception(f'Engine loop died: {type(e).__name__}: {e}')
        self._error = f'{type(e).__name__}: {e}'
        if self._gang is not None:
            self._gang.fail(self._error)
        self._ready.clear()
        self.sched.fail_all(self._error)

    # ------------------------------------------------- gray-failure defense
    def _wd_arm(self) -> None:
        with self._wd_lock:
            self._step_started = self._wd_clock()

    def _wd_clear(self) -> None:
        with self._wd_lock:
            self._step_started = None

    def watchdog_age_s(self) -> float:
        """Age of the engine step currently in flight (0 between
        steps) — the ``skytpu_engine_step_watchdog_age_seconds``
        gauge, on the injectable watchdog clock."""
        with self._wd_lock:
            if self._step_started is None:
                return 0.0
            return max(0.0, self._wd_clock() - self._step_started)

    def watchdog_check(self) -> bool:
        """One watchdog evaluation (the monitor thread's body; tests
        call it directly on a virtual clock): a step older than
        ``step_watchdog_s`` flips the replica to the degraded state.
        Returns True when the watchdog fired."""
        if self.step_watchdog_s <= 0 or self._degraded is not None:
            return False
        age = self.watchdog_age_s()
        if age <= self.step_watchdog_s:
            return False
        self._gray_degrade(
            'wedged_step',
            f'engine step stuck for {age:.1f}s '
            f'(deadline {self.step_watchdog_s:.1f}s)')
        return True

    def _gray_degrade(self, kind: str, detail: str,
                      count: bool = True) -> None:
        """Containment for a replica-level gray failure: mark the
        replica degraded (readiness flips to a 503 the manager's probe
        escalation acts on), stop admitting, and fail every queued and
        in-flight request with a retryable error — the LB's in-flight
        recovery resubmits the streams to surviving replicas. The
        process stays up (a wedged accelerator does not kill HTTP),
        which is exactly why the state is 'degraded', not 'failed'."""
        if count:
            faults_lib.gray_failure_counter(kind).inc()
        self._degraded = f'{kind}: {detail}'
        logger.warning(f'replica degraded ({self._degraded}); failing '
                       'in-flight work over')
        if self._error is None:
            self._error = f'degraded ({kind}): {detail}'
        self._ready.clear()
        self.sched.fail_all(
            f'replica degraded ({kind}); retry on another replica')

    def _watchdog_loop(self) -> None:
        import random as random_mod
        rng = random_mod.Random()
        period = min(5.0, max(0.05, self.step_watchdog_s / 4.0))
        while not self._stopping and self._degraded is None:
            try:
                self.watchdog_check()
            except Exception:  # pylint: disable=broad-except
                logger.exception('watchdog check error')
            # Jittered poll (graftcheck GC112: no fixed-sleep loops).
            time.sleep(period * (0.5 + rng.random()))

    def _inject_nan_evict(self, events):
        """Injected ``nan_logits`` (engine lock held): cancel one live
        decoding request and prepend the non-finite sentinel event —
        the scheduler then fails exactly that outbox retryably, the
        same containment a real device-side sentinel drives. Stays
        latched until a live request exists (deterministic under any
        arrival timing)."""
        rids = self.engine.decoding_request_ids()
        if not rids:
            return events
        self._nan_evict_pending = False
        rid = rids[0]
        if self._gang is not None:
            # Keep the op log consistent: followers must drop the
            # same slot at the same log position.
            self._gang.append_op({'k': 'cancel', 'rid': rid})
            self._gang.digest.drop(rid)
        self.engine.cancel(rid)
        self.engine.nan_evictions += 1
        logger.warning(f'injected nan_logits: evicting request {rid}')
        return [(rid, -1, True)] + list(events)

    # --------------------------------------------------------------- gang
    def _gang_record_admit(self, rid: int, sr) -> None:
        """Scheduler admission hook (engine lock held): log the exact
        ``add_request`` call for follower replay."""
        s = sr.sampling
        self._gang.append_op({
            'k': 'add', 'rid': rid, 'prompt': list(sr.prompt),
            'max_new_tokens': sr.max_new_tokens,
            'priority': scheduler_lib.TIERS.index(sr.tier),
            'temperature': s.get('temperature', 0.0),
            'top_k': s.get('top_k', 0), 'top_p': s.get('top_p', 1.0),
            'eos_id': s.get('eos_id'), 'stop': s.get('stop'),
            # Multi-tenant LoRA: followers must decode with the same
            # bank row (and the same logit mask) or their digests
            # diverge on the first adapter token.
            'adapter': s.get('adapter'), 'tenant': s.get('tenant'),
            'grammar': s.get('grammar'),
            # Fleet trace id: follower ranks attribute their lockstep
            # replay of this request to the same trace.
            'trace_id': (sr.trace_ctx or {}).get('trace_id')})

    def _gang_record_cancel(self, rid: int) -> None:
        self._gang.append_op({'k': 'cancel', 'rid': rid})
        self._gang.digest.drop(rid)

    def _gang_monitor(self) -> None:
        """Leader-side gang health loop: join-deadline and follower
        heartbeat enforcement. Any gang failure routes through
        ``_fatal`` — the whole replica leaves rotation at once and the
        LB's in-flight recovery resubmits to a surviving replica."""
        import random as random_mod
        rng = random_mod.Random()
        while not self._stopping and self._error is None:
            try:
                self._gang.check()
            except gang_lib.GangFailure as e:
                self._gang.count_failure(e.cause)
                self._gang.fail(str(e))
                self._fatal(e)
                return
            except Exception:  # pylint: disable=broad-except
                logger.exception('gang monitor error')
            # Jittered poll (graftcheck GC112: no fixed-sleep loops).
            time.sleep(self.gang.heartbeat_s * (0.5 + rng.random()))

    def gang_status(self) -> Dict[str, Any]:
        """The /gang/status payload (also the health-accounting block
        the controller ships to the LB): stable keys whether or not
        this replica is a gang."""
        if self._gang is None:
            return {'gang_id': self.gang.gang_id, 'world': 1,
                    'barrier': True, 'join_seconds': None, 'ops': 0,
                    'failed': self._error, 'members': {}}
        return self._gang.status()

    def submit(self, prompt, max_new_tokens: int, temperature: float,
               top_k: int, eos_id: Optional[int], top_p: float = 1.0,
               stop=None, tier: Optional[str] = None,
               adapter: Optional[str] = None,
               tenant: Optional[str] = None,
               grammar: Optional[Any] = None,
               handoff_target: Optional[str] = None,
               trace_ctx: Optional[Dict[str, Any]] = None
               ) -> Dict[str, Any]:
        """Blocking submit (non-streaming handlers): admission-control
        through the scheduler, then drain the outbox to completion.
        Raises ``scheduler.ShedError`` (→ HTTP 429) when the tier's
        queue bound would be exceeded. On a prefill-role replica with a
        ``handoff_target``, the request hands off to the decode worker
        after prefill and the continuation is collected from its
        stream (falling back to local decode on any failure)."""
        if self._error is not None:
            raise RuntimeError(f'engine failed: {self._error}')
        sr = self.sched.submit(
            prompt, max_new_tokens=max_new_tokens, tier=tier,
            trace_ctx=trace_ctx,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_id=eos_id, stop=stop,
            adapter=adapter, tenant=tenant, grammar=grammar,
            hold=handoff_target is not None)
        pre = None
        if handoff_target is not None:
            pre = sr.outbox.get(timeout=300)
            if pre[0] is not None and not pre[1]:
                result = self._collect_handoff(
                    sr, handoff_target, prompt,
                    dict(temperature=temperature, top_k=top_k,
                         top_p=top_p, eos_id=eos_id, stop=stop,
                         adapter=adapter, tenant=tenant,
                         grammar=grammar))
                if result is not None:
                    return result
                self._m_handoff['fallback_local'].inc()
                self.release_hold(sr)
        while True:
            token, finished = (pre if pre is not None
                               else sr.outbox.get())
            pre = None
            if token is None or finished:
                break
        if sr.outbox.error is not None or sr.result is None:
            raise RuntimeError(
                f'engine failed: {sr.outbox.error or self._error}')
        req = sr.result
        self._record_finished(req)
        hit_eos = (req.eos_id is not None and req.output
                   and req.output[-1] == req.eos_id)
        return {
            'request_id': sr.request_id,
            'tokens': req.output,
            'ttft_ms': req.ttft_ms,
            'finish_reason': ('stop' if (req.stop_hit or hit_eos)
                              else 'length'),
            'prompt_tokens': len(req.prompt),
        }

    def submit_stream(self, prompt, max_new_tokens: int, temperature: float,
                      top_k: int, eos_id: Optional[int],
                      top_p: float = 1.0, stop=None,
                      tier: Optional[str] = None,
                      adapter: Optional[str] = None,
                      tenant: Optional[str] = None,
                      grammar: Optional[Any] = None,
                      hold: bool = False,
                      trace_ctx: Optional[Dict[str, Any]] = None):
        """Register a streaming request; returns its ScheduledRequest
        (``sr.outbox`` streams ``(token, finished)`` tuples). Callers
        must call ``finish_stream(sr)`` when done. Raises
        ``scheduler.ShedError`` (→ HTTP 429) on admission refusal.
        ``hold``: stop after the prefill-sampled first token (the
        disaggregated-handoff window; see ``release_hold``)."""
        if self._error is not None:
            raise RuntimeError(f'engine failed: {self._error}')
        return self.sched.submit(
            prompt, max_new_tokens=max_new_tokens, tier=tier,
            trace_ctx=trace_ctx,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_id=eos_id, stop=stop,
            adapter=adapter, tenant=tenant, grammar=grammar, hold=hold)

    def release_hold(self, sr) -> None:
        """Resume local decoding of a held (handoff-candidate) request
        — the colocated fallback when no decode worker took it."""
        with self._lock:
            if self.engine is not None and sr.request_id is not None:
                self.engine.release_hold(sr.request_id)
        self._work.set()

    def finish_stream(self, sr) -> None:
        """Deregister a streaming request. If the client disconnected
        mid-stream (the request is not finished), cancel it so the
        slot stops generating tokens nobody will read — and count it
        as aborted, not served."""
        if sr.result is not None:
            self._record_finished(sr.result)
            return
        if self.sched.cancel(sr):
            self._m_aborted.inc()
        elif sr.result is not None:
            # Finished during the cancel race: cancel() popped the
            # finished request into sr.result instead of aborting.
            self._record_finished(sr.result)

    # ------------------------------------------------------------ handoff
    def handoff_target(self, header_value: Optional[str]
                       ) -> Optional[str]:
        """The decode worker this request should hand off to — None on
        non-prefill replicas (and when neither the router header nor a
        live static peer names one), in which case the request decodes
        locally exactly as before."""
        if self.role != 'prefill':
            return None
        return disagg_lib.pick_target(header_value,
                                      self.handoff_targets)

    def start_handoff(self, sr, target: str) -> Optional[Dict[str, Any]]:
        """Export ``sr``'s KV (int8 stays int8 on the wire) and POST it
        to ``target``'s ``/kv/ingest``; the response IS the decode
        worker's continuation token stream. On success the LOCAL
        request is cancelled (the slot frees for more prefill work; its
        full prefix pages stay cached) and the caller relays the
        stream. Returns None on ANY failure — the caller keeps serving
        locally (colocated fallback; the outbox still holds every
        token)."""
        if self._faults is not None:
            # Deterministic handoff failure (site 'handoff', kind
            # partial_response): the POST "breaks" before it is sent —
            # drives the exact colocated-fallback path a dead decode
            # worker would.
            rule = self._faults.fire('handoff')
            if rule is not None and rule.kind == 'partial_response':
                self._m_handoff['failed'].inc()
                logger.warning('handoff suppressed (injected '
                               'partial_response); decoding locally')
                return None
        with self._lock:
            if self.engine is None:
                return None
            snap, events = self.engine.export_kv_snapshot(
                sr.request_id)
        if events:
            # Tokens drained from the async pipeline during export
            # belong to their outboxes exactly like step() events.
            self.sched.on_events(self.engine, events)
        if snap is None or sr.result is not None:
            return None          # finished/cancelled during the drain
        t0 = time.monotonic()
        try:
            blob = kv_transfer.encode_handoff(snap)
            if self._faults is not None:
                # Deterministic wire corruption (site 'kv_wire', kind
                # kv_corruption): one byte of the encoded container
                # flips in transit — the receiver's CRC layer must
                # refuse it all-or-nothing (a retryable 400 → this
                # prefill falls back to local decode, never a
                # byte-wrong continuation).
                rule = self._faults.fire('kv_wire')
                if rule is not None and rule.kind == 'kv_corruption':
                    blob = faults_lib.corrupt_blob(blob, rule)
                    logger.warning('injected kv_corruption on the '
                                   'handoff wire (1 byte flipped)')
            # The handoff hop carries the fleet trace: the decode
            # worker's continuation joins this request's trace id with
            # the prefill span as its causal parent.
            trace = None
            if sr.trace_ctx and sr.trace_ctx.get('trace_id'):
                trace = {'trace_id': sr.trace_ctx['trace_id'],
                         'parent_span': 'prefill'}
            resp = wire.urlopen(
                target + '/kv/ingest', data=blob,
                headers={'Content-Type': 'application/octet-stream',
                         'X-SLO-Tier': sr.tier},
                trace=trace, timeout=120)
        except urllib.error.HTTPError as e:
            body = e.read()
            outcome = 'no_capacity' if e.code == 503 else 'failed'
            self._m_handoff[outcome].inc()
            logger.warning(
                f'handoff to {target} refused (HTTP {e.code}: '
                f'{body[:120]!r}); decoding locally')
            return None
        except Exception as e:  # pylint: disable=broad-except
            self._m_handoff['failed'].inc()
            logger.warning(f'handoff to {target} failed '
                           f'({type(e).__name__}: {e}); decoding '
                           'locally')
            return None
        self._m_kv_bytes['export'].inc(len(blob))
        self._h_kv_transfer.observe(time.monotonic() - t0)
        self._m_handoff['sent'].inc()
        # The continuation now lives on the decode worker: release the
        # local slot. The snapshot's registered prefix pages survive in
        # the LRU, so a migration resubmit landing back here re-matches
        # them.
        self.sched.cancel(sr)
        return {'prelude': [int(t) for t in snap['output']],
                'resp': resp, 'target': target}

    def _collect_handoff(self, sr, target: str, prompt,
                         sampling: Dict[str, Any]
                         ) -> Optional[Dict[str, Any]]:
        """Non-streaming handoff: run ``start_handoff`` and drain the
        decode worker's SSE continuation into one result dict. A
        decode-side failure mid-continuation resubmits
        ``prompt + tokens so far`` LOCALLY (the prefix cache makes the
        recompute cheap) so the caller still gets a complete answer —
        zero lost requests without an LB in the path."""
        ho = self.start_handoff(sr, target)
        if ho is None:
            return None
        tokens = list(ho['prelude'])
        finish_reason = None
        broke: Optional[str] = None
        try:
            with ho['resp'] as resp:
                for raw in resp:
                    if not raw.startswith(b'data:'):
                        continue
                    try:
                        ev = json.loads(raw[5:].strip())
                    except ValueError:
                        continue
                    if 'error' in ev:
                        broke = str(ev['error'])
                        break
                    if ev.get('done'):
                        finish_reason = ev.get('finish_reason',
                                               'length')
                        break
                    if 'token' in ev:
                        tokens.append(int(ev['token']))
        except Exception as e:  # pylint: disable=broad-except
            broke = f'{type(e).__name__}: {e}'
        if finish_reason is None:
            # Decode worker died mid-continuation: finish locally from
            # the generated prefix.
            self._m_handoff['failed'].inc()
            logger.warning(f'handoff continuation on {ho["target"]} '
                           f'broke ({broke}); resuming locally with '
                           f'{len(tokens)} token(s) generated')
            remaining = sr.max_new_tokens - len(tokens)
            if remaining > 0:
                sr2 = self.sched.submit(
                    list(prompt) + tokens, max_new_tokens=remaining,
                    tier=sr.tier, **sampling)
                while True:
                    token, finished = sr2.outbox.get()
                    if token is None:
                        raise RuntimeError(
                            f'engine failed: {sr2.outbox.error}')
                    if finished:
                        break
                req2 = sr2.result
                # req2.output is the authoritative continuation (stop
                # sequences arrive trimmed).
                tokens = tokens + list(req2.output
                                       if req2 is not None else [])
                hit_eos = (req2 is not None and req2.eos_id is not None
                           and req2.output
                           and req2.output[-1] == req2.eos_id)
                finish_reason = ('stop' if req2 is not None
                                 and (req2.stop_hit or hit_eos)
                                 else 'length')
            else:
                finish_reason = 'length'
        else:
            self._m_handoff['completed'].inc()
        self._m_served.inc()
        ttft = (round((sr.first_token_time - sr.submit_time) * 1e3, 3)
                if sr.first_token_time is not None else None)
        return {
            'request_id': sr.request_id,
            'tokens': tokens,
            'ttft_ms': ttft,
            'finish_reason': finish_reason,
            'prompt_tokens': len(prompt),
            'handoff': True,
        }

    # --------------------------------------------------- spot checkpoint
    def export_checkpoint(self, max_entries: int = 8):
        """The replica's resilience checkpoint as ``(bytes, n_entries)``:
        the hottest prefix-cache page chains (SKPF) plus snapshots of
        every in-flight decoding request (SKKV), in one SKCK container.
        Request entries are landed as prefix WARMTH by the receiver,
        never re-executed — the LB's in-flight recovery owns
        re-execution, so a checkpointed request that also migrates is
        warm on arrival instead of double-run. Safe on a cold/loading
        engine (empty container)."""
        entries: List[Dict[str, Any]] = []
        events: List[Any] = []
        eng = self.engine
        if eng is not None:
            with self._lock:
                if self._gang is not None:
                    # Gang checkpoint: record the pipeline flush the
                    # exports below perform, so followers flush at the
                    # same log position and stay event-aligned.
                    self._gang.append_op({'k': 'flush'})
                for rid in eng.decoding_request_ids():
                    if len(entries) >= max_entries:
                        break
                    snap, ev = eng.export_kv_snapshot(rid)
                    events.extend(ev)
                    if snap is not None:
                        entries.append(snap)
                pentries, ev = eng.export_prefix_snapshots(
                    max_entries=max_entries)
                events.extend(ev)
                entries.extend(pentries)
                if self._gang is not None and events:
                    self._gang.digest.update(eng, events)
            if events:
                # Tokens drained from the async pipeline during the
                # export belong to their outboxes exactly like step()
                # events.
                self.sched.on_events(eng, events)
        blob = kv_transfer.encode_checkpoint(entries)
        if self._gang is not None:
            # Checkpoint completes only when every rank acks — the
            # gang-atomic contract: "checkpointed" means the WHOLE
            # replica reached this state, not just rank 0. Bounded
            # wait (GC116); stragglers degrade to a leader-only
            # checkpoint with a loud log, never a hang.
            cid = self._gang.command('checkpoint')
            if not self._gang.wait_acked(
                    cid, timeout=min(10.0,
                                     4 * self.gang.heartbeat_timeout_s)):
                logger.warning(
                    'gang checkpoint: not every rank acked in time '
                    f'({self._gang.status()["members"]}); exporting '
                    'leader state anyway')
        self._m_kv_bytes['export'].inc(len(blob))
        return blob, len(entries)

    def export_prefix_blob(self, hash_hex: str):
        """One digest-named hot prefix chain as a CRC-checked SKCK
        container (single SKPF entry) — ``(blob, n_rows)``, or
        ``(None, 0)`` when the chain is unknown or already evicted.
        The prefix-affinity LB fetches this from the chain's home
        replica and POSTs it to the migration target's ``/kv/warmup``
        instead of letting the target recompute the prefix."""
        eng = self.engine
        if eng is None or not hasattr(eng, 'export_prefix_entry'):
            return None, 0
        with self._lock:
            if self._gang is not None:
                # Record the pipeline flush the export performs so
                # followers flush at the same op-log position (same
                # contract as export_checkpoint).
                self._gang.append_op({'k': 'flush'})
            entry, events = eng.export_prefix_entry(hash_hex)
            if self._gang is not None and events:
                self._gang.digest.update(eng, events)
        if events:
            self.sched.on_events(eng, events)
        if entry is None:
            return None, 0
        blob = kv_transfer.encode_checkpoint([entry])
        self._m_kv_bytes['export'].inc(len(blob))
        return blob, int(entry['n_rows'])

    def warm_from_checkpoint(self, blob: bytes) -> Dict[str, Any]:
        """Land a checkpoint container into the engine's prefix cache:
        every entry (request snapshots included) lands as prefix
        warmth via ``warm_prefix`` — byte-exact KV, content-addressed,
        no request is seated or re-executed. Best-effort under pool
        pressure: landing stops at the first capacity refusal (the
        hottest entries land first). Raises ``ValueError`` on a
        malformed container and ``RuntimeError`` when no engine is
        loaded."""
        entries = kv_transfer.decode_checkpoint(blob)
        warmed_rows = 0
        landed = 0
        skipped_capacity = 0
        with self._lock:
            if self.engine is None:
                raise RuntimeError('engine not loaded')
            if self._gang is not None:
                # Fan the landing out through the op log (under the
                # engine lock: op order == execution order) so every
                # rank's prefix cache warms with the identical entries
                # — a warm leader over cold followers would diverge on
                # later prefix-cache hits.
                import base64
                self._gang.append_op({
                    'k': 'warmup',
                    'blob': base64.b64encode(blob).decode()})
            for entry in entries:
                try:
                    rows = self.engine.warm_prefix(entry)
                except kv_transfer.HandoffCapacityError:
                    skipped_capacity = len(entries) - landed
                    break
                if rows:
                    landed += 1
                warmed_rows += rows
        self._m_kv_bytes['ingest'].inc(len(blob))
        return {'entries': len(entries), 'landed': landed,
                'warmed_rows': warmed_rows,
                'skipped_capacity': skipped_capacity,
                'kv_cache': self.kv_cache}

    def _persist_checkpoint(self) -> None:
        """Write the resilience checkpoint to ``checkpoint_path``
        (atomic rename) — the warm-boot source for a restarted
        standalone replica."""
        assert self.checkpoint_path is not None
        try:
            blob, n = self.export_checkpoint()
            tmp = self.checkpoint_path + '.tmp'
            with open(tmp, 'wb') as f:
                f.write(blob)
            os.replace(tmp, self.checkpoint_path)
            logger.info(f'Checkpointed {n} entr(ies) '
                        f'({len(blob)} bytes) to '
                        f'{self.checkpoint_path}')
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'Checkpoint persist failed '
                           f'({type(e).__name__}: {e})')

    # -------------------------------------------------------------- drain
    def begin_drain(self, deadline_s: Optional[float] = None
                    ) -> Dict[str, Any]:
        """Enter graceful drain: the scheduler stops admitting (new
        submits get a retryable 503 + Retry-After), in-flight requests
        run to completion, and a monitor thread records the drain
        duration — failing whatever is still running once the deadline
        passes (the LB migrates those). Idempotent; returns the status
        payload."""
        with self._drain_lock:
            if self._drain_started is None:
                self._drain_started = time.monotonic()
                self._drain_deadline = self._drain_started + (
                    float(deadline_s) if deadline_s else
                    self.drain_deadline_s)
                self.sched.begin_drain()
                self._work.set()      # wake the loop to run the tail
                if self._gang is not None:
                    # Gang drain: the command pins the current op-log
                    # index; a follower acks only once it has applied
                    # everything up to it, so "gang drained" means
                    # every rank reached the drained state.
                    self._gang_drain_cid = self._gang.command(
                        'drain', {'deadline_s': float(deadline_s)
                                  if deadline_s else
                                  self.drain_deadline_s})
                if self.checkpoint_path:
                    # Persist the prefix-cache checkpoint alongside
                    # the drain (off-thread: the drain response must
                    # not wait on the KV gather) — the warm-boot
                    # source for a restarted replica.
                    threading.Thread(target=self._persist_checkpoint,
                                     daemon=True).start()
                threading.Thread(target=self._drain_monitor,
                                 daemon=True).start()
                logger.info(
                    'drain started: deadline '
                    f'{self._drain_deadline - self._drain_started:.1f}s,'
                    f' {self.sched.inflight} request(s) in flight')
        return self.drain_status()

    def _drain_monitor(self) -> None:
        import random
        with self._drain_lock:
            started, deadline = self._drain_started, self._drain_deadline
        while time.monotonic() < deadline:
            if self.sched.drained and self._gang_drain_acked():
                break
            # Jittered poll (graftcheck GC112: no fixed-sleep loops).
            time.sleep(0.05 * (0.5 + random.random()))
        dur = time.monotonic() - started
        clean = self.sched.drained and self._gang_drain_acked()
        self._h_drain.observe(dur)
        self._drained.set()
        if clean:
            logger.info(f'drain complete in {dur:.2f}s')
        else:
            # Deadline exceeded: fail the stragglers with a retryable
            # error — the LB resubmits them to a surviving replica, so
            # the teardown that follows still loses nothing.
            logger.warning(
                f'drain deadline exceeded after {dur:.1f}s with '
                f'{self.sched.inflight} request(s) still running; '
                'failing them over')
            self.sched.fail_all('drain deadline exceeded; retry on '
                                'another replica')

    def _gang_drain_acked(self) -> bool:
        """True once every gang rank acked the drain command (always
        True for non-gang replicas and before a drain started)."""
        if self._gang is None:
            return True
        with self._drain_lock:
            cid = self._gang_drain_cid
        return cid is None or self._gang.acked(cid)

    def drain_status(self) -> Dict[str, Any]:
        with self._drain_lock:
            started, deadline = self._drain_started, self._drain_deadline
        now = time.monotonic()
        out = {
            'draining': started is not None,
            'drained': (self._drained.is_set() and self.sched.drained
                        and self._gang_drain_acked()),
            'inflight': self.sched.inflight,
            'deadline_remaining_s': (round(max(0.0, deadline - now), 2)
                                     if deadline is not None else None),
        }
        if self._gang is not None:
            out['gang_drain_acked'] = self._gang_drain_acked()
        return out

    # -------------------------------------------------------- idempotency
    def lookup_request_key(self, key: Optional[str]
                           ) -> Optional[Dict[str, Any]]:
        if not key:
            return None
        with self._keys_lock:
            return self._completed_keys.get(key)

    def record_request_key(self, key: Optional[str],
                           result: Dict[str, Any]) -> None:
        """Remember a completed keyed request (bounded LRU): a replay
        of the same key returns this result instead of executing the
        request a second time."""
        if not key:
            return
        with self._keys_lock:
            self._completed_keys[key] = result
            self._completed_keys.move_to_end(key)
            while len(self._completed_keys) > self._max_completed_keys:
                self._completed_keys.popitem(last=False)

    def _record_finished(self, req) -> None:
        """Fold one finished request into the registry: served counter
        plus the TTFT / TPOT / queue-wait latency decomposition (the
        queue-wait span comes off the request's telemetry trace)."""
        self._m_served.inc()
        if req.ttft_ms is not None:
            self._h_ttft.observe(req.ttft_ms)
        if (req.first_token_time is not None
                and req.finish_time is not None
                and len(req.output) > 1):
            self._h_tpot.observe(
                (req.finish_time - req.first_token_time) * 1e3
                / (len(req.output) - 1))
        trace = tracing.get_trace_buffer().find(req.request_id)
        if trace is not None:
            queue_ms = trace.span_ms('queue')
            if queue_ms is not None:
                self._h_queue_wait.observe(queue_ms)

    # ----------------------------------------------------------- metrics
    def _update_gauges(self) -> None:
        """Refresh the scrape-time registry gauges from engine state.
        Gauges are registered here get-or-create, so the Prometheus
        schema is stable from the first scrape (zeros before the
        engine loads or a feature turns on)."""
        eng = self.engine
        spec = (eng.spec_metrics() if eng is not None
                and hasattr(eng, 'spec_metrics') else {})
        g = self._reg.gauge
        g('skytpu_active_slots',
          'Occupied decode slots').set(eng.num_active if eng else 0)
        # Queue depth = engine queue (kept ~empty by the scheduler) +
        # the scheduler's own tier backlog: the number operators (and
        # the queue-depth LB policy) actually care about.
        g('skytpu_queue_depth',
          'Requests waiting for a slot').set(
              (eng.queue_depth if eng else 0) + self.sched.backlog)
        g('skytpu_sched_engine_work_tokens',
          'Estimated work tokens ahead in the engine '
          '(prefill tails + decode budgets)').set(
              eng.remaining_work_tokens() if eng else 0)
        g('skytpu_prefill_inflight',
          'Slots still streaming prompt chunks in').set(
              len(getattr(eng, '_prefill_off', ())) if eng else 0)
        g('skytpu_max_batch', 'Configured decode batch').set(
            self.max_batch)
        # Wedge-watchdog age: 0 between steps; sustained growth means
        # a step is stuck (the gauge operators alert on BEFORE the
        # watchdog deadline fires).
        self._g_wd_age.set(round(self.watchdog_age_s(), 3))
        # Serving mesh shape, one series per logical axis — all 1s on
        # a single-chip replica, configured values before the engine
        # loads (stable schema: the series never appear/disappear).
        for axis, size in self._mesh_axes().items():
            g('skytpu_mesh_shape',
              'Serving mesh axis size (1 = axis unused)',
              axis=axis).set(size)
        # Multi-step decode: the pinned fused steps per jitted decode
        # call (0 = the loop's adaptive horizon). Registered every
        # scrape get-or-create: present-and-zero before the knob (or
        # the engine) exists.
        g('skytpu_decode_steps_per_call',
          'Pinned fused decode steps per jitted call '
          '(0 = adaptive horizon)').set(
              getattr(eng, 'decode_steps_per_call', None)
              or self.decode_steps_per_call or 0)
        g('skytpu_speculate_k',
          'Speculative proposal depth (0 = off)').set(
              spec.get('speculate_k', 0))
        g('skytpu_spec_accept_rate',
          'Accepted / proposed draft tokens').set(
              spec.get('spec_accept_rate', 0.0))
        g('skytpu_spec_tokens_per_step',
          'Mean tokens committed per slot per verify call').set(
              spec.get('spec_tokens_per_step', 0.0))
        g('skytpu_spec_proposed_total',
          'Draft tokens proposed').set(spec.get('spec_proposed', 0))
        g('skytpu_spec_accepted_total',
          'Draft tokens accepted').set(spec.get('spec_accepted', 0))
        g('skytpu_spec_rounds_total',
          'Speculative verify rounds').set(spec.get('spec_rounds', 0))
        # KV pool capacity/pressure (shared engine schema; zeros until
        # the engine loads). The kv_cache_dtype label is constant for
        # the process, so the series set is stable from first scrape.
        pool = self._kv_pool_stats()
        dtype = pool['kv_cache_dtype']
        g('skytpu_kv_pool_tokens',
          'KV cache pool tokens by state (paged: page-granular)',
          state='used', kv_cache_dtype=dtype).set(pool['tokens_used'])
        g('skytpu_kv_pool_tokens',
          'KV cache pool tokens by state (paged: page-granular)',
          state='free', kv_cache_dtype=dtype).set(pool['tokens_free'])
        g('skytpu_kv_pool_token_capacity',
          'Total KV pool token capacity',
          kv_cache_dtype=dtype).set(pool['pool_token_capacity'])
        g('skytpu_kv_pool_preemptions_total',
          'Pool-pressure preemptions (recompute requeues)').set(
              pool['preemptions'])

    def _mesh_axes(self) -> Dict[str, int]:
        """The replica's mesh shape: the live engine's view once
        loaded, the configured (tp, dp) spec before — same keys either
        way (every logical axis, 1 when unused)."""
        eng = self.engine
        if eng is not None and hasattr(eng, 'mesh_axes'):
            return eng.mesh_axes()
        from skypilot_tpu.parallel import mesh as mesh_lib
        return {a: int(s) for a, s in zip(mesh_lib.MESH_AXES,
                                          self._mesh_spec.shape)}

    def _kv_pool_stats(self) -> Dict[str, Any]:
        """Engine KV pool stats with a stable all-zeros fallback before
        the engine loads (the dtype resolves from the configured flags
        so the gauge label never flips once serving starts)."""
        eng = self.engine
        if eng is not None and hasattr(eng, 'kv_pool_stats'):
            return eng.kv_pool_stats()
        from skypilot_tpu.inference.engine import resolve_kv_cache_dtype
        return {
            'kv_cache_dtype': resolve_kv_cache_dtype(
                self.kv_cache_dtype, self.quantize),
            'pool_token_capacity': 0, 'tokens_used': 0,
            'tokens_free': 0, 'preemptions': 0, 'kv_token_bytes': 0,
        }

    def _lora_stats(self) -> Dict[str, Any]:
        """The JSON ``lora`` block with a stable all-zeros fallback
        before the engine loads (or with the adapter bank off) — same
        keys either way, sized from the configured flags so the schema
        never flips once serving starts."""
        eng = self.engine
        reg = getattr(eng, 'adapters', None) if eng is not None else None
        if reg is not None:
            return reg.stats()
        return {
            'slots': self.adapter_slots, 'used': 0,
            'free': self.adapter_slots,
            'rank': self.adapter_rank if self.adapter_slots else 0,
            'targets': [], 'loads_total': 0, 'evictions_total': 0,
            'last_load_ms': 0.0, 'loaded': [], 'pinned': {},
        }

    def _metrics_json_payload(self) -> Dict[str, Any]:
        """The PR-3 stable-schema JSON gauge block, now sourced from
        the telemetry registry (every key ALWAYS present and numeric;
        0 when idle / a feature is off — scrapers see one stable
        schema, never a key that appears only once traffic or
        speculation starts)."""
        eng = self.engine
        spec = (eng.spec_metrics() if eng is not None
                and hasattr(eng, 'spec_metrics') else {})
        pool = self._kv_pool_stats()
        sched_stats = self.sched.json_stats()
        return {
            'requests_served': int(self._m_served.value),
            'requests_aborted': int(self._m_aborted.value),
            'active_slots': eng.num_active if eng else 0,
            'queue_depth': ((eng.queue_depth if eng else 0)
                            + self.sched.backlog),
            # Estimated work tokens ahead (engine prefill tails +
            # decode budgets + scheduler backlog) — what the
            # queue-depth LB policy load-ranks replicas by.
            'queue_tokens_total': (
                (eng.remaining_work_tokens() if eng else 0)
                + sum(t['queue_tokens']
                      for t in sched_stats['tiers'].values())),
            # Slots still streaming prompt chunks in — decodable
            # occupancy = active - this.
            'prefill_inflight': (len(getattr(
                eng, '_prefill_off', ())) if eng else 0),
            'max_batch': self.max_batch,
            'ttft_ms_median': round(self._h_ttft.quantile(0.5), 1),
            'ttft_ms_p90': round(self._h_ttft.quantile(0.9), 1),
            'ttft_window': self._h_ttft.window_len,
            'tpot_ms_median': round(self._h_tpot.quantile(0.5), 2),
            'tpot_ms_p90': round(self._h_tpot.quantile(0.9), 2),
            'queue_wait_ms_median': round(
                self._h_queue_wait.quantile(0.5), 1),
            'queue_wait_ms_p90': round(
                self._h_queue_wait.quantile(0.9), 1),
            # Speculative decoding gauges (zeros when off).
            'speculate_k': spec.get('speculate_k', 0),
            'spec_accept_rate': round(
                spec.get('spec_accept_rate', 0.0), 4),
            'spec_tokens_per_step': round(
                spec.get('spec_tokens_per_step', 0.0), 3),
            'spec_proposed': spec.get('spec_proposed', 0),
            'spec_accepted': spec.get('spec_accepted', 0),
            'spec_rounds': spec.get('spec_rounds', 0),
            # KV pool capacity/pressure (zeros before the engine loads;
            # kv_cache_dtype is the configured resolution either way).
            'kv_cache_dtype': pool['kv_cache_dtype'],
            'kv_pool_token_capacity': pool['pool_token_capacity'],
            'kv_pool_tokens_used': pool['tokens_used'],
            'kv_pool_tokens_free': pool['tokens_free'],
            'kv_pool_preemptions': pool['preemptions'],
            # Serving mesh shape (stable: configured values before the
            # engine loads, 1s on a single-chip replica). The LB's
            # replica view and the adaptive-TP policy read this.
            'mesh': dict(self._mesh_axes(),
                         devices=self.tp * self.dp),
            # Disaggregation block (stable schema: role + every handoff
            # outcome and transfer direction, zeros when idle). The
            # phase-aware LB policy routes and picks handoff targets
            # from this plus kv_pool_tokens_free above.
            'disagg': disagg_lib.json_block(self.role),
            # Gang block (stable schema: world 1 / barrier true on a
            # non-gang replica). The LB's replica view carries it for
            # health accounting — follower ranks have no routable
            # endpoint of their own.
            'gang': self.gang_status(),
            # Multi-step decode pin (0 = adaptive horizon) — stable
            # schema like every other key.
            'decode_steps_per_call': int(
                getattr(eng, 'decode_steps_per_call', None)
                or self.decode_steps_per_call or 0),
            'scheduler': {
                'prefill_chunk_tokens': getattr(eng, 'chunk', 0) or 0,
                'decode_priority_ratio': getattr(
                    eng, 'decode_priority_ratio', 0) or 0,
                'decode_steps_per_call': int(
                    getattr(eng, 'decode_steps_per_call', None)
                    or self.decode_steps_per_call or 0),
                'speculate_k': spec.get('speculate_k', 0),
            },
            # SLO scheduler block (stable schema: every tier and every
            # key present from the first scrape, zeros when idle).
            'sched': sched_stats,
            # Multi-tenant LoRA bank (stable schema: zeros/empty with
            # the bank off or before the engine loads). slots/used/free
            # are what the LB or an operator watches for bank-pressure
            # churn; loads/evictions count row re-uploads (never
            # recompiles).
            'lora': self._lora_stats(),
            # Hot-prefix digest (stable schema: page 0 / empty entries
            # on a slot engine or before the engine loads). Built from
            # the engine's HOST-SIDE heat tracker only — shipping it on
            # every probe adds zero d2h and zero recompiles (pinned by
            # the jaxpr-audit serve preset). The prefix-affinity LB
            # policy routes by longest match against these hashes.
            'prefix_digest': {
                'page': int(getattr(eng, 'page', 0) or 0),
                'entries': (eng.hot_prefix_digest()
                            if eng is not None
                            and hasattr(eng, 'hot_prefix_digest')
                            else []),
            },
        }

    # --------------------------------------------------------------- HTTP
    def _make_handler(server):  # noqa: N805
        class Handler(http.server.BaseHTTPRequestHandler):
            # Socket-op timeout (graftcheck GC107): a client that stops
            # reading its stream must not pin a handler thread (and its
            # engine slot) forever. Above the 300s stream-queue wait so
            # a healthy-but-slow engine never trips it first; the
            # finally: finish_stream path cancels the slot on timeout.
            timeout = 330

            def log_message(self, *args):
                del args

            def _json(self, code: int, payload: Dict[str, Any],
                      extra_headers: Optional[Dict[str, str]] = None
                      ) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(body)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _shed(self, e: 'scheduler_lib.ShedError') -> None:
                """Admission refusal: HTTP 429 (overload) or 503
                (draining), always with Retry-After from live queue
                telemetry — clients back off for a meaningful interval
                instead of hammering a saturated or leaving replica."""
                self._json(e.http_status, {'error': {
                    'message': str(e),
                    'type': ('draining' if e.reason == 'draining'
                             else 'overloaded'),
                    'tier': e.tier,
                    'reason': e.reason,
                    'retry_after_s': e.retry_after_s,
                }}, extra_headers={'Retry-After': str(e.retry_after_s)})

            def _request_key(self, payload) -> Optional[str]:
                """Client-supplied idempotency key: JSON field wins
                over the X-Request-ID header (the LB mints one for
                recoverable requests)."""
                key = payload.get('request_key')
                if key is None:
                    key = self.headers.get('X-Request-ID')
                return str(key) if key else None

            def _slo_tier(self, payload) -> Optional[str]:
                """Per-request SLO tier: JSON field (``slo_tier``) wins
                over the ``X-SLO-Tier`` header; None -> server
                default. Unknown values 400 via resolve_tier."""
                tier = payload.get('slo_tier')
                if tier is None:
                    tier = self.headers.get('X-SLO-Tier')
                return server.sched.resolve_tier(tier)

            def _gang_sync(self) -> None:
                """One follower heartbeat against the leader's gang
                bus: registers/refreshes the member, verifies its
                finished-request digests, returns the op-log tail and
                pending commands (404 on a non-gang replica)."""
                if server._gang is None:
                    self._json(404, {'error': 'not a gang leader'})
                    return
                length = int(self.headers.get('Content-Length', 0))
                try:
                    payload = json.loads(self.rfile.read(length))
                    rank = int(payload['rank'])
                except (KeyError, ValueError, TypeError,
                        json.JSONDecodeError) as e:
                    self._json(400, {'error': f'{type(e).__name__}: '
                                              f'{e}'})
                    return
                gid = payload.get('gang_id')
                if gid and server.gang.gang_id and \
                        gid != server.gang.gang_id:
                    self._json(409, {'failed': f'gang id mismatch: '
                                               f'{gid!r} != '
                                               f'{server.gang.gang_id!r}'})
                    return
                self._json(200, server._gang.sync(
                    rank, int(payload.get('applied', 0)),
                    payload.get('acks') or [],
                    payload.get('finished') or {}))

            def do_GET(self):  # noqa: N802
                parsed = urllib.parse.urlparse(self.path)
                query = urllib.parse.parse_qs(parsed.query)
                if parsed.path == '/readiness':
                    if server._degraded is not None:
                        # Gray failure contained: the process is alive
                        # (that is the POINT of a gray failure) but the
                        # data plane is not trustworthy — the manager's
                        # probe escalation fails the replica over.
                        self._json(503, {'status': 'degraded',
                                         'cause': server._degraded,
                                         'watchdog_age_s': round(
                                             server.watchdog_age_s(),
                                             3)})
                    elif server._error is not None:
                        self._json(503, {'status': 'failed',
                                         'error': server._error})
                    elif server.sched.draining:
                        # Out of rotation: probes see 503 so the LB /
                        # controller stop routing here while the tail
                        # of in-flight work finishes.
                        self._json(503, dict(
                            server.drain_status(), status='draining'))
                    elif (server._gang is not None
                          and not server._gang.all_joined):
                        # Gang barrier gates readiness: the replica is
                        # servable only once EVERY rank joined within
                        # the join timeout — a partial gang never
                        # enters LB rotation.
                        self._json(503, dict(server.gang_status(),
                                             status='gang_joining'))
                    elif server._ready.is_set():
                        self._json(200, {'status': 'ready',
                                         'model': server.cfg_name,
                                         'gang': server.gang_status()})
                    else:
                        self._json(503, {'status': 'loading'})
                elif parsed.path == '/gang/status':
                    self._json(200, server.gang_status())
                elif parsed.path == '/drain':
                    self._json(200, server.drain_status())
                elif parsed.path == '/metrics':
                    server._update_gauges()
                    if query.get('format', [''])[0] == 'json':
                        self._json(200, server._metrics_json_payload())
                        return
                    body = server._reg.render_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        'Content-Type',
                        'text/plain; version=0.0.4; charset=utf-8')
                    self.send_header('Content-Length', str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif parsed.path == '/kv/prefix/export':
                    h = query.get('hash', [''])[0]
                    blob, n_rows = server.export_prefix_blob(h)
                    if blob is None:
                        self._json(404, {'error': {
                            'message': f'prefix {h!r} not cached',
                            'type': 'prefix_not_found'}})
                        return
                    self.send_response(200)
                    self.send_header('Content-Type',
                                     'application/octet-stream')
                    self.send_header('X-Prefix-Rows', str(n_rows))
                    self.send_header('Content-Length', str(len(blob)))
                    self.end_headers()
                    self.wfile.write(blob)
                elif parsed.path == '/telemetry/summary':
                    # Fleet-plane scrape: the controller pulls this on
                    # the probe path. ``since`` is the caller's trace
                    # cursor (resume semantics — only traces completed
                    # after it ship); the clock block lets the
                    # controller compute per-process skew at scrape
                    # time and apply it at trace assembly.
                    try:
                        since = int(query.get('since', ['0'])[0])
                    except ValueError:
                        since = 0
                    server._update_gauges()
                    cursor, traces = (tracing.get_trace_buffer()
                                      .summaries_since(since))
                    self._json(200, {
                        'clock': {'wall': time.time(),
                                  'monotonic': time.monotonic()},
                        'registry': server._reg.export_wire(),
                        'traces': traces,
                        'cursor': cursor,
                    })
                elif parsed.path == '/debug/requests':
                    try:
                        limit = int(query.get('limit', ['64'])[0])
                    except ValueError:
                        limit = 64
                    self._json(200, {'requests': tracing.
                                     get_trace_buffer().to_json(limit)})
                elif parsed.path == '/v1/models':
                    self._json(200, {
                        'object': 'list',
                        'data': [{'id': server.cfg_name,
                                  'object': 'model',
                                  'owned_by': 'skypilot-tpu'}],
                    })
                else:
                    self._json(404, {'error': f'no route {self.path}'})

            def _stream_generate(self, prompt, is_text, kwargs,
                                 key=None) -> None:
                """Server-sent events: one ``data:`` line per token as
                the engine emits it, a final ``done`` event with the
                full sequence. Token streaming end to end — the LB
                passes text/event-stream responses through unbuffered.
                Tokens arrive through the request's scheduler outbox,
                fed fire-and-forget off the engine loop: a slow reader
                here never stalls the step.

                Prefill role: once the first token lands (prefill
                complete), the request's KV hands off to a decode
                worker and this handler relays its continuation stream
                — one client stream either way. Any handoff failure
                falls back to local decoding seamlessly (the pre-read
                first token re-enters the loop)."""
                tok = server.tokenizer
                target = server.handoff_target(
                    self.headers.get('X-Handoff-Target'))
                sr = server.submit_stream(prompt,
                                          hold=target is not None,
                                          trace_ctx=self._trace_ctx(),
                                          **kwargs)
                tokens = []
                # Everything after registration lives under the finally:
                # even a client that drops before the headers flush must
                # reach finish_stream, or the slot decodes to
                # max_new_tokens for nobody.
                try:
                    self.send_response(200)
                    self.send_header('Content-Type', 'text/event-stream')
                    self.send_header('Cache-Control', 'no-cache')
                    self.send_header('Connection', 'close')
                    self.end_headers()
                    pre = None
                    if target is not None:
                        pre = sr.outbox.get(timeout=300)
                        if pre[0] is not None and not pre[1]:
                            ho = server.start_handoff(sr, target)
                            if ho is not None:
                                self._relay_handoff(ho, sr, tokens,
                                                    is_text, tok, key)
                                return
                            server._m_handoff['fallback_local'].inc()
                            server.release_hold(sr)
                    self._stream_loop(sr, tokens, is_text, tok, key,
                                      pre=pre)
                except (BrokenPipeError, ConnectionResetError):
                    pass    # client vanished; finish_stream cancels
                finally:
                    server.finish_stream(sr)
                    self.close_connection = True

            def _relay_handoff(self, ho, sr, tokens, is_text, tok,
                               key=None) -> None:
                """Relay a handoff continuation: the snapshot's prelude
                tokens (generated here during prefill) followed by the
                decode worker's live SSE events, merged into ONE client
                stream whose done event carries the full token list. A
                broken decode leg surfaces as a retryable error event
                with ``tokens_so_far`` — exactly what the LB's
                in-flight recovery needs to resubmit
                ``prompt + prefix`` to a surviving replica."""
                def emit(ev) -> None:
                    self.wfile.write(
                        f'data: {json.dumps(ev)}\n\n'.encode())
                    self.wfile.flush()

                def token_event(t: int) -> Dict[str, Any]:
                    ev = {'token': int(t)}
                    if is_text:
                        ev['text'] = sanitize_text(tok.decode([int(t)]))
                    return ev

                for t in ho['prelude']:
                    tokens.append(int(t))
                    emit(token_event(t))
                broke = None
                try:
                    with ho['resp'] as resp:
                        for raw in resp:
                            if not raw.startswith(b'data:'):
                                continue
                            try:
                                ev = json.loads(raw[5:].strip())
                            except ValueError:
                                continue
                            if 'error' in ev:
                                broke = str(ev['error'])
                                break
                            if ev.get('done'):
                                done = {'done': True,
                                        'request_id': sr.request_id,
                                        'tokens': list(tokens)}
                                if 'finish_reason' in ev:
                                    done['finish_reason'] = \
                                        ev['finish_reason']
                                if is_text:
                                    done['text'] = sanitize_text(tok.decode(tokens))
                                server.record_request_key(
                                    key, dict(done))
                                emit(done)
                                server._m_handoff['completed'].inc()
                                server._m_served.inc()
                                return
                            if 'token' in ev:
                                tokens.append(int(ev['token']))
                                emit(token_event(ev['token']))
                    if broke is None:
                        broke = 'decode worker stream ended early'
                except (BrokenPipeError, ConnectionResetError):
                    raise       # OUR client vanished — outer cleanup
                except Exception as e:  # pylint: disable=broad-except
                    broke = f'{type(e).__name__}: {e}'
                # Decode worker died mid-continuation: a retryable
                # error event with the generated prefix — the LB
                # resubmits prompt+prefix to a surviving replica (the
                # client sees one stream); direct clients retry.
                server._m_handoff['failed'].inc()
                logger.warning(f'handoff continuation on '
                               f'{ho["target"]} broke ({broke})')
                # failed_upstream names the DEAD replica (the decode
                # worker) — this relay is healthy, and the LB's
                # migration must exclude the right one.
                emit({'error': f'decode worker failed mid-stream: '
                               f'{broke}',
                      'retryable': True, 'retry_after_s': 1,
                      'failed_upstream': ho['target'],
                      'tokens_so_far': list(tokens)})

            def _stream_loop(self, sr, tokens, is_text, tok,
                             key=None, pre=None) -> None:
                pending = [] if pre is None else [pre]
                while True:
                    token, finished = (pending.pop(0) if pending
                                       else sr.outbox.get(timeout=300))
                    if token is None:       # engine died / shed
                        # Retryable stream failure: the error event
                        # carries enough for the LB (or a client) to
                        # resubmit elsewhere instead of giving up.
                        self.wfile.write(
                            ('data: ' + json.dumps({
                                'error': sr.outbox.error
                                or 'engine failed',
                                'retryable': True,
                                'retry_after_s': 1}) + '\n\n').encode())
                        break
                    tokens.append(int(token))
                    event = {'token': int(token)}
                    if is_text:
                        event['text'] = sanitize_text(tok.decode([int(token)]))
                    self.wfile.write(
                        f'data: {json.dumps(event)}\n\n'.encode())
                    self.wfile.flush()
                    if finished:
                        done = {'done': True,
                                'request_id': sr.request_id,
                                'tokens': tokens}
                        if is_text:
                            done['text'] = sanitize_text(tok.decode(tokens))
                        server.record_request_key(key, dict(
                            done, request_id=sr.request_id))
                        self.wfile.write(
                            f'data: {json.dumps(done)}\n\n'.encode())
                        break

            def _replay_stream(self, cached, is_text, tok) -> None:
                """Replay a completed keyed request as one SSE burst —
                the duplicate of an already-answered request streams
                the SAME tokens, never a second execution."""
                try:
                    self.send_response(200)
                    self.send_header('Content-Type', 'text/event-stream')
                    self.send_header('Cache-Control', 'no-cache')
                    self.send_header('Connection', 'close')
                    self.end_headers()
                    for t in cached.get('tokens', []):
                        event = {'token': int(t)}
                        if is_text:
                            event['text'] = sanitize_text(tok.decode([int(t)]))
                        self.wfile.write(
                            f'data: {json.dumps(event)}\n\n'.encode())
                    done = dict(cached, done=True, deduped=True)
                    self.wfile.write(
                        f'data: {json.dumps(done)}\n\n'.encode())
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass    # replay consumer vanished; nothing to free
                finally:
                    self.close_connection = True

            # ---------------- OpenAI-compatible surface ----------------
            # The reference's serving recipes expose vLLM's OpenAI API
            # (llm/llama-3/llama3.yaml, llm/vllm/README.md) — clients
            # built against it work against these routes unchanged.
            def _parse_sampling(self, payload, tok):
                stop = payload.get('stop')
                if stop is not None:
                    if isinstance(stop, (str, bytes)):
                        stop = [stop]
                    stop = [tok.encode(s, bos=False)
                            if isinstance(s, str)
                            else [int(t) for t in s] for s in stop]
                kwargs = dict(
                    max_new_tokens=int(payload.get(
                        'max_tokens', payload.get('max_new_tokens', 128))),
                    temperature=float(payload.get('temperature', 0.0)),
                    top_k=int(payload.get('top_k', 0)),
                    top_p=float(payload.get('top_p', 1.0)),
                    stop=stop,
                    eos_id=payload.get('eos_id', tok.eos_id))
                # Multi-tenant LoRA + constrained decoding: adapter
                # name (also OpenAI-style 'model: base:adapter'),
                # tenant attribution label, and grammar ('json' |
                # allowed-token-id list). Only forwarded when present
                # so adapter-free deployments see the exact legacy
                # call.
                adapter = payload.get('adapter')
                model = payload.get('model')
                if adapter is None and isinstance(model, str) \
                        and ':' in model:
                    base, _, suffix = model.partition(':')
                    # Colon-bearing model ids (e.g. 'llama3:8b' tags)
                    # were always ignored on adapter-free deployments;
                    # only read 'base:adapter' when this replica has a
                    # bank, or the prefix names the served model (an
                    # unambiguous adapter request either way).
                    if getattr(server, 'adapter_slots', 0) \
                            or base == server.cfg_name:
                        adapter = suffix or None
                if adapter is not None:
                    kwargs['adapter'] = str(adapter)
                if payload.get('tenant') is not None:
                    kwargs['tenant'] = str(payload['tenant'])
                grammar = payload.get('grammar',
                                      payload.get('response_format'))
                if isinstance(grammar, dict):
                    # OpenAI response_format: {'type': 'json_object'}.
                    grammar = ('json' if grammar.get('type')
                               in ('json_object', 'json') else None)
                if grammar is not None:
                    kwargs['grammar'] = grammar
                return kwargs

            def _trace_ctx(self):
                """Parse the inbound cross-process trace context (LB or
                client supplied ``X-Skytpu-Trace``); None when absent
                or malformed — the engine mints a fresh root id."""
                return tracing.parse_trace_header(
                    self.headers.get(tracing.TRACE_HEADER))

            def _openai_completions(self, payload, chat: bool) -> None:
                import time as time_mod
                tok = server.tokenizer
                if chat:
                    msgs = payload['messages']
                    # Minimal role-tagged template (no in-repo chat
                    # templates; HF tokenizers with one still consume
                    # plain text fine for completion-style serving).
                    text = ''.join(
                        f"{m['role']}: {m['content']}\n" for m in msgs)
                    text += 'assistant:'
                else:
                    text = payload['prompt']
                    # OpenAI accepts str | [str] | [int] | [[int]];
                    # single-element wrappers unwrap (n>1 prompts need
                    # one request per prompt — the engine queue batches
                    # them anyway).
                    if (isinstance(text, list) and text
                            and isinstance(text[0], (list, str))):
                        if len(text) != 1:
                            raise ValueError(
                                'multiple prompts per request are not '
                                'supported; send one request per '
                                'prompt')
                        text = text[0]
                prompt_ids = (tok.encode(text) if isinstance(text, str)
                              else [int(t) for t in text])
                kwargs = self._parse_sampling(payload, tok)
                kwargs['tier'] = self._slo_tier(payload)
                if payload.get('stream'):
                    self._openai_stream(prompt_ids, payload, chat,
                                        kwargs)
                    return
                result = server.submit(
                    prompt_ids, handoff_target=server.handoff_target(
                        self.headers.get('X-Handoff-Target')),
                    trace_ctx=self._trace_ctx(), **kwargs)
                out_text = sanitize_text(tok.decode(result['tokens']))
                created = int(time_mod.time())
                if chat:
                    choice = {'index': 0,
                              'message': {'role': 'assistant',
                                          'content': out_text},
                              'finish_reason': result['finish_reason']}
                    obj = 'chat.completion'
                else:
                    choice = {'index': 0, 'text': out_text,
                              'logprobs': None,
                              'finish_reason': result['finish_reason']}
                    obj = 'text_completion'
                self._json(200, {
                    'id': f'cmpl-{result["request_id"]}',
                    'object': obj,
                    'created': created,
                    'model': server.cfg_name,
                    'choices': [choice],
                    'usage': {
                        'prompt_tokens': result['prompt_tokens'],
                        'completion_tokens': len(result['tokens']),
                        'total_tokens': (result['prompt_tokens'] +
                                         len(result['tokens'])),
                    },
                })

            def _openai_stream(self, prompt_ids, payload, chat,
                               kwargs) -> None:
                import time as time_mod
                tok = server.tokenizer
                sr = server.submit_stream(
                    prompt_ids, trace_ctx=self._trace_ctx(), **kwargs)
                created = int(time_mod.time())
                obj = ('chat.completion.chunk' if chat
                       else 'text_completion')
                def chunk_of(choice):
                    return {'id': f'cmpl-{sr.request_id}',
                            'object': obj,
                            'created': created,
                            'model': server.cfg_name,
                            'choices': [choice]}

                def emit(data) -> None:
                    self.wfile.write(f'data: {data}\n\n'.encode())
                    self.wfile.flush()
                try:
                    self.send_response(200)
                    self.send_header('Content-Type', 'text/event-stream')
                    self.send_header('Cache-Control', 'no-cache')
                    self.send_header('Connection', 'close')
                    self.end_headers()
                    if chat:
                        # OpenAI chat streams open with a role delta.
                        emit(json.dumps(chunk_of(
                            {'index': 0,
                             'delta': {'role': 'assistant'},
                             'finish_reason': None})))
                    while True:
                        token, finished = sr.outbox.get(timeout=300)
                        if token is None:
                            # Engine died mid-stream: an explicit error
                            # event (and NO [DONE]) so clients can tell
                            # truncation from completion.
                            emit(json.dumps({'error': {
                                'message': 'engine failed'}}))
                            break
                        piece = sanitize_text(tok.decode([int(token)]))
                        if chat:
                            choice = {'index': 0,
                                      'delta': {'content': piece},
                                      'finish_reason': None}
                        else:
                            choice = {'index': 0, 'text': piece,
                                      'finish_reason': None}
                        emit(json.dumps(chunk_of(choice)))
                        if finished:
                            # Terminal chunk: empty delta/text with the
                            # real finish_reason, then [DONE] — the
                            # OpenAI truncation-detection contract.
                            # sr.result is populated BEFORE the
                            # finished token lands in the outbox.
                            req = sr.result
                            hit_eos = (req is not None
                                       and req.eos_id is not None
                                       and req.output
                                       and req.output[-1] == req.eos_id)
                            reason = ('stop' if req is not None
                                      and (req.stop_hit or hit_eos)
                                      else 'length')
                            final = ({'index': 0, 'delta': {},
                                      'finish_reason': reason} if chat
                                     else {'index': 0, 'text': '',
                                           'finish_reason': reason})
                            emit(json.dumps(chunk_of(final)))
                            emit('[DONE]')
                            break
                except (BrokenPipeError, ConnectionResetError):
                    pass
                finally:
                    server.finish_stream(sr)
                    self.close_connection = True

            def _kv_ingest(self) -> None:
                """Land a prefill worker's KV handoff and stream the
                continuation back ON THIS RESPONSE: the length-prefixed
                wire blob (``inference/kv_transfer.py``) is decoded,
                validated, and seated directly in the engine
                (``ingest_kv_snapshot`` — decode resumes at the exact
                original KV bytes), then every newly decoded token
                streams back as an SSE event, ending in a ``done``
                event carrying the FULL merged token list and
                finish_reason. Refusals: 400 (malformed/mismatched —
                permanent), 503 + Retry-After (no slot/pool capacity,
                or draining — retryable elsewhere)."""
                length = int(self.headers.get('Content-Length', 0))
                data = self.rfile.read(length) if length else b''
                t0 = time.monotonic()
                try:
                    snap = kv_transfer.decode_handoff(data)
                    tier = server.sched.resolve_tier(
                        self.headers.get('X-SLO-Tier'))
                except ValueError as e:
                    server._m_handoff['rejected'].inc()
                    if 'checksum mismatch' in str(e):
                        # A bit-flipped wire container, caught by the
                        # CRC layer before any row landed.
                        faults_lib.gray_failure_counter(
                            'kv_corruption').inc()
                    self._json(400, {'error': {
                        'message': str(e),
                        'type': 'invalid_handoff'}})
                    return
                if server.sched.draining:
                    self._json(503, {'error': {
                        'message': 'replica is draining; hand off to '
                                   'another decode worker',
                        'type': 'draining', 'retry_after_s': 5}},
                        extra_headers={'Retry-After': '5'})
                    return
                trace_ctx = self._trace_ctx()
                if trace_ctx:
                    # The handoff hop carries the trace on the header,
                    # not in the KV wire container — the decode-side
                    # request adopts the prefill worker's trace id.
                    snap['trace'] = trace_ctx
                try:
                    with server._lock:
                        rid = server.engine.ingest_kv_snapshot(snap)
                        # Adopt under the engine lock: fail_all cannot
                        # slip between seat and registration.
                        sr = server.sched.adopt(
                            rid, tier=tier, prompt=snap['prompt'],
                            output=snap['output'],
                            max_new_tokens=snap['max_new_tokens'],
                            trace_ctx=trace_ctx)
                except kv_transfer.HandoffCapacityError as e:
                    server._m_handoff['no_capacity'].inc()
                    retry = server.sched.retry_after_s(
                        tier, len(snap['prompt'])
                        + int(snap['max_new_tokens']))
                    self._json(503, {'error': {
                        'message': str(e), 'type': 'no_capacity',
                        'retry_after_s': retry}},
                        extra_headers={'Retry-After': str(retry)})
                    return
                except ValueError as e:
                    server._m_handoff['rejected'].inc()
                    self._json(400, {'error': {
                        'message': str(e),
                        'type': 'invalid_handoff'}})
                    return
                except RuntimeError as e:
                    self._json(500, {'error': {'message': str(e)}})
                    return
                server._m_kv_bytes['ingest'].inc(len(data))
                server._h_kv_transfer.observe(time.monotonic() - t0)
                server._m_handoff['ingested'].inc()
                server._work.set()        # wake the engine loop
                try:
                    self.send_response(200)
                    self.send_header('Content-Type',
                                     'text/event-stream')
                    self.send_header('Cache-Control', 'no-cache')
                    self.send_header('Connection', 'close')
                    self.end_headers()
                    while True:
                        token, finished = sr.outbox.get(timeout=300)
                        if token is None:
                            self.wfile.write(
                                ('data: ' + json.dumps({
                                    'error': sr.outbox.error
                                    or 'engine failed',
                                    'retryable': True,
                                    'retry_after_s': 1})
                                 + '\n\n').encode())
                            break
                        self.wfile.write(
                            ('data: '
                             + json.dumps({'token': int(token)})
                             + '\n\n').encode())
                        self.wfile.flush()
                        if finished:
                            req = sr.result
                            hit_eos = (req is not None
                                       and req.eos_id is not None
                                       and req.output
                                       and req.output[-1]
                                       == req.eos_id)
                            reason = ('stop' if req is not None
                                      and (req.stop_hit or hit_eos)
                                      else 'length')
                            done = {'done': True, 'request_id': rid,
                                    'tokens': (list(req.output)
                                               if req is not None
                                               else []),
                                    'finish_reason': reason}
                            self.wfile.write(
                                f'data: {json.dumps(done)}\n\n'
                                .encode())
                            break
                except (BrokenPipeError, ConnectionResetError):
                    pass    # prefill relay vanished; cancel below
                finally:
                    if sr.result is None:
                        # Relay gone mid-continuation: free the slot
                        # (the prefill side / LB resubmits elsewhere).
                        server.sched.cancel(sr)
                    self.close_connection = True

            def _checkpoint(self) -> None:
                """Export the spot-resilience checkpoint. The response
                body IS the SKCK container (octet-stream) — or, with a
                ``path`` in the JSON body, the container is written to
                that file and a JSON summary returned (the standalone
                / shared-filesystem flavor)."""
                length = int(self.headers.get('Content-Length', 0))
                try:
                    payload = (json.loads(self.rfile.read(length))
                               if length else {})
                except json.JSONDecodeError:
                    self._json(400, {'error': 'bad json'})
                    return
                try:
                    blob, n = server.export_checkpoint(
                        int(payload.get('max_entries', 8)))
                except Exception as e:  # pylint: disable=broad-except
                    self._json(500, {'error': {'message':
                                               f'{type(e).__name__}: '
                                               f'{e}'}})
                    return
                path = payload.get('path')
                if path:
                    tmp = path + '.tmp'
                    with open(tmp, 'wb') as f:
                        f.write(blob)
                    os.replace(tmp, path)
                    self._json(200, {'entries': n, 'bytes': len(blob),
                                     'path': path})
                    return
                self.send_response(200)
                self.send_header('Content-Type',
                                 'application/octet-stream')
                self.send_header('X-Checkpoint-Entries', str(n))
                self.send_header('Content-Length', str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def _kv_warmup(self) -> None:
                """Land a checkpoint container into this replica's
                prefix cache (the recovery-warmup half of
                /checkpoint). 400 on a malformed container; partial
                landings under pool pressure are reported, not
                errors."""
                length = int(self.headers.get('Content-Length', 0))
                data = self.rfile.read(length) if length else b''
                try:
                    self._json(200, server.warm_from_checkpoint(data))
                except ValueError as e:
                    if 'checksum mismatch' in str(e):
                        faults_lib.gray_failure_counter(
                            'kv_corruption').inc()
                    self._json(400, {'error': {
                        'message': str(e),
                        'type': 'invalid_checkpoint'}})
                except RuntimeError as e:
                    self._json(503, {'error': {'message': str(e)}},
                               extra_headers={'Retry-After': '5'})

            def do_POST(self):  # noqa: N802
                routes = ('/generate', '/v1/completions',
                          '/v1/chat/completions', '/drain',
                          '/kv/ingest', '/checkpoint', '/kv/warmup',
                          '/gang/sync')
                if self.path not in routes:
                    self._json(404, {'error': f'no route {self.path}'})
                    return
                if self.path == '/gang/sync':
                    self._gang_sync()
                    return
                if self.path == '/drain':
                    length = int(self.headers.get('Content-Length', 0))
                    try:
                        payload = (json.loads(self.rfile.read(length))
                                   if length else {})
                    except json.JSONDecodeError:
                        self._json(400, {'error': 'bad json'})
                        return
                    self._json(200, server.begin_drain(
                        payload.get('deadline_s')))
                    return
                if server._degraded is not None:
                    # Retryable refusal: the LB treats a replica 503 as
                    # never-executed and retries on another replica.
                    self._json(503, {'status': 'degraded',
                                     'cause': server._degraded,
                                     'retry_after_s': 5},
                               extra_headers={'Retry-After': '5'})
                    return
                if not server._ready.is_set():
                    self._json(503, {'status': 'loading'},
                               extra_headers={'Retry-After': '5'})
                    return
                if self.path == '/kv/ingest':
                    if server._gang is not None:
                        # A gang leader cannot adopt foreign KV: the
                        # seat would bypass the op log and desync
                        # every follower. Retryable — phase routing
                        # picks another decode worker.
                        self._json(503, {'error': {
                            'message': 'gang replicas do not accept '
                                       'KV handoffs',
                            'type': 'gang', 'retry_after_s': 5}},
                            extra_headers={'Retry-After': '5'})
                        return
                    self._kv_ingest()
                    return
                if self.path == '/checkpoint':
                    self._checkpoint()
                    return
                if self.path == '/kv/warmup':
                    self._kv_warmup()
                    return
                if self.path != '/generate':
                    length = int(self.headers.get('Content-Length', 0))
                    try:
                        payload = json.loads(self.rfile.read(length))
                        self._openai_completions(
                            payload, chat=self.path.endswith(
                                'chat/completions'))
                    except (KeyError, ValueError, TypeError,
                            json.JSONDecodeError) as e:
                        self._json(400, {'error': {
                            'message': f'{type(e).__name__}: {e}',
                            'type': 'invalid_request_error'}})
                    except scheduler_lib.ShedError as e:
                        # Before RuntimeError: ShedError subclasses it,
                        # and a shed is a 429 contract, not a 500.
                        self._shed(e)
                    except RuntimeError as e:
                        self._json(500, {'error': {'message': str(e)}})
                    return
                length = int(self.headers.get('Content-Length', 0))
                try:
                    payload = json.loads(self.rfile.read(length))
                    prompt = payload['prompt']
                    tok = server.tokenizer
                    is_text = isinstance(prompt, str)
                    if is_text:
                        prompt = tok.encode(prompt)
                    key = self._request_key(payload)
                    cached = server.lookup_request_key(key)
                    if cached is not None:
                        # Idempotent replay: the key already completed
                        # here — return the SAME answer instead of
                        # executing a second time (the one-answer
                        # guarantee behind the LB's hedged retry).
                        if payload.get('stream'):
                            self._replay_stream(cached, is_text, tok)
                        else:
                            self._json(200, dict(cached, deduped=True))
                        return
                    kwargs = self._parse_sampling(payload, tok)
                    kwargs['tier'] = self._slo_tier(payload)
                    # /generate's legacy defaults: eos only applies to
                    # text prompts unless explicitly requested.
                    if 'eos_id' not in payload and not is_text:
                        kwargs['eos_id'] = None
                    if payload.get('stream'):
                        self._stream_generate(prompt, is_text, kwargs,
                                              key)
                        return
                    result = server.submit(
                        prompt, handoff_target=server.handoff_target(
                            self.headers.get('X-Handoff-Target')),
                        trace_ctx=self._trace_ctx(), **kwargs)
                    if is_text:
                        result['text'] = sanitize_text(tok.decode(result['tokens']))
                    server.record_request_key(key, result)
                    self._json(200, result)
                except (KeyError, ValueError, TypeError,
                        json.JSONDecodeError) as e:
                    self._json(400, {'error': f'{type(e).__name__}: {e}'})
                except scheduler_lib.ShedError as e:
                    self._shed(e)
                except RuntimeError as e:
                    self._json(500, {'error': str(e)})

        return Handler

    def start(self, block: bool = True) -> None:
        self._engine_thread = threading.Thread(target=self._engine_loop,
                                               daemon=True)
        self._engine_thread.start()
        if self._gang is not None:
            threading.Thread(target=self._gang_monitor,
                             daemon=True).start()
        if self.step_watchdog_s > 0:
            threading.Thread(target=self._watchdog_loop,
                             daemon=True).start()
        handler = self._make_handler()
        self._httpd = http.server.ThreadingHTTPServer(('0.0.0.0', self.port),
                                                      handler)
        logger.info(f'Model server listening on :{self.port}')
        if block:
            self._httpd.serve_forever()
        else:
            threading.Thread(target=self._httpd.serve_forever,
                             daemon=True).start()

    def stop(self) -> None:
        """Shut down the HTTP front end AND the engine loop, dropping
        the engine reference — the daemon loop thread would otherwise
        keep the model weights + KV pool alive (on TPU, several GB of
        HBM) for the life of the process."""
        self._stopping = True
        if self._gang is not None:
            # Clean gang teardown: followers get the shutdown command
            # (or, if they miss it, lose the coordinator and
            # self-terminate — either way nobody outlives the gang).
            # Bounded grace for the acks (GC116), then shut down
            # regardless.
            cid = self._gang.command('shutdown')
            self._gang.wait_acked(
                cid, timeout=min(1.0, 2 * self.gang.heartbeat_s))
        self._work.set()                      # wake the loop to exit
        if self._httpd is not None:
            self._httpd.shutdown()
        if self._engine_thread is not None:
            self._engine_thread.join(timeout=60)
        self.engine = None


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='tiny',
                        help='preset config name (random weights)')
    parser.add_argument('--model-path', default=None,
                        help='HF checkpoint dir (real weights + tokenizer)')
    parser.add_argument('--quantize', default=None,
                        choices=['int8', 'int4'],
                        help='weight quantization: int8 halves the '
                             'decode weight stream (the KV cache '
                             'follows via --kv-cache-dtype auto); '
                             'int4 packs two codes per byte with '
                             'fused dequant — half the streamed '
                             'weight bytes again on top of int8 (KV '
                             'follows to int4 under auto)')
    parser.add_argument('--tp', type=int, default=None,
                        help='tensor-parallel degree: shard weights + '
                             'KV heads over this many chips (decode '
                             'TPOT improves ~linearly; required once '
                             'the model outgrows one chip). Default: '
                             'SKYTPU_TP env (the controller\'s '
                             'adaptive-TP placement), else 1')
    parser.add_argument('--dp', type=int, default=None,
                        help='data-parallel degree: shard the decode '
                             'batch over chip groups (aggregate tok/s '
                             'scales; TPOT unchanged). Default: '
                             'SKYTPU_DP env, else 1. The mesh uses '
                             'tp*dp visible devices')
    parser.add_argument('--kv-cache-dtype', default=None,
                        choices=['bf16', 'int8', 'int4'],
                        help='KV cache storage dtype; default follows '
                             '--quantize (int8 weights => int8 KV, '
                             'int4 weights => int4 KV). '
                             'int8 halves KV HBM traffic in decode and '
                             '~doubles paged pool token capacity, with '
                             'dequant fused into the attention kernels; '
                             'int4 packs two nibble codes per byte — '
                             '~4x bf16 pool capacity at a further '
                             'bounded accuracy cost')
    parser.add_argument('--decode-impl', default=None,
                        choices=['gather', 'pallas', 'cross_layer'],
                        help='paged decode attention path (paged '
                             'cache only; default = engine auto). '
                             'cross_layer batches ALL layers\' KV '
                             'page reads per page visit — one kernel '
                             'pass per decode step instead of one '
                             'per layer')
    parser.add_argument('--kv-cache', default='paged',
                        choices=['slot', 'paged'],
                        help='paged (default) = shared page pool with '
                             'prefix caching, chunked prefill and '
                             'continuous admission; slot = fixed '
                             'per-slot reservations')
    parser.add_argument('--page-size', type=int, default=None,
                        help='paged-cache page granularity (tokens); '
                             'default auto-selects a fast-path size '
                             '(int8 decode needs a multiple of 128 to '
                             'stay on the manual-DMA fast path)')
    parser.add_argument('--prefill-chunk-tokens', type=int, default=None,
                        help='chunked-prefill chunk width (tokens); '
                             'prompts prefill in chunks interleaved '
                             'with decode so running requests keep '
                             'streaming behind long prompts. Engine '
                             'default 256; 0 = monolithic prefill '
                             '(slot engine only)')
    parser.add_argument('--decode-priority-ratio', type=float,
                        default=None,
                        help='decode share of the interleaved token '
                             'budget while prompts are mid-prefill '
                             '(0..1); higher favors streaming TPOT, '
                             'lower favors TTFT. Default: engine-tuned')
    parser.add_argument('--decode-steps-per-call', type=int,
                        default=None,
                        help='multi-step on-device decode: fuse '
                             'EXACTLY this many decode steps (with '
                             'on-device sampling) into each jitted '
                             'call, so per-step dispatch, readback and '
                             'sampling host-syncs amortize k x. '
                             'Default: adaptive horizon (8 idle / 32 '
                             'saturated). Ignored while --speculate-k '
                             'drives decode')
    parser.add_argument('--speculate-k', type=int, default=0,
                        help='speculative decoding: propose up to K '
                             'tokens per verify step via prompt-lookup '
                             '(n-gram) matching against each request\'s '
                             'own history (0 = off). Greedy outputs are '
                             'identical to vanilla decode; sampling '
                             'keeps the output distribution. Biggest '
                             'win on repetitive/extractive text')
    parser.add_argument('--adapter-slots', type=int,
                        default=int(os.environ.get(
                            'SKYTPU_ADAPTER_SLOTS', '0')),
                        help='Device-resident LoRA adapter bank rows '
                             '(0 = multi-tenant adapters off). Each '
                             'request may name an adapter; slots '
                             'load/evict by LRU with row re-uploads, '
                             'never recompiles. Env fallback: the '
                             'controller ships the adapters: spec '
                             'block as SKYTPU_ADAPTER_*.')
    parser.add_argument('--adapter-dir',
                        default=os.environ.get('SKYTPU_ADAPTER_DIR')
                        or None,
                        help='Directory of <name>.npz LoRA checkpoints '
                             '(models/multilora.save_adapter layout) '
                             'loaded on first use by name.')
    parser.add_argument('--adapter-rank', type=int,
                        default=int(os.environ.get(
                            'SKYTPU_ADAPTER_RANK', '8')),
                        help='Adapter bank rank: lower-rank '
                             'checkpoints zero-pad into the bank; '
                             'higher-rank ones are rejected.')
    parser.add_argument('--prefill-w8a8', action='store_true',
                        help='quantize prefill activations to int8 '
                             '(2x MXU rate on the compute-bound '
                             'prefill; adds quantization noise to '
                             'prefilled KV rows — decode unaffected)')
    parser.add_argument('--slo-tier-default', default='latency',
                        choices=list(scheduler_lib.TIERS),
                        help='SLO tier for requests that declare none '
                             '(per-request override: "slo_tier" in the '
                             'JSON body or the X-SLO-Tier header). '
                             'latency = interactive TTFT contract, '
                             'throughput = batch tokens/s contract')
    parser.add_argument('--max-queue-tokens', type=int, default=None,
                        help='per-tier admission bound in work tokens '
                             '(prompt + decode budget); a request that '
                             'would overflow its tier is shed with '
                             'HTTP 429 + Retry-After instead of '
                             'queueing. Default: 2x the KV pool token '
                             'capacity')
    parser.add_argument('--latency-admit-frac', type=float, default=0.7,
                        help='share of admitted work tokens reserved '
                             'for the latency tier while both tiers '
                             'are backlogged (0..1, exclusive)')
    parser.add_argument('--drain-deadline-s', type=float, default=30.0,
                        help='graceful-drain deadline (seconds): on '
                             'POST /drain new requests get a retryable '
                             '503 + Retry-After while in-flight ones '
                             'run to completion; stragglers past the '
                             'deadline are failed over (retryable)')
    parser.add_argument('--step-watchdog-s', type=float, default=None,
                        help='wedge-watchdog deadline (seconds) on '
                             'each engine step: a step stuck longer '
                             'flips /readiness to a degraded 503 and '
                             'fails in-flight requests over '
                             '(retryable — the LB resubmits them to '
                             'surviving replicas). Default: '
                             'SKYTPU_STEP_WATCHDOG_S env, else 120; '
                             '0 disables')
    parser.add_argument('--fault-spec', default=None,
                        help='deterministic fault-injection spec (JSON '
                             'or @/path/to/spec.json; default: the '
                             'SKYTPU_FAULT_SPEC env var). Unset = '
                             'injection compiled out of the hot path')
    parser.add_argument('--role', default=None,
                        choices=list(disagg_lib.ROLES),
                        help='disaggregated-serving phase role: '
                             'prefill workers hand each finished '
                             'prefill\'s KV (int8 stays int8 on the '
                             'wire) to a decode worker via POST '
                             '/kv/ingest and relay its token stream; '
                             'decode workers run high-batch decode '
                             'without prefill stalls; colocated '
                             '(default) interleaves both phases. '
                             'Default: SKYTPU_ROLE env (the '
                             'controller\'s disaggregation plan), '
                             'else colocated')
    parser.add_argument('--checkpoint-path', default=None,
                        help='local prefix-cache checkpoint file '
                             '(default: SKYTPU_KV_CHECKPOINT_PATH '
                             'env). When set: a drain/preemption '
                             'warning persists the hottest prefix '
                             'chains + in-flight KV snapshots here, '
                             'and a (re)booting server warms its '
                             'prefix cache from the file BEFORE '
                             'declaring readiness — near-warm TTFT '
                             'after spot recovery instead of cold')
    parser.add_argument('--handoff-targets', default=None,
                        help='comma-separated decode-worker base URLs '
                             'a prefill replica may hand off to when '
                             'no router supplied X-Handoff-Target '
                             '(picked by live KV-pool headroom). '
                             'Default: SKYTPU_HANDOFF_TARGETS env')
    parser.add_argument('--gang-rank', type=int, default=None,
                        help='multi-host gang rank (0 = leader: HTTP '
                             'front end + scheduler; >0 = follower '
                             'loop executing the leader\'s op log). '
                             'Default: SKYTPU_RANK env, else 0')
    parser.add_argument('--gang-world', type=int, default=None,
                        help='gang size (processes per replica; 1 = '
                             'not a gang). Default: SKYTPU_WORLD env')
    parser.add_argument('--gang-coordinator', default=None,
                        help='rank 0\'s base URL (the gang bus; '
                             'required on nonzero ranks). Default: '
                             'SKYTPU_COORDINATOR env')
    parser.add_argument('--gang-id', default=None,
                        help='shared gang identity (the replica '
                             'manager\'s unit of drain/checkpoint/'
                             'teardown). Default: SKYTPU_GANG_ID env')
    parser.add_argument('--max-batch', type=int, default=8)
    parser.add_argument('--max-seq', type=int, default=1024)
    parser.add_argument('--port', type=int,
                        default=int(os.environ.get('SKYTPU_REPLICA_PORT',
                                                   '8081')))
    args = parser.parse_args()
    if args.kv_cache != 'paged' and args.page_size is not None:
        parser.error('--page-size only applies with --kv-cache paged')
    if args.kv_cache != 'paged' and args.decode_impl is not None:
        parser.error('--decode-impl only applies with --kv-cache paged')
    gang_spec = gang_lib.GangSpec.from_env(
        rank=args.gang_rank, world=args.gang_world,
        coordinator=args.gang_coordinator, gang_id=args.gang_id)
    if gang_spec.is_gang and not gang_spec.is_leader:
        run_follower(gang_spec, args)
        return
    server = ModelServer(args.model, max_batch=args.max_batch,
                         max_seq=args.max_seq, port=args.port,
                         model_path=args.model_path,
                         quantize=args.quantize,
                         tp=args.tp, dp=args.dp,
                         kv_cache=args.kv_cache,
                         kv_cache_dtype=args.kv_cache_dtype,
                         page_size=args.page_size,
                         decode_impl=args.decode_impl,
                         prefill_w8a8=args.prefill_w8a8,
                         prefill_chunk_tokens=args.prefill_chunk_tokens,
                         decode_priority_ratio=args.decode_priority_ratio,
                         decode_steps_per_call=args.decode_steps_per_call,
                         speculate_k=args.speculate_k,
                         adapter_slots=args.adapter_slots,
                         adapter_dir=args.adapter_dir,
                         adapter_rank=args.adapter_rank,
                         slo_tier_default=args.slo_tier_default,
                         max_queue_tokens=args.max_queue_tokens,
                         latency_admit_frac=args.latency_admit_frac,
                         drain_deadline_s=args.drain_deadline_s,
                         fault_spec=args.fault_spec,
                         role=args.role,
                         handoff_targets=(args.handoff_targets.split(',')
                                          if args.handoff_targets
                                          else None),
                         checkpoint_path=args.checkpoint_path,
                         gang=gang_spec,
                         step_watchdog_s=args.step_watchdog_s)
    server.start(block=True)


def run_follower(spec: 'gang_lib.GangSpec', args) -> None:
    """Nonzero-rank gang entry: build the identical engine rank 0
    builds (same config, same warmup — `build_engine` is the shared
    recipe), join the coordinator, and replay its op log until
    shutdown or gang death. The process exit code reflects the cause:
    0 for a clean shutdown, nonzero when the gang died — the replica
    manager treats a dead rank as a dead gang either way."""
    import sys
    from skypilot_tpu.parallel import mesh as mesh_lib
    mesh_spec = mesh_lib.serving_spec_from_env(tp=args.tp, dp=args.dp)
    logger.info(f'gang follower rank {spec.rank}/{spec.world} '
                f'(gang {spec.gang_id or "?"}) building engine...')
    engine = build_engine(
        args.model, max_batch=args.max_batch, max_seq=args.max_seq,
        model_path=args.model_path, quantize=args.quantize,
        kv_cache=args.kv_cache, kv_cache_dtype=args.kv_cache_dtype,
        page_size=args.page_size,
        decode_impl=getattr(args, 'decode_impl', None),
        prefill_w8a8=args.prefill_w8a8,
        prefill_chunk_tokens=args.prefill_chunk_tokens,
        decode_priority_ratio=args.decode_priority_ratio,
        decode_steps_per_call=getattr(args, 'decode_steps_per_call',
                                      None),
        speculate_k=args.speculate_k,
        adapter_slots=getattr(args, 'adapter_slots', 0),
        adapter_dir=getattr(args, 'adapter_dir', None),
        adapter_rank=getattr(args, 'adapter_rank', 8),
        tp=mesh_spec.tp, dp=mesh_spec.dp, gang=spec)
    follower = gang_lib.GangFollower(
        spec, engine,
        faults=faults_lib.make_injector(args.fault_spec))
    cause = follower.run()
    logger.info(f'gang follower rank {spec.rank} exiting: {cause}')
    sys.exit(0 if cause in ('shutdown', 'stopped') else 1)


if __name__ == '__main__':
    main()
