"""Load balancer: the service's public endpoint; proxies requests to
ready replicas.

Role of reference ``SkyServeLoadBalancer`` (``sky/serve/load_balancer.py:
22``): every ``_sync_with_controller`` period (``:72``) it POSTs the
request timestamps collected since the last sync to the controller (the
autoscaler's QPS signal) and receives the current ready-replica URLs;
requests are proxied per the load-balancing policy. Reference stack is
FastAPI+httpx; stdlib http.server + urllib here (the LB does one stream
per request — threads suffice).
"""
from __future__ import annotations

import http.server
import json
import os
import threading
import time
from typing import List, Optional
import urllib.error
import urllib.request

from skypilot_tpu import tpu_logging
from skypilot_tpu.serve import load_balancing_policies as lb_policies

logger = tpu_logging.init_logger(__name__)

_HOP_HEADERS = {'connection', 'keep-alive', 'transfer-encoding', 'host',
                'content-length'}


def _sync_period() -> float:
    return float(os.environ.get('SKYTPU_LB_SYNC', '3'))


class SkyServeLoadBalancer:

    def __init__(self, controller_url: str, port: int,
                 policy_name: str = 'round_robin'):
        self.controller_url = controller_url.rstrip('/')
        self.port = port
        self.policy = lb_policies.make_policy(policy_name)
        self._request_timestamps: List[float] = []
        self._ts_lock = threading.Lock()
        self._stop = threading.Event()
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None

    # ------------------------------------------------------------- sync
    def _sync_once(self) -> None:
        with self._ts_lock:
            timestamps, self._request_timestamps = \
                self._request_timestamps, []
        body = json.dumps({'request_timestamps': timestamps}).encode()
        req = urllib.request.Request(
            self.controller_url + '/controller/load_balancer_sync',
            data=body, headers={'Content-Type': 'application/json'})
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                payload = json.loads(resp.read())
            self.policy.set_ready_replicas(
                payload.get('ready_replica_urls', []))
        except Exception as e:  # pylint: disable=broad-except
            # Keep serving the last known replica set; re-queue the
            # timestamps so the QPS signal survives controller restarts —
            # but only those still inside the autoscaler's QPS window, or
            # memory grows unboundedly across a long controller outage.
            cutoff = time.time() - 60.0
            with self._ts_lock:
                self._request_timestamps = (
                    [t for t in timestamps if t >= cutoff]
                    + self._request_timestamps)
            logger.warning(f'LB sync with controller failed: '
                           f'{type(e).__name__}: {e}')

    def _sync_loop(self) -> None:
        while not self._stop.is_set():
            self._sync_once()
            self._stop.wait(_sync_period())

    # ------------------------------------------------------------- proxy
    def _make_handler(lb):  # noqa: N805
        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = 'HTTP/1.1'

            def log_message(self, *args):
                del args

            def _proxy(self, method: str) -> None:
                with lb._ts_lock:
                    lb._request_timestamps.append(time.time())
                url = lb.policy.select_replica()
                if url is None:
                    body = json.dumps({
                        'error': 'No ready replicas. '
                                 'Use "sky serve status" to check.'
                    }).encode()
                    self.send_response(503)
                    self.send_header('Content-Type', 'application/json')
                    self.send_header('Content-Length', str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                length = int(self.headers.get('Content-Length', 0))
                data = self.rfile.read(length) if length else None
                headers = {k: v for k, v in self.headers.items()
                           if k.lower() not in _HOP_HEADERS}
                req = urllib.request.Request(url + self.path, data=data,
                                             headers=headers, method=method)
                lb.policy.pre_execute(url)
                try:
                    with urllib.request.urlopen(req, timeout=120) as resp:
                        body = resp.read()
                        self.send_response(resp.status)
                        for k, v in resp.headers.items():
                            if k.lower() not in _HOP_HEADERS:
                                self.send_header(k, v)
                        self.send_header('Content-Length', str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                except urllib.error.HTTPError as e:
                    body = e.read()
                    self.send_response(e.code)
                    self.send_header('Content-Length', str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception as e:  # pylint: disable=broad-except
                    body = json.dumps({
                        'error': f'replica unreachable: '
                                 f'{type(e).__name__}: {e}'}).encode()
                    self.send_response(502)
                    self.send_header('Content-Type', 'application/json')
                    self.send_header('Content-Length', str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                finally:
                    lb.policy.post_execute(url)

            def do_GET(self):  # noqa: N802
                self._proxy('GET')

            def do_POST(self):  # noqa: N802
                self._proxy('POST')

        return Handler

    # ---------------------------------------------------------- lifecycle
    def start(self) -> None:
        handler = self._make_handler()
        self._httpd = http.server.ThreadingHTTPServer(
            ('0.0.0.0', self.port), handler)
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        threading.Thread(target=self._sync_loop, daemon=True).start()
        logger.info(f'Load balancer on port {self.port} → '
                    f'{self.controller_url}')

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
