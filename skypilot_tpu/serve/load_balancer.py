"""Load balancer: the service's public endpoint; proxies requests to
ready replicas.

Role of reference ``SkyServeLoadBalancer`` (``sky/serve/load_balancer.py:
22``): every ``_sync_with_controller`` period (``:72``) it POSTs the
request timestamps collected since the last sync to the controller (the
autoscaler's QPS signal) and receives the current ready-replica URLs;
requests are proxied per the load-balancing policy. Reference stack is
FastAPI+httpx; stdlib http.server + urllib here (the LB does one stream
per request — threads suffice).
"""
from __future__ import annotations

import http.server
import json
import os
import threading
import time
from typing import List, Optional
import urllib.error
import urllib.request

from skypilot_tpu import telemetry
from skypilot_tpu import tpu_logging
from skypilot_tpu.serve import load_balancing_policies as lb_policies

logger = tpu_logging.init_logger(__name__)

_HOP_HEADERS = {'connection', 'keep-alive', 'transfer-encoding', 'host',
                'content-length'}


def _failed_before_send(e: Exception) -> bool:
    """True when the error provably happened BEFORE the request reached
    the replica (connect refused / DNS / connect timeout) — the only
    failures safe to retry for non-idempotent methods."""
    import socket
    reason = getattr(e, 'reason', e)
    return isinstance(reason, (ConnectionRefusedError, socket.gaierror))


def _sync_period() -> float:
    return float(os.environ.get('SKYTPU_LB_SYNC', '3'))


class SkyServeLoadBalancer:

    def __init__(self, controller_url: str, port: int,
                 policy_name: str = 'round_robin',
                 tls_certfile: Optional[str] = None,
                 tls_keyfile: Optional[str] = None,
                 max_attempts: int = 3):
        self.controller_url = controller_url.rstrip('/')
        self.port = port
        self.policy = lb_policies.make_policy(policy_name)
        self.tls_certfile = tls_certfile
        self.tls_keyfile = tls_keyfile
        self.max_attempts = max_attempts
        self._request_timestamps: List[float] = []
        self._ts_lock = threading.Lock()
        self._stop = threading.Event()
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        # Telemetry (the shared process registry): proxy traffic,
        # transparent retries, and controller-sync health.
        reg = telemetry.get_registry()
        self._m_requests = reg.counter(
            'skytpu_lb_requests_total', 'Requests proxied by the LB')
        self._m_retries = reg.counter(
            'skytpu_lb_retries_total',
            'Transparent retries after a replica failed before '
            'answering')
        self._m_sync_failures = reg.counter(
            'skytpu_lb_sync_failures_total',
            'Failed controller sync rounds')
        self._h_proxy = reg.histogram(
            'skytpu_lb_request_ms',
            'LB-observed request latency, non-streaming (ms)')

    # ------------------------------------------------------------- sync
    def _sync_once(self) -> None:
        with self._ts_lock:
            timestamps, self._request_timestamps = \
                self._request_timestamps, []
        body = json.dumps({'request_timestamps': timestamps}).encode()
        req = urllib.request.Request(
            self.controller_url + '/controller/load_balancer_sync',
            data=body, headers={'Content-Type': 'application/json'})
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                payload = json.loads(resp.read())
            self.policy.set_ready_replicas(
                payload.get('ready_replica_urls', []))
        except Exception as e:  # pylint: disable=broad-except
            # Keep serving the last known replica set; re-queue the
            # timestamps so the QPS signal survives controller restarts —
            # but only those still inside the autoscaler's QPS window, or
            # memory grows unboundedly across a long controller outage.
            cutoff = time.time() - 60.0
            with self._ts_lock:
                self._request_timestamps = (
                    [t for t in timestamps if t >= cutoff]
                    + self._request_timestamps)
            self._m_sync_failures.inc()
            logger.warning(f'LB sync with controller failed: '
                           f'{type(e).__name__}: {e}')

    def _sync_loop(self) -> None:
        while not self._stop.is_set():
            self._sync_once()
            self._stop.wait(_sync_period())

    # ------------------------------------------------------------- proxy
    def _make_handler(lb):  # noqa: N805
        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = 'HTTP/1.1'
            # Socket-op timeout (graftcheck GC107): a slow-loris client
            # (or one that stops reading mid-proxy) must not pin an LB
            # thread forever. Set above the 120s upstream urlopen
            # timeout so healthy long requests are never cut by the LB
            # first.
            timeout = 150

            def log_message(self, *args):
                del args

            def _send_json(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _stream_response(self, resp) -> None:
                """Pass a streaming (SSE/chunk) response through as it
                arrives; the connection closes to mark the end (no
                Content-Length is known up front)."""
                self.send_response(resp.status)
                for k, v in resp.headers.items():
                    if k.lower() not in _HOP_HEADERS:
                        self.send_header(k, v)
                self.send_header('Connection', 'close')
                self.end_headers()
                while True:
                    chunk = resp.read1(65536)
                    if not chunk:
                        break
                    self.wfile.write(chunk)
                    self.wfile.flush()
                self.close_connection = True

            def _proxy(self, method: str) -> None:
                t_start = time.monotonic()
                lb._m_requests.inc()
                with lb._ts_lock:
                    lb._request_timestamps.append(time.time())
                length = int(self.headers.get('Content-Length', 0))
                data = self.rfile.read(length) if length else None
                headers = {k: v for k, v in self.headers.items()
                           if k.lower() not in _HOP_HEADERS}

                # A replica dying mid-connect is retried transparently on
                # another replica (reference LB behavior); an HTTP error
                # response is NOT retried — the replica answered.
                tried = set()
                last_err: Optional[Exception] = None
                responded = False       # bytes already sent to client?
                for _ in range(lb.max_attempts):
                    url = lb.policy.select_replica(exclude=tried)
                    if url is None:
                        break
                    tried.add(url)
                    req = urllib.request.Request(
                        url + self.path, data=data, headers=headers,
                        method=method)
                    lb.policy.pre_execute(url)
                    try:
                        with urllib.request.urlopen(req,
                                                    timeout=120) as resp:
                            ctype = resp.headers.get('Content-Type', '')
                            if ('text/event-stream' in ctype
                                    or 'chunked' in (resp.headers.get(
                                        'Transfer-Encoding') or '')):
                                responded = True
                                self._stream_response(resp)
                                return
                            # Read the FULL body before sending anything
                            # client-ward: a mid-read failure here is
                            # still safely retryable.
                            body = resp.read()
                            status, rheaders = resp.status, resp.headers
                        responded = True
                        self.send_response(status)
                        for k, v in rheaders.items():
                            if k.lower() not in _HOP_HEADERS:
                                self.send_header(k, v)
                        self.send_header('Content-Length', str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        lb._h_proxy.observe(
                            (time.monotonic() - t_start) * 1e3)
                        return
                    except urllib.error.HTTPError as e:
                        # The replica ANSWERED; pass its error through —
                        # replaying a side-effectful request is wrong.
                        body = e.read()
                        responded = True
                        self.send_response(e.code)
                        self.send_header('Content-Length', str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    except Exception as e:  # pylint: disable=broad-except
                        if responded:
                            # Mid-stream death (or client disconnect)
                            # AFTER bytes went out: the response cannot
                            # be restarted and the request must not be
                            # replayed — drop the connection.
                            logger.warning(
                                f'stream to/from {url} broke mid-response'
                                f' ({type(e).__name__}: {e}); closing')
                            self.close_connection = True
                            return
                        if method != 'GET' and not _failed_before_send(e):
                            # The replica may have EXECUTED this request
                            # (it died while we read the response);
                            # replaying a non-idempotent method would
                            # run it twice. Surface the failure instead.
                            self._send_json(502, {
                                'error': f'replica failed mid-request '
                                         f'({type(e).__name__}: {e}); '
                                         'not retried (non-idempotent)'})
                            return
                        last_err = e
                        lb._m_retries.inc()
                        logger.warning(
                            f'replica {url} failed before answering '
                            f'({type(e).__name__}: {e}); retrying on '
                            f'another replica')
                    finally:
                        lb.policy.post_execute(url)
                if last_err is not None:
                    self._send_json(502, {
                        'error': f'replicas unreachable after '
                                 f'{len(tried)} attempt(s): '
                                 f'{type(last_err).__name__}: {last_err}'})
                else:
                    self._send_json(503, {
                        'error': 'No ready replicas. '
                                 'Use "sky serve status" to check.'})

            def do_GET(self):  # noqa: N802
                self._proxy('GET')

            def do_POST(self):  # noqa: N802
                self._proxy('POST')

        return Handler

    # ---------------------------------------------------------- lifecycle
    def start(self) -> None:
        handler = self._make_handler()
        self._httpd = http.server.ThreadingHTTPServer(
            ('0.0.0.0', self.port), handler)
        scheme = 'http'
        if self.tls_certfile and self.tls_keyfile:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile=self.tls_certfile,
                                keyfile=self.tls_keyfile)
            self._httpd.socket = ctx.wrap_socket(self._httpd.socket,
                                                 server_side=True)
            scheme = 'https'
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        threading.Thread(target=self._sync_loop, daemon=True).start()
        logger.info(f'Load balancer ({scheme}) on port {self.port} → '
                    f'{self.controller_url}')

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
