"""Load balancer: the service's public endpoint; proxies requests to
ready replicas.

Role of reference ``SkyServeLoadBalancer`` (``sky/serve/load_balancer.py:
22``): every ``_sync_with_controller`` period (``:72``) it POSTs the
request timestamps collected since the last sync to the controller (the
autoscaler's QPS signal) and receives the current ready-replica URLs;
requests are proxied per the load-balancing policy. Reference stack is
FastAPI+httpx; stdlib http.server + urllib here (the LB does one stream
per request — threads suffice).

Fault tolerance (round 7, ``docs/robustness.md``): the LB owns the
**zero-lost-requests** contract — under a replica crash, preemption, or
injected fault, every accepted request either completes or gets a clean
retryable error with ``Retry-After``:

- **In-flight recovery.** A streaming ``/generate`` request with a
  token-id prompt is *recoverable*: the LB parses the SSE events it
  forwards, and when the upstream replica dies mid-stream (transport
  break or a retryable error event) it resubmits the request to a
  surviving replica as ``original prompt + tokens generated so far``
  (the prefix cache makes the recompute cheap, and greedy decode
  continues byte-identically), then keeps feeding the SAME client
  stream. The client sees one uninterrupted stream and one ``done``
  event carrying the full merged token list.
- **Idempotent request keys.** The LB mints an ``X-Request-ID`` for
  recoverable requests (client-supplied keys pass through). Replicas
  dedupe completed keys, so a replayed request returns the same answer
  instead of executing twice — which makes mid-request failures safe
  to retry (the *hedged retry* extension of ``_failed_before_send``:
  un-keyed non-idempotent requests still refuse the replay).
- **Retryable replica refusals.** A replica answering 503 (loading /
  draining / failed) did not execute the request: the LB transparently
  retries it on another replica, and only passes the 503 through (with
  its ``Retry-After``) when every replica refused. Scheduler 429s pass
  through unmodified — including their ``Retry-After``.
- **No-replica 503.** The LB's own 503 carries a JSON error body and a
  ``Retry-After`` derived from the controller's probe/launch backoff
  state (shipped on every sync).

LB autonomy during a controller outage (round 15,
``docs/robustness.md`` "controller failure domain"): the controller
is a coordinator, not a dependency —

- **Stale-while-revalidate.** A failed sync keeps the last-synced
  replica set serving; ``skytpu_lb_sync_age_seconds`` gauges how old
  that view is and ``skytpu_lb_controller_up`` flips to 0 (with one
  loud bounded-staleness alarm log) once the age crosses
  ``SKYTPU_LB_MAX_STALENESS`` (default 30 s).
- **Local eviction.** The LB removes a replica from its OWN rotation —
  no controller input — when the replica proves dead to the data
  plane: a connection-level refusal or a mid-stream death that
  recovery migrated off. Evictions are TTL-bounded
  (``SKYTPU_LB_EVICT_TTL``, default 120 s), so a false positive costs
  one TTL, never permanent capacity.
- **Reconcile, not clobber.** When the controller returns, its list is
  re-applied MINUS still-live local evictions (the controller's view
  may predate the deaths the LB watched happen); an eviction record is
  dropped once the controller itself stops listing the replica (it
  caught up) or the TTL expires.
"""
from __future__ import annotations

import http.server
import json
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Set
import urllib.error
import urllib.parse
import urllib.request
import uuid

from skypilot_tpu import telemetry
from skypilot_tpu import tpu_logging
from skypilot_tpu.serve import faults as faults_lib
from skypilot_tpu.serve import lb_ring
from skypilot_tpu.serve import load_balancing_policies as lb_policies
from skypilot_tpu.serve import wire
from skypilot_tpu.telemetry import tracing

logger = tpu_logging.init_logger(__name__)

_HOP_HEADERS = {'connection', 'keep-alive', 'transfer-encoding', 'host',
                'content-length'}


class _ClientGone(Exception):
    """The DOWNSTREAM client broke mid-stream: abort forwarding (and
    any migration) — there is nobody left to answer."""


def _failed_before_send(e: Exception) -> bool:
    """True when the error provably happened BEFORE the request reached
    the replica (connect refused / reset during connect / DNS) — the
    only failures safe to retry for non-idempotent, un-keyed methods.
    Requests carrying an idempotency key retry on ANY pre-response
    failure instead (the replica-side key dedupe makes the replay
    return one answer) — see ``_retry_safe``."""
    import socket
    reason = getattr(e, 'reason', e)
    return isinstance(reason, (ConnectionRefusedError,
                               ConnectionAbortedError, socket.gaierror))


def _retry_safe(method: str, e: Exception, has_key: bool) -> bool:
    """May this failed attempt be replayed on another replica? GETs are
    idempotent by definition; keyed requests by construction (replica
    dedupe); everything else only when the failure provably preceded
    the send."""
    return method == 'GET' or has_key or _failed_before_send(e)


def _sync_period() -> float:
    return float(os.environ.get('SKYTPU_LB_SYNC', '3'))


def _max_staleness() -> float:
    """Bounded-staleness alarm threshold: how long the LB may serve
    from its last controller sync before the outage is alarmed (the
    serving itself continues — the alarm is for operators)."""
    return float(os.environ.get('SKYTPU_LB_MAX_STALENESS', '30'))


def _evict_ttl() -> float:
    """How long a locally-evicted replica stays out of rotation when
    the controller keeps listing it (a stale controller view). A false
    eviction costs at most this; a real death is usually confirmed by
    the controller's own probes well before it expires."""
    return float(os.environ.get('SKYTPU_LB_EVICT_TTL', '120'))


class SkyServeLoadBalancer:

    def __init__(self, controller_url: str, port: int,
                 policy_name: str = 'round_robin',
                 tls_certfile: Optional[str] = None,
                 tls_keyfile: Optional[str] = None,
                 max_attempts: int = 3,
                 lb_id: Optional[str] = None,
                 advertise_url: Optional[str] = None):
        self.controller_url = controller_url.rstrip('/')
        self.port = port
        self.policy = lb_policies.make_policy(policy_name)
        self.tls_certfile = tls_certfile
        self.tls_keyfile = tls_keyfile
        self.max_attempts = max_attempts
        # Horizontal LB tier: this LB's identity in the consistent-
        # hash ring (and its probe-jitter seed), plus the URL peers
        # reach it at for idempotency-key handoff. Peers arrive on
        # every controller sync (``lb_peers``); until then the ring
        # is just this LB.
        self.lb_id = (lb_id or os.environ.get('SKYTPU_LB_ID')
                      or f'lb-{uuid.uuid4().hex[:8]}')
        self.advertise_url = (
            advertise_url or os.environ.get('SKYTPU_LB_URL')
            or f'http://127.0.0.1:{port}').rstrip('/')
        self._ring = lb_ring.HashRing()
        self._ring.set_members({self.lb_id: self.advertise_url})
        # Completed keyed requests (request_key -> answering replica
        # url): the LB-side idempotency LRU. A replay routes back to
        # the replica whose own key LRU returns the recorded answer —
        # and the record lives at the key's RING OWNER, so a replay
        # landing on a different LB still dedupes.
        self._completed = lb_policies.BoundedStore(
            8192, ttl_s=600.0, name='lb_completed')
        self._completed_lock = threading.Lock()
        set_ident = getattr(self.policy, 'set_probe_identity', None)
        if set_ident is not None:
            set_ident(self.lb_id)
        self._request_timestamps: List[float] = []
        # Parallel SLO-tier tags ('' = unknown): the controller-side
        # forecaster keeps per-tier arrival series so forecast-aware
        # scaling can see tier mix shifts, not just totals.
        self._request_tiers: List[str] = []
        self._ts_lock = threading.Lock()
        self._stop = threading.Event()
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        # Telemetry (the shared process registry): proxy traffic,
        # transparent retries, and controller-sync health.
        reg = telemetry.get_registry()
        self._m_requests = reg.counter(
            'skytpu_lb_requests_total', 'Requests proxied by the LB')
        self._m_retries = reg.counter(
            'skytpu_lb_retries_total',
            'Transparent retries after a replica failed before '
            'answering')
        self._m_sync_failures = reg.counter(
            'skytpu_lb_sync_failures_total',
            'Failed controller sync rounds')
        self._h_proxy = reg.histogram(
            'skytpu_lb_request_ms',
            'LB-observed request latency, non-streaming (ms)')
        # Robustness series (stable schema: all registered up front).
        faults_lib.register_metrics()
        self._m_migrated = {
            outcome: reg.counter(
                'skytpu_requests_migrated_total',
                'In-flight requests migrated off a failed replica',
                outcome=outcome)
            for outcome in faults_lib.MIGRATION_OUTCOMES}
        self._h_recovery = reg.histogram(
            'skytpu_replica_recovery_seconds',
            'Mid-stream migration: replica failure detected to stream '
            'resumed on a surviving replica (s)',
            buckets=telemetry.registry.DEFAULT_SECONDS_BUCKETS)
        # Controller-outage autonomy (round 15): sync-age/health
        # gauges, the bounded-staleness alarm latch, and the local
        # eviction table (url -> monotonic eviction time). The LB's
        # last-synced controller list is kept separately from the
        # policy's live set so reconciliation can re-apply it minus
        # evictions at any time.
        self._g_sync_age = reg.gauge(
            'skytpu_lb_sync_age_seconds',
            'Age of the LB\'s last successful controller sync (the '
            'staleness of the replica view it is serving from)')
        self._g_ctrl_up = reg.gauge(
            'skytpu_lb_controller_up',
            'Controller health as the LB sees it (1 = syncing; 0 = '
            'outage past the bounded-staleness alarm)')
        self._m_local_evict = reg.counter(
            'skytpu_lb_local_evictions_total',
            'Replicas the LB evicted from rotation on its own '
            'data-plane evidence (no controller input)')
        # Prefix-affinity + horizontal-LB series (PR 18; stable
        # schema — registered here, zeros from the first scrape).
        self._m_affinity = {
            outcome: reg.counter(
                'skytpu_lb_affinity_hits_total',
                'Prefix-affinity routing outcomes (hit = routed to '
                'the longest-match replica; miss = no replica held '
                'the prefix; migrated = load override with a '
                'proactive SKPF prefix migration)',
                outcome=outcome)
            for outcome in ('hit', 'miss', 'migrated')}
        self._m_recompute = reg.counter(
            'skytpu_prefix_recompute_tokens_total',
            'Prefix tokens the chosen replica recomputes although '
            'another replica had them cached (affinity routing '
            'losses, un-migrated)')
        self._g_ring = reg.gauge(
            'skytpu_lb_ring_size',
            'Live LB-tier members in the consistent-hash ring (0 '
            'until the first controller sync)')
        self._m_handoff = reg.counter(
            'skytpu_lb_handoff_total',
            'Idempotency-key records exchanged with peer LBs '
            '(ring-owner pushes accepted + remote lookup hits)')
        if isinstance(self.policy, lb_policies.PrefixAffinityPolicy):
            self.policy.configure_affinity_observer(
                self._note_affinity)
            self.policy.configure_migration(self._migrate_prefix)
        self._evict_lock = threading.Lock()
        self._evicted: Dict[str, float] = {}
        self._last_ready: List[str] = []
        self._last_sync_ok: Optional[float] = None
        self._started_at = time.monotonic()
        self._staleness_alarmed = False
        # Fault injection (serve/faults.py): resolved once; None keeps
        # the hooks at a single attribute check.
        self._faults = faults_lib.get_injector()
        # Retry-After hint for the LB's own 503 (no ready replicas),
        # refreshed from the controller's probe/launch backoff state on
        # every sync. Plain int write — single-writer sync loop.
        self._retry_after_hint = 5
        # The controller's (tp, dp) replica plan, refreshed on every
        # sync (single-writer) — part of the /lb/replicas view next to
        # the live per-replica mesh probes.
        self._replica_parallelism: Dict[str, Any] = {}
        # Controller-planned disaggregation roles (url -> role),
        # refreshed on every sync; the phase-aware policy uses them as
        # the cold-probe fallback and the replica view surfaces them.
        self._replica_roles: Dict[str, str] = {}
        # Gang health blocks (rank0 url -> gang view), refreshed on
        # every sync: a gang presents exactly ONE routable endpoint;
        # the policies use this to keep follower addresses out of
        # probe sweeps and the replica view carries it for health
        # accounting.
        self._replica_gangs: Dict[str, Any] = {}
        # Fleet tracing: the LB mints the 128-bit trace id for every
        # request that arrives without a client-supplied
        # ``X-Skytpu-Trace`` (seedable for sim determinism), records
        # its own hop legs (dispatch, retry, migration — with cause)
        # in a PRIVATE buffer, and ships completed legs to the
        # controller on the sync path. The buffer is private rather
        # than the process-global one so an in-process replica's
        # traces are never double-shipped through the LB.
        seed = os.environ.get('SKYTPU_TRACE_SEED')
        self._trace_rng = random.Random(int(seed)) if seed else None
        self._trace_buf = tracing.TraceBuffer()
        self._trace_cursor = 0
        # Controller-computed per-tier SLO burn/attainment (refreshed
        # on every sync; single-writer sync loop).
        self._last_slo: Dict[str, Any] = {}

    # ------------------------------------------------------------- sync
    def _sync_once(self) -> None:
        with self._ts_lock:
            timestamps, self._request_timestamps = \
                self._request_timestamps, []
            tiers, self._request_tiers = self._request_tiers, []
        # Piggyback the LB's completed trace legs (and its clock, for
        # controller-side skew accounting) on the sync it already
        # makes. The cursor advances optimistically: a failed sync
        # drops that batch (at-most-once) rather than duplicating
        # legs in the controller's trace store on the retry.
        self._trace_cursor, lb_traces = \
            self._trace_buf.summaries_since(self._trace_cursor)
        sync_body = {'request_timestamps': timestamps,
                     'request_tiers': tiers,
                     'lb_id': self.lb_id,
                     'lb_url': self.advertise_url,
                     'telemetry': {
                         'clock': {'wall': time.time(),
                                   'monotonic': time.monotonic()},
                         'traces': lb_traces,
                     }}
        try:
            payload = wire.post_json(
                self.controller_url + '/controller/load_balancer_sync',
                sync_body, timeout=5)
            self._last_sync_ok = time.monotonic()
            self._g_sync_age.set(0.0)
            self._g_ctrl_up.set(1)
            if self._staleness_alarmed:
                logger.warning('controller is back; reconciling the '
                               'replica view (stale-while-revalidate '
                               'mode ends)')
                self._staleness_alarmed = False
            # Reconcile, don't clobber: the controller's list is
            # authority for MEMBERSHIP, but a replica the LB watched
            # die stays evicted until the controller stops listing it
            # or the eviction TTL expires.
            self._last_ready = list(
                payload.get('ready_replica_urls', []))
            self._apply_ready_urls()
            # Fleet SLO view (per-tier burn/attainment), computed
            # controller-side; surfaced in the LB's replica view.
            self._last_slo = payload.get('slo') or {}
            hint = payload.get('retry_after_s')
            if hint:
                self._retry_after_hint = max(1, int(hint))
            par = payload.get('replica_parallelism')
            if par is not None:
                self._replica_parallelism = par
            roles = payload.get('replica_roles')
            if roles is not None:
                self._replica_roles = dict(roles)
                self.policy.set_replica_roles(roles)
            gangs = payload.get('replica_gangs')
            if gangs is not None:
                self._replica_gangs = dict(gangs)
                self.policy.set_replica_gangs(gangs)
            # Consistent-hash ring membership from the shared sync
            # feed: a crashed peer ages out of the controller's
            # registry and key ownership converges on the survivors;
            # an absent/old controller leaves a single-member ring.
            peers = payload.get('lb_peers') or {}
            peers = {str(k): str(v) for k, v in peers.items()}
            peers.setdefault(self.lb_id, self.advertise_url)
            if set(peers) != set(self._ring.members):
                logger.info(
                    f'LB ring membership now {sorted(peers)}')
            self._ring.set_members(peers)
            self._g_ring.set(len(peers))
        except Exception as e:  # pylint: disable=broad-except
            # Keep serving the last known replica set; re-queue the
            # timestamps so the QPS signal survives controller restarts —
            # but only those still inside the autoscaler's QPS window, or
            # memory grows unboundedly across a long controller outage.
            cutoff = time.time() - 60.0
            keep = [(t, tr) for t, tr in zip(timestamps, tiers)
                    if t >= cutoff]
            with self._ts_lock:
                self._request_timestamps = (
                    [t for t, _ in keep] + self._request_timestamps)
                self._request_tiers = (
                    [tr for _, tr in keep] + self._request_tiers)
            self._m_sync_failures.inc()
            # Stale-while-revalidate accounting: the last-synced view
            # keeps serving; the age gauge tells operators how stale
            # it is, and one loud alarm fires when the outage crosses
            # the staleness bound.
            age = time.monotonic() - (self._last_sync_ok
                                      if self._last_sync_ok is not None
                                      else self._started_at)
            self._g_sync_age.set(age)
            if age > _max_staleness():
                self._g_ctrl_up.set(0)
                if not self._staleness_alarmed:
                    self._staleness_alarmed = True
                    logger.error(
                        f'controller unreachable for {age:.0f}s '
                        f'(> SKYTPU_LB_MAX_STALENESS='
                        f'{_max_staleness():.0f}s): serving from the '
                        'stale replica view; dead replicas are '
                        'evicted locally from data-plane evidence')
            logger.warning(f'LB sync with controller failed: '
                           f'{type(e).__name__}: {e}')

    def _sync_loop(self) -> None:
        while not self._stop.is_set():
            self._sync_once()
            self._stop.wait(_sync_period())

    # ----------------------------------------------- local evictions
    def _apply_ready_urls(self) -> None:
        """Install the effective rotation: the controller's last list
        minus still-live local evictions. Eviction records are dropped
        when the controller no longer lists the replica (its probes
        caught up with the death) or their TTL expired."""
        now = time.monotonic()
        listed = set(self._last_ready)
        with self._evict_lock:
            self._evicted = {
                u: t for u, t in self._evicted.items()
                if u in listed and now - t < _evict_ttl()}
            evicted = set(self._evicted)
        self.policy.set_ready_replicas(
            [u for u in self._last_ready if u not in evicted])

    def note_replica_dead(self, url: Optional[str],
                          reason: str) -> None:
        """Data-plane death evidence: take ``url`` out of the LB's OWN
        rotation immediately — no controller round-trip. Called when a
        replica refuses connections or dies mid-stream (the recovery
        path already migrated the work); during a controller outage
        this is the ONLY way dead capacity leaves rotation."""
        if not url:
            return
        url = url.rstrip('/')
        with self._evict_lock:
            if url in self._evicted:
                return
            self._evicted[url] = time.monotonic()
        self._m_local_evict.inc()
        logger.warning(f'locally evicting replica {url} from rotation '
                       f'({reason}); TTL {_evict_ttl():.0f}s or until '
                       'the controller confirms')
        self._apply_ready_urls()

    # ------------------------------------------- affinity + LB tier
    def _note_affinity(self, outcome: str,
                       recompute_tokens: int) -> None:
        """Affinity observer the prefix_affinity policy calls on every
        routed request (outside its lock)."""
        counter = self._m_affinity.get(outcome)
        if counter is not None:
            counter.inc()
        if recompute_tokens > 0:
            self._m_recompute.inc(recompute_tokens)

    def _migrate_prefix(self, src: str, dst: str, chain_hash: str,
                        n_tokens: int) -> bool:
        """Proactive prefix migration, fire-and-forget: fetch the
        chain's CRC-checked SKPF blob from ``src`` and land it on
        ``dst``'s ``/kv/warmup`` — off-thread, so the request that
        triggered it routes immediately (it benefits the NEXT turn
        of the session, not this one)."""
        def _ship() -> None:
            try:
                with urllib.request.urlopen(
                        f'{src}/kv/prefix/export?hash={chain_hash}',
                        timeout=30) as resp:
                    blob = resp.read()
                landed = wire.post_bytes(dst + '/kv/warmup', blob,
                                         timeout=30)
                logger.info(
                    f'migrated prefix {chain_hash[:12]} '
                    f'({n_tokens} token(s)) {src} -> {dst}: '
                    f'{landed.get("warmed_rows", 0)} row(s) warm')
            except Exception as e:  # pylint: disable=broad-except
                logger.warning(
                    f'prefix migration {src} -> {dst} failed: '
                    f'{type(e).__name__}: {e}')
        threading.Thread(target=_ship, daemon=True).start()
        return True

    def record_completed_key(self, key: str, replica_url: str,
                             trace: Optional[str] = None) -> None:
        """Record which replica answered ``key`` — locally, and at the
        key's ring owner when that is a peer (fire-and-forget push;
        the authoritative dedupe stays replica-side). ``trace`` is the
        answering request's wire trace header: the LB↔LB handoff is a
        hop of that request's fleet trace."""
        with self._completed_lock:
            self._completed.put(key, replica_url)
        owner, owner_url = self._ring.owner_url(key)
        if owner is None or owner == self.lb_id or not owner_url:
            return

        def _push() -> None:
            try:
                wire.post_json(owner_url + '/lb/idempotency',
                               {'key': key, 'url': replica_url},
                               timeout=5, trace=trace)
            except Exception as e:  # pylint: disable=broad-except
                logger.debug(
                    f'idempotency push for {key} to {owner} failed: '
                    f'{type(e).__name__}: {e}')
        threading.Thread(target=_push, daemon=True).start()

    def accept_completed_key(self, key: str,
                             replica_url: str) -> None:
        """A peer LB pushed a completed key this LB owns on the ring."""
        with self._completed_lock:
            self._completed.put(key, replica_url)
        self._m_handoff.inc()

    def lookup_completed_key(self, key: str) -> Optional[str]:
        """The replica that already answered ``key``, if any LB in the
        tier knows: local LRU first, then the key's ring owner. Only
        called for CLIENT-supplied keys (a freshly minted key cannot
        be a replay)."""
        with self._completed_lock:
            hit = self._completed.get(key)
        if hit:
            return hit
        owner, owner_url = self._ring.owner_url(key)
        if owner is None or owner == self.lb_id or not owner_url:
            return None
        try:
            q = urllib.parse.urlencode({'key': key})
            with urllib.request.urlopen(
                    f'{owner_url}/lb/idempotency?{q}',
                    timeout=2) as resp:
                payload = json.loads(resp.read())
        except Exception as e:  # pylint: disable=broad-except
            # Owner unreachable is routine during an LB crash window —
            # fall back to fresh dispatch (at-least-once, idempotent).
            logger.debug(f'idempotency lookup at {owner_url} failed: {e}')
            return None
        url = payload.get('url')
        if url:
            self._m_handoff.inc()
            with self._completed_lock:
                self._completed.put(key, url)
            return url
        return None

    # --------------------------------------------------------- recovery
    @staticmethod
    def _recoverable(method: str, path: str,
                     data: Optional[bytes]) -> Optional[Dict[str, Any]]:
        """The parsed payload when this request supports in-flight
        recovery — a ``/generate`` POST with a token-id prompt (the
        continuation must splice generated token ids onto the prompt,
        which a text prompt cannot express). Streaming payloads also
        migrate mid-stream; non-streaming ones get the keyed hedged
        retry."""
        if method != 'POST' or path != '/generate' or not data:
            return None
        try:
            payload = json.loads(data)
        except (ValueError, UnicodeDecodeError):
            return None
        if not isinstance(payload, dict):
            return None
        prompt = payload.get('prompt')
        if (not isinstance(prompt, list) or not prompt
                or not all(isinstance(t, int) for t in prompt)):
            return None
        return payload

    @staticmethod
    def _remaining_budget(payload: Dict[str, Any],
                          tokens: List[int]) -> int:
        """Decode tokens still owed after ``tokens`` already streamed."""
        budget = int(payload.get('max_new_tokens',
                                 payload.get('max_tokens', 128)))
        return budget - len(tokens)

    # ------------------------------------------------------------- proxy
    def _make_handler(lb):  # noqa: N805
        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = 'HTTP/1.1'
            # Socket-op timeout (graftcheck GC107): a slow-loris client
            # (or one that stops reading mid-proxy) must not pin an LB
            # thread forever. Set above the 120s upstream urlopen
            # timeout so healthy long requests are never cut by the LB
            # first.
            timeout = 150

            def log_message(self, *args):
                del args

            def _send_json(self, code: int, payload: dict,
                           extra_headers: Optional[dict] = None) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(body)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _forward_http_error(self, code: int, body: bytes,
                                    rheaders) -> None:
                """Pass a replica's HTTP error through — headers
                included, so scheduler 429/503 Retry-After values reach
                the client unmodified (a retryable code without one
                gets the LB's backoff-derived hint)."""
                self.send_response(code)
                seen_retry_after = False
                for k, v in rheaders.items():
                    if k.lower() in _HOP_HEADERS:
                        continue
                    if k.lower() == 'retry-after':
                        seen_retry_after = True
                    self.send_header(k, v)
                if not seen_retry_after and code in (429, 503):
                    self.send_header('Retry-After',
                                     str(lb._retry_after_hint))
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _stream_response(self, resp) -> None:
                """Pass a streaming (SSE/chunk) response through as it
                arrives; the connection closes to mark the end (no
                Content-Length is known up front)."""
                self.send_response(resp.status)
                for k, v in resp.headers.items():
                    if k.lower() not in _HOP_HEADERS:
                        self.send_header(k, v)
                self.send_header('Connection', 'close')
                self.end_headers()
                while True:
                    chunk = resp.read1(65536)
                    if not chunk:
                        break
                    self.wfile.write(chunk)
                    self.wfile.flush()
                self.close_connection = True

            # ---------------------------------------- in-flight recovery
            def _emit_event(self, ev: dict) -> None:
                try:
                    self.wfile.write(
                        f'data: {json.dumps(ev)}\n\n'.encode())
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError) as e:
                    raise _ClientGone(str(e)) from e

            def _forward_sse(self, resp, tokens: list,
                             break_after: Optional[int],
                             info: Optional[dict] = None) -> bool:
                """Forward one upstream SSE leg, accumulating token ids
                into ``tokens``. Returns True when the stream finished
                cleanly (its ``done`` event was forwarded with the full
                MERGED token list); False when the upstream broke or
                reported a retryable error — the caller migrates.
                ``info`` (optional dict) receives the error event's
                ``failed_upstream`` when present: a disaggregated
                prefill relay naming its DEAD decode worker — the
                relay itself is healthy and must stay eligible.
                Raises :class:`_ClientGone` when the downstream client
                went away."""
                events = 0
                try:
                    for raw in resp:
                        if not raw.startswith(b'data:'):
                            continue
                        try:
                            ev = json.loads(raw[5:].strip())
                        except ValueError:
                            continue
                        if 'error' in ev:
                            # Replica-side failure event (engine died /
                            # drain deadline): migrate, don't forward.
                            logger.warning(
                                f'upstream stream error: {ev["error"]}')
                            if info is not None:
                                info['error'] = str(ev['error'])
                                if ev.get('failed_upstream'):
                                    info['failed_upstream'] = \
                                        str(ev['failed_upstream'])
                            return False
                        if ev.get('done'):
                            done = dict(ev)
                            done['tokens'] = list(tokens)
                            self._emit_event(done)
                            return True
                        if 'token' in ev:
                            tokens.append(int(ev['token']))
                            self._emit_event(ev)
                            events += 1
                            if (break_after is not None
                                    and events >= break_after):
                                # Injected partial_response: the
                                # upstream "dies" mid-stream, with a
                                # nonzero generated prefix.
                                resp.close()
                                return False
                        else:
                            self._emit_event(ev)
                except _ClientGone:
                    raise
                except Exception as e:  # pylint: disable=broad-except
                    logger.warning(f'upstream stream broke: '
                                   f'{type(e).__name__}: {e}')
                    if info is not None:
                        # Transport-level death (vs a replica-side
                        # error EVENT, which an alive replica sent):
                        # the caller evicts the upstream locally.
                        info['transport_break'] = True
                    return False
                return False       # EOF without a done event: broken

            def _stream_recover(self, resp, url: str, payload: dict,
                                headers: dict, tried: Set[str]) -> None:
                """Forward a *recoverable* stream, migrating it to a
                surviving replica when the upstream dies mid-stream:
                the resubmission carries ``original prompt + tokens so
                far`` and the remaining decode budget, under the same
                idempotency key. The client sees ONE stream and one
                ``done`` event with the merged tokens; if every replica
                is exhausted it sees a clean retryable error event."""
                self.send_response(resp.status)
                for k, v in resp.headers.items():
                    if k.lower() not in _HOP_HEADERS:
                        self.send_header(k, v)
                self.send_header('Connection', 'close')
                self.end_headers()
                tokens: list = []
                break_after = None
                if lb._faults is not None:
                    rule = lb._faults.fire('proxy_stream')
                    if rule is not None and \
                            rule.kind == 'partial_response':
                        break_after = rule.after_events or 1
                migrated = False
                leg = resp              # caller's with closes the first
                cur_url = url           # replica serving the live leg
                own_leg = None          # legs we opened get closed here
                info: Dict[str, Any] = {}
                try:
                    while True:
                        info.clear()
                        finished = self._forward_sse(leg, tokens,
                                                     break_after, info)
                        break_after = None    # injected break fires once
                        if finished:
                            if migrated:
                                lb._m_migrated['completed'].inc()
                            return
                        failed = info.get('failed_upstream')
                        # Cause-tagged migration leg for the fleet
                        # trace: WHY this request left its replica.
                        err_text = info.get('error', '')
                        if failed:
                            cause = 'decode_worker_dead'
                        elif info.get('transport_break'):
                            cause = 'replica_crash'
                        elif 'nan' in err_text.lower():
                            cause = 'nan_evicted'
                        else:
                            cause = 'replica_error'
                        if failed:
                            # A disagg prefill relay reported its
                            # DECODE worker dead: exclude that worker,
                            # keep the (healthy) relay eligible for
                            # the resubmit.
                            tried.add(failed.rstrip('/'))
                            tried.discard(cur_url)
                            lb.note_replica_dead(
                                failed, 'relay reported decode '
                                        'worker dead')
                        elif info.get('transport_break'):
                            # The serving replica itself died
                            # mid-stream: out of the LB's own rotation
                            # now — controller confirmation can wait
                            # (or never come, during an outage).
                            lb.note_replica_dead(
                                cur_url, 'died mid-stream')
                        t_fail = time.monotonic()
                        if own_leg is not None:
                            try:
                                own_leg.close()
                            except OSError:
                                pass    # already dead — that's the point
                            own_leg = None
                        trace = getattr(self, '_lb_trace', None)
                        mig = (trace.begin('lb_migrate', cause=cause,
                                           src=cur_url,
                                           tokens_so_far=len(tokens))
                               if trace is not None else None)
                        own_leg, cur_url = self._open_continuation(
                            payload, tokens, headers, tried)
                        if mig is not None:
                            mig.meta['dst'] = cur_url
                            mig.meta['outcome'] = (
                                'resumed' if own_leg is not None
                                else 'exhausted')
                            trace.end('lb_migrate')
                        if own_leg is None:
                            # Budget already exhausted -> the request IS
                            # complete; otherwise: every replica failed.
                            remaining = lb._remaining_budget(payload,
                                                             tokens)
                            if remaining <= 0 and tokens:
                                self._emit_event({'done': True,
                                                  'tokens': tokens,
                                                  'migrated': True})
                                lb._m_migrated['completed'].inc()
                                return
                            lb._m_migrated['failed'].inc()
                            self._emit_event({
                                'error': 'replica failed mid-stream and '
                                         'no surviving replica could '
                                         'resume',
                                'retryable': True,
                                'retry_after_s': lb._retry_after_hint,
                                'tokens_so_far': tokens,
                            })
                            return
                        migrated = True
                        lb._h_recovery.observe(
                            time.monotonic() - t_fail)
                        leg = own_leg
                except _ClientGone:
                    logger.info('client disconnected mid-stream; '
                                'abandoning recovery')
                finally:
                    if own_leg is not None:
                        try:
                            own_leg.close()
                        except OSError:
                            pass    # best-effort close of a dead leg
                    self.close_connection = True

            def _open_continuation(self, payload: dict, tokens: list,
                                   headers: dict, tried: Set[str]):
                """Open the continuation stream on a surviving replica
                (prompt extended with the generated prefix, budget
                reduced). Returns ``(response, replica_url)``, or
                ``(None, None)`` when no replica could take it (or
                nothing remains to decode)."""
                remaining = lb._remaining_budget(payload, tokens)
                if remaining <= 0:
                    return None, None
                cont = dict(payload)
                cont['prompt'] = list(payload['prompt']) + list(tokens)
                cont['max_new_tokens'] = remaining
                cont.pop('max_tokens', None)
                body = json.dumps(cont).encode()
                # The continuation keeps its session identity: the
                # affinity policy routes the resubmit to whichever
                # survivor holds the longest piece of the (original
                # prompt + generated prefix) chain.
                ctx = {'tokens': cont['prompt'],
                       'request_key': headers.get('X-Request-ID')}
                while True:
                    nxt = lb.policy.select_replica(exclude=tried,
                                                   context=ctx)
                    if nxt is None or len(tried) >= lb.max_attempts + 2:
                        return None, None
                    tried.add(nxt)
                    # Disaggregated fleets: the resubmitted
                    # prompt+prefix prefills on a surviving prefill
                    # worker and hands off to a surviving decode
                    # worker — the dead upstream(s) in ``tried`` must
                    # not be re-picked as the handoff target.
                    target = lb.policy.handoff_target(exclude=tried)
                    if target is not None:
                        headers = dict(headers,
                                       **{'X-Handoff-Target': target})
                    else:
                        headers = {k: v for k, v in headers.items()
                                   if k.lower() != 'x-handoff-target'}
                    # ``headers`` already carries the request's
                    # X-Skytpu-Trace (stamped once in _proxy): the
                    # continuation leg joins the same fleet trace.
                    req = wire.build_request(
                        nxt + '/generate', data=body, headers=headers,
                        method='POST')
                    try:
                        leg = wire.urlopen(req, timeout=120)
                    except Exception as e:  # pylint: disable=broad-except
                        logger.warning(
                            f'continuation on {nxt} failed '
                            f'({type(e).__name__}: {e}); trying next')
                        if _failed_before_send(e):
                            lb.note_replica_dead(
                                nxt, 'refused continuation connect')
                        continue
                    logger.info(
                        f'migrated stream to {nxt} with '
                        f'{len(tokens)} generated token(s) '
                        f'({remaining} remaining)')
                    return leg, nxt

            def _proxy(self, method: str) -> None:
                """Trace-owning wrapper: every proxied request runs
                under a fleet trace — adopted from a client-supplied
                ``X-Skytpu-Trace`` or minted here (the LB is the trace
                root for ordinary clients). The LB's own hop legs
                (dispatch, retries, cause-tagged migrations) complete
                into the private buffer and ship on the next
                controller sync."""
                ctx = tracing.parse_trace_header(
                    self.headers.get(wire.TRACE_HEADER))
                tid = (ctx['trace_id'] if ctx
                       else tracing.mint_trace_id(lb._trace_rng))
                trace = tracing.RequestTrace(
                    0, trace_id=tid,
                    parent_span=(ctx or {}).get('parent_span'))
                trace.begin('lb_proxy', lb=lb.lb_id, path=self.path,
                            method=method)
                self._lb_trace = trace
                try:
                    self._proxy_dispatch(method, trace)
                finally:
                    self._lb_trace = None
                    trace.finish()
                    lb._trace_buf.add(trace)

            def _proxy_dispatch(self, method: str, trace) -> None:
                t_start = time.monotonic()
                lb._m_requests.inc()
                with lb._ts_lock:
                    lb._request_timestamps.append(time.time())
                    lb._request_tiers.append(
                        self.headers.get('X-SLO-Tier') or '')
                length = int(self.headers.get('Content-Length', 0))
                data = self.rfile.read(length) if length else None
                headers = {k: v for k, v in self.headers.items()
                           if k.lower() not in _HOP_HEADERS
                           and k.lower() != wire.TRACE_HEADER.lower()}
                # Stamp the outbound hop header once: every dispatch
                # attempt AND every continuation leg opened during
                # mid-stream recovery reuses this dict, so they all
                # carry the same fleet trace id with this LB as the
                # parent hop.
                headers[wire.TRACE_HEADER] = tracing.format_trace_header(
                    trace.trace_id, f'lb:{lb.lb_id}')
                forced_break = False
                if lb._faults is not None:
                    rule = lb._faults.fire('proxy')
                    if rule is not None:
                        if rule.kind == 'slow_response':
                            time.sleep(rule.delay_s)
                        elif rule.kind == 'partial_response':
                            forced_break = True
                # Recoverable request? (streaming /generate, token-id
                # prompt). The LB mints an idempotency key for it, so a
                # replay on another replica returns one answer.
                recover = lb._recoverable(method, self.path, data)
                client_keyed = (
                    self.headers.get('X-Request-ID') is not None)
                req_key = self.headers.get('X-Request-ID')
                if recover is not None and req_key is None:
                    req_key = uuid.uuid4().hex
                    headers['X-Request-ID'] = req_key
                if req_key is not None:
                    trace.meta['request_key'] = req_key
                # Prefix-affinity context: the prompt's token ids let
                # the policy hash the page-grid prefix; the request
                # key pins session stickiness.
                affinity_ctx = None
                if recover is not None:
                    affinity_ctx = {'tokens': recover['prompt'],
                                    'request_key': req_key}
                # Replay dedupe across the LB tier: a client-supplied
                # key may have been answered via ANOTHER LB — the
                # ring owner knows which replica holds the recorded
                # answer.
                preferred: Optional[str] = None
                if client_keyed and req_key:
                    preferred = lb.lookup_completed_key(req_key)

                # A replica dying mid-connect is retried transparently
                # on another replica; an HTTP-503 refusal (loading /
                # draining) never executed and retries too; any other
                # HTTP error passes through — the replica answered.
                tried: Set[str] = set()
                last_err: Optional[Exception] = None
                last_http = None        # (code, body, headers)
                responded = False       # bytes already sent to client?
                for _ in range(lb.max_attempts):
                    if (preferred is not None
                            and preferred not in tried
                            and preferred in lb.policy.ready_replicas):
                        url: Optional[str] = preferred
                        preferred = None
                    else:
                        url = lb.policy.select_replica(
                            exclude=tried, context=affinity_ctx)
                    if url is None:
                        break
                    tried.add(url)
                    if recover is not None:
                        # Phase-aware routing: stamp the decode worker
                        # this prefill should hand its KV to (picked by
                        # live KV-pool headroom). Refreshed per attempt
                        # — a retry must not carry a dead target.
                        target = lb.policy.handoff_target(
                            exclude=tried | {url})
                        if target is not None:
                            headers['X-Handoff-Target'] = target
                        else:
                            headers.pop('X-Handoff-Target', None)
                    req = wire.build_request(
                        url + self.path, data=data, headers=headers,
                        method=method)
                    lb.policy.pre_execute(url)
                    dispatch = trace.begin('lb_dispatch', replica=url)
                    try:
                        if forced_break:
                            # Injected partial_response: the connection
                            # "breaks" before the request lands —
                            # drives the exact retry path a flaky
                            # network does.
                            forced_break = False
                            raise ConnectionResetError(
                                'injected partial_response')
                        with wire.urlopen(req, timeout=120) as resp:
                            ctype = resp.headers.get('Content-Type', '')
                            if ('text/event-stream' in ctype
                                    or 'chunked' in (resp.headers.get(
                                        'Transfer-Encoding') or '')):
                                responded = True
                                if req_key is not None:
                                    lb.record_completed_key(
                                        req_key, url,
                                        trace=headers.get(
                                            wire.TRACE_HEADER))
                                if (recover is not None
                                        and recover.get('stream')):
                                    self._stream_recover(
                                        resp, url, recover, headers,
                                        tried)
                                else:
                                    self._stream_response(resp)
                                return
                            # Read the FULL body before sending anything
                            # client-ward: a mid-read failure here is
                            # still safely retryable.
                            body = resp.read()
                            status, rheaders = resp.status, resp.headers
                        responded = True
                        if req_key is not None and status < 300:
                            lb.record_completed_key(
                                req_key, url,
                                trace=headers.get(wire.TRACE_HEADER))
                        self.send_response(status)
                        for k, v in rheaders.items():
                            if k.lower() not in _HOP_HEADERS:
                                self.send_header(k, v)
                        self.send_header('Content-Length', str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        lb._h_proxy.observe(
                            (time.monotonic() - t_start) * 1e3)
                        return
                    except urllib.error.HTTPError as e:
                        body = e.read()
                        if e.code == 503:
                            # Pre-admission refusal (loading/draining/
                            # failed): nothing executed — try another
                            # replica; the last refusal passes through
                            # (with Retry-After) if all of them refuse.
                            last_http = (e.code, body, e.headers)
                            dispatch.meta['outcome'] = 'refused_503'
                            lb._m_retries.inc()
                            logger.warning(
                                f'replica {url} refused ({e.code}); '
                                'retrying on another replica')
                            continue
                        # The replica ANSWERED; pass its error through
                        # headers included (scheduler 429 Retry-After
                        # reaches the client unmodified).
                        responded = True
                        self._forward_http_error(e.code, body, e.headers)
                        return
                    except Exception as e:  # pylint: disable=broad-except
                        if responded:
                            # Mid-stream death (or client disconnect)
                            # AFTER bytes went out on a non-recoverable
                            # stream: the response cannot be restarted —
                            # drop the connection.
                            logger.warning(
                                f'stream to/from {url} broke mid-response'
                                f' ({type(e).__name__}: {e}); closing')
                            self.close_connection = True
                            return
                        if not _retry_safe(method, e, req_key is not None):
                            # The replica may have EXECUTED this
                            # un-keyed request (it died while we read
                            # the response); replaying could run it
                            # twice. Surface the failure instead.
                            self._send_json(502, {
                                'error': f'replica failed mid-request '
                                         f'({type(e).__name__}: {e}); '
                                         'not retried (non-idempotent; '
                                         'pass X-Request-ID to make it '
                                         'replayable)'})
                            return
                        last_err = e
                        lb._m_retries.inc()
                        dispatch.meta['outcome'] = (
                            'connect_failed' if _failed_before_send(e)
                            else 'replica_crash')
                        if _failed_before_send(e):
                            # Connection-level refusal: the replica
                            # process is gone — out of the LB's own
                            # rotation without waiting for the
                            # controller (which may be down itself).
                            lb.note_replica_dead(
                                url, 'connection refused')
                        logger.warning(
                            f'replica {url} failed before answering '
                            f'({type(e).__name__}: {e}); retrying on '
                            f'another replica')
                    finally:
                        trace.end('lb_dispatch')
                        lb.policy.post_execute(url)
                if last_http is not None:
                    self._forward_http_error(*last_http)
                elif last_err is not None:
                    self._send_json(502, {
                        'error': f'replicas unreachable after '
                                 f'{len(tried)} attempt(s): '
                                 f'{type(last_err).__name__}: {last_err}',
                        'retryable': True,
                        'retry_after_s': lb._retry_after_hint,
                    }, extra_headers={
                        'Retry-After': str(lb._retry_after_hint)})
                else:
                    # No ready replicas: a clean retryable error with a
                    # Retry-After derived from the controller's probe/
                    # launch backoff state (shipped on every sync).
                    self._send_json(503, {
                        'error': 'No ready replicas. '
                                 'Use "sky serve status" to check.',
                        'retryable': True,
                        'retry_after_s': lb._retry_after_hint,
                    }, extra_headers={
                        'Retry-After': str(lb._retry_after_hint)})

            def do_GET(self):  # noqa: N802
                if self.path == '/lb/replicas':
                    # LB-local replica view (not proxied): ready URLs,
                    # the controller's (tp, dp) plan, and live-probed
                    # per-replica mesh shapes where the policy caches
                    # them (queue_depth probes /metrics JSON anyway).
                    self._send_json(200, lb.replica_view())
                    return
                if self.path.startswith('/lb/idempotency'):
                    # Peer-LB lookup: which replica answered this key
                    # (this LB is the key's ring owner).
                    query = urllib.parse.parse_qs(
                        urllib.parse.urlparse(self.path).query)
                    key = (query.get('key') or [''])[0]
                    with lb._completed_lock:
                        url = lb._completed.get(key) if key else None
                    if url:
                        self._send_json(200, {'key': key, 'url': url})
                    else:
                        self._send_json(404, {'key': key, 'url': None})
                    return
                self._proxy('GET')

            def do_POST(self):  # noqa: N802
                if self.path == '/lb/idempotency':
                    # Peer-LB push: a completed key this LB owns.
                    length = int(
                        self.headers.get('Content-Length', 0))
                    try:
                        payload = json.loads(
                            self.rfile.read(length) or b'{}')
                        key = payload.get('key')
                        url = payload.get('url')
                    except (ValueError, UnicodeDecodeError):
                        key = url = None
                    if key and url:
                        lb.accept_completed_key(str(key), str(url))
                        ctx = tracing.parse_trace_header(
                            self.headers.get(wire.TRACE_HEADER))
                        if ctx:
                            # The LB↔LB handoff is a hop of the
                            # request's fleet trace: one instant leg
                            # on the ACCEPTING LB, causally under the
                            # pushing LB's span.
                            t = tracing.RequestTrace(
                                0, trace_id=ctx['trace_id'],
                                parent_span=ctx.get('parent_span'))
                            t.instant('lb_handoff_accept',
                                      lb=lb.lb_id, cause='lb_handoff')
                            t.finish()
                            lb._trace_buf.add(t)
                        self._send_json(200, {'recorded': True})
                    else:
                        self._send_json(400, {'error': 'need key+url'})
                    return
                self._proxy('POST')

        return Handler

    def replica_view(self) -> Dict[str, Any]:
        """The LB's replica view: ready URLs + mesh shape per replica.
        ``mesh`` is the live shape from the policy's /metrics probes
        when available (queue_depth policy), else null — the
        controller-planned ``replica_parallelism`` block is always
        present as the configured truth."""
        meshes = self.policy.replica_meshes()
        urls = list(self.policy.ready_replicas)
        now = time.monotonic()
        age = now - (self._last_sync_ok if self._last_sync_ok
                     is not None else self._started_at)
        with self._evict_lock:
            evicted = sorted(self._evicted)
        return {
            'ready_replica_urls': urls,
            # Horizontal-LB-tier surface: this LB's ring identity and
            # the agreed membership (session/idempotency keys hash to
            # exactly one owner here on EVERY member).
            'lb_id': self.lb_id,
            'lb_ring': {'members': self._ring.members,
                        'size': len(self._ring)},
            # Controller-outage autonomy surface: how stale the view
            # is, whether the LB considers the controller up, and what
            # it evicted on its own evidence.
            'controller_sync_age_s': round(age, 3),
            'controller_up': not self._staleness_alarmed,
            'locally_evicted': evicted,
            'replica_parallelism': self._replica_parallelism,
            'replica_roles': dict(self._replica_roles),
            # Controller-computed per-tier SLO burn/attainment from
            # the last sync (empty until one succeeds).
            'slo': dict(self._last_slo),
            # Gang health accounting: follower ranks are not routable
            # endpoints, but their existence and statuses ride the
            # per-gang block under their rank 0's URL.
            'replica_gangs': dict(self._replica_gangs),
            'replicas': [{'url': u, 'mesh': meshes.get(u),
                          'role': self._replica_roles.get(u),
                          'gang': self._replica_gangs.get(u)}
                         for u in urls],
        }

    # ---------------------------------------------------------- lifecycle
    def start(self) -> None:
        handler = self._make_handler()
        self._httpd = http.server.ThreadingHTTPServer(
            ('0.0.0.0', self.port), handler)
        scheme = 'http'
        if self.tls_certfile and self.tls_keyfile:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile=self.tls_certfile,
                                keyfile=self.tls_keyfile)
            self._httpd.socket = ctx.wrap_socket(self._httpd.socket,
                                                 server_side=True)
            scheme = 'https'
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        threading.Thread(target=self._sync_loop, daemon=True).start()
        logger.info(f'Load balancer ({scheme}) on port {self.port} → '
                    f'{self.controller_url}')

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
