"""Simulated replicas: a fluid queueing model of one model server,
with service curves calibrated from the repo's BENCH engine numbers,
speaking exactly the HTTP contract the control plane drives — so the
REAL replica manager probes, drains, checkpoints and warms them
without knowing they are synthetic.

Service model (deliberately fluid, O(1) per event): a replica with
``slots`` concurrent decode slots processes ``slots`` service-seconds
of work per virtual second. One request of ``p`` prompt and ``g``
generated tokens costs ``svc = ttft_base + p/prefill_rate + g*tpot``
single-slot seconds; a batch of ``n`` advances the replica's
``busy_until`` horizon by ``n*svc/slots``, and the queue wait a new
arrival sees is ``max(0, busy_until - now)``. TTFT = queue wait +
prefill part (minus the warm-prefix discount when the replica was
warmed from a checkpoint — the PR-10 recovery contract, visible in
the sim's recovery-TTFT numbers). Waits beyond ``max_queue_wait_s``
model the SLO scheduler's token-bounded admission: the request is
shed with a retryable 429, exactly what the live scheduler does.

Calibration: :meth:`ServiceCurve.from_bench` scans the repo's
``BENCH_r*.json`` records (newest first) for the serving-path numbers
— ``tpot_ms_median`` at 0.7 capacity, the prefix-cache hit/miss TTFT
medians, the paged engine ``batch`` — and falls back to the r05 CPU
anchors when no record parses. Provision-latency distributions live
in the scenario (they are a property of the cloud, not the engine).
"""
from __future__ import annotations

import collections
import dataclasses
import hashlib
import json
import re
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import telemetry
from skypilot_tpu.telemetry import fleet as fleet_lib
from skypilot_tpu.telemetry import registry as registry_lib
from skypilot_tpu.telemetry import tracing

# r05 fallback anchors (BENCH_r05.json serving_http.at_0p7_capacity and
# prefix_cache blocks): tpot 23.22 ms, TTFT hit/miss 254.8/350.5 ms,
# paged batch 48, ~220-token anchor prompts.
_FALLBACK = {'tpot_ms': 23.22, 'ttft_hit_ms': 254.8,
             'ttft_miss_ms': 350.5, 'batch': 48, 'avg_prompt': 220.0}

_NUM = r'([0-9]+(?:\.[0-9]+)?)'


@dataclasses.dataclass(frozen=True)
class ServiceCurve:
    """Per-replica service parameters (single SLO-tier-independent
    engine curve; tiers differ in SLO targets and admission, not in
    silicon speed)."""
    ttft_base_s: float          # fixed prefill overhead, cold prefix
    warm_ttft_base_s: float     # ... with a warm prefix cache
    prefill_tok_per_s: float    # prompt-token throughput
    tpot_s: float               # seconds per generated token (1 slot)
    slots: int                  # concurrent decode slots
    max_queue_wait_s: float     # admission bound (models 429 shedding)
    kv_pool_tokens: int         # advertised KV capacity (LB handoffs)

    def service_s(self, prompt_tokens: float, gen_tokens: float,
                  warm: bool = False) -> float:
        base = self.warm_ttft_base_s if warm else self.ttft_base_s
        return (base + prompt_tokens / self.prefill_tok_per_s
                + gen_tokens * self.tpot_s)

    def prefill_s(self, prompt_tokens: float, warm: bool) -> float:
        base = self.warm_ttft_base_s if warm else self.ttft_base_s
        return base + prompt_tokens / self.prefill_tok_per_s

    @classmethod
    def from_bench(cls, bench_texts: Optional[List[str]] = None,
                   max_queue_wait_s: float = 8.0) -> 'ServiceCurve':
        """Calibrate from BENCH record texts (newest first; the caller
        reads the files — this module does no I/O so it stays pure and
        GC117-clean). Falls back to the r05 anchors per-field."""
        vals = dict(_FALLBACK)
        found: Dict[str, float] = {}
        for text in bench_texts or []:
            for key, pat in (
                    ('tpot_ms', rf'"tpot_ms_median":\s*{_NUM}'),
                    ('ttft_hit_ms', rf'"ttft_ms_hit_median":\s*{_NUM}'),
                    ('ttft_miss_ms',
                     rf'"ttft_ms_miss_median":\s*{_NUM}'),
                    ('batch', rf'"batch":\s*{_NUM}'),
                    ('avg_prompt', rf'"avg_prompt":\s*{_NUM}')):
                if key in found:
                    continue
                m = re.search(pat, text)
                if m:
                    found[key] = float(m.group(1))
            if len(found) == 5:
                break
        vals.update(found)
        # TTFT decomposition: the miss median is base + avg_prompt /
        # prefill_rate; the hit median skips the shared-prefix
        # recompute — treat it as the warm base and attribute the
        # hit->miss delta to prompt streaming.
        warm_base = vals['ttft_hit_ms'] / 1e3
        miss = vals['ttft_miss_ms'] / 1e3
        prefill_rate = max(500.0,
                           vals['avg_prompt'] / max(1e-3,
                                                    miss - warm_base))
        slots = max(1, int(vals['batch']))
        return cls(ttft_base_s=miss - vals['avg_prompt'] / prefill_rate,
                   warm_ttft_base_s=warm_base,
                   prefill_tok_per_s=prefill_rate,
                   tpot_s=vals['tpot_ms'] / 1e3,
                   slots=slots,
                   max_queue_wait_s=max_queue_wait_s,
                   kv_pool_tokens=slots * 424)  # ~anchor tokens/slot


def canary_response_tokens(prompt: List[int], n: int) -> List[int]:
    """The deterministic greedy 'generation' every HEALTHY simulated
    replica answers a canary prompt with — same prompt, same tokens,
    fleet-wide (standing in for greedy decode on identical weights).
    A byzantine replica perturbs these (silent data corruption)."""
    seed = sum(int(t) * (i + 1) for i, t in enumerate(prompt))
    return [(seed * 31 + i * 7 + 3) % 997 for i in range(max(1, n))]


class SimHTTPError(RuntimeError):
    """A simulated HTTP failure (dead replica / 4xx-5xx) — the sim
    env raises it where urllib would raise, so the manager's error
    handling runs the same branches live and simulated."""

    def __init__(self, code: int, message: str):
        super().__init__(f'HTTP {code}: {message}')
        self.code = code


@dataclasses.dataclass
class SimJob:
    """One dispatched batch (``count`` identical requests riding one
    event — the fluid model's unit of work)."""
    job_id: int
    count: int
    prompt_tokens: float
    gen_tokens: float
    tier: str
    submit_t: float
    ttft_s: float               # per-request TTFT (queue wait + prefill)
    finish_t: float
    wait_s: float = 0.0                   # queue-wait part of the TTFT
    # 128-bit fleet trace id, minted at first admission and preserved
    # across migration legs — the controller assembles all legs of a
    # migrated job under ONE trace.
    trace_id: Optional[str] = None
    migrated_from: Optional[str] = None   # url of the replica that died
    failed_at: Optional[float] = None     # when its first replica died
    cancelled: bool = False
    lb_idx: int = 0                       # LB that dispatched it
    session: Optional[Dict[str, Any]] = None   # multi-turn identity


class SimReplica:
    """One synthetic model server. Owns only local state; the fleet
    wires completion scheduling and death notification."""

    # Page grid the simulated engine hashes prefix chains at, and the
    # heat-store bound — both mirror the live paged engine (64-token
    # pages, ``_PREFIX_HEAT_MAX = 64`` hottest chains).
    PAGE = 64
    PREFIX_STORE_CAP = 64
    DIGEST_MAX_ENTRIES = 16

    def __init__(self, cluster_name: str, url: str, curve: ServiceCurve,
                 now_fn: Callable[[], float], *,
                 role: str = 'colocated', zone: str = 'z0',
                 is_spot: bool = False, gang_id: Optional[str] = None,
                 gang_rank: int = 0, gang_world: int = 1,
                 tp: int = 1, dp: int = 1,
                 never_drain: bool = False):
        self.cluster_name = cluster_name
        self.url = url
        self.curve = curve
        self._now = now_fn
        self.role = role
        self.zone = zone
        self.is_spot = is_spot
        self.gang_id = gang_id
        self.gang_rank = gang_rank
        self.gang_world = gang_world
        self.tp = tp
        self.dp = dp
        self.alive = True
        self.draining = False
        self.drain_started_t: Optional[float] = None
        self._drain_observed = False
        # Scenario knob: a straggler that acks /drain but never
        # reports drained — the deadline-failover path's test double.
        self.never_drain = never_drain
        self.warm = False                  # warmed from a checkpoint
        self.slowdown = 1.0                # straggler fault multiplier
        # Gray-failure fault switches (round 13):
        # wedged: the engine loop is stuck — the replica ACCEPTS work
        # that never finishes and its /readiness reports degraded (the
        # probe escalation must replace it; in-flight jobs migrate at
        # teardown). byzantine: silently corrupted — serves normally
        # but answers the manager's canary prompt WRONG (the
        # quarantine path must catch it).
        self.wedged = False
        self.byzantine = False
        self.busy_until = 0.0
        self.inflight: Dict[int, SimJob] = {}
        self._next_job = 1
        # Hot-prefix chain store (hash-hex -> [covered_len, hits]),
        # LRU-bounded like the live engine's heat tracker — session
        # working sets beyond the cap thrash out, which is exactly the
        # capacity effect affinity routing is supposed to dodge.
        self._prefix_store: 'collections.OrderedDict[str, List[int]]' = (
            collections.OrderedDict())
        # Fleet-plane telemetry (round 19): each simulated server owns
        # a PRIVATE registry + trace buffer — never the process-global
        # one, which thousands of sim replicas would share — scraped
        # by the REAL replica manager over /telemetry/summary exactly
        # like a live model server, so the controller-side aggregation
        # runs identical code on the virtual clock.
        self._reg = registry_lib.MetricsRegistry()
        self._trace_buf = tracing.TraceBuffer()
        # SimWorld.request strips query strings, so the scrape's
        # ``since`` cursor cannot reach us; a replica-side shipped
        # cursor gives the same at-most-once delivery (exactly one
        # controller scrapes a replica).
        self._trace_shipped = 0

    # ------------------------------------------------------ prefix cache
    def note_prefix(self, chain_hash: str, chain_len: int) -> None:
        """Record that this replica now holds a KV chain covering
        ``chain_len`` prompt tokens (computed locally or warmed from a
        migration blob); LRU-evicts beyond the heat-store cap."""
        rec = self._prefix_store.get(chain_hash)
        if rec is not None:
            rec[0] = max(rec[0], int(chain_len))
            rec[1] += 1
            self._prefix_store.move_to_end(chain_hash)
            return
        while len(self._prefix_store) >= self.PREFIX_STORE_CAP:
            self._prefix_store.popitem(last=False)
        self._prefix_store[chain_hash] = [int(chain_len), 1]

    def match_prefix(self, chain_hashes: List[str]) -> int:
        """Longest resident chain: ``chain_hashes[k-1]`` is the hash of
        the request's first ``k`` pages; returns the covered page count
        (0 = fully cold)."""
        for k in range(len(chain_hashes), 0, -1):
            rec = self._prefix_store.get(chain_hashes[k - 1])
            if rec is not None:
                rec[1] += 1
                self._prefix_store.move_to_end(chain_hashes[k - 1])
                return k
        return 0

    def prefix_digest(self) -> Dict[str, Any]:
        """The ``prefix_digest`` block a live model server publishes on
        ``/metrics?format=json``: hottest chains, bounded, determinis-
        tically ordered by (-hits, hash)."""
        by_heat = sorted(self._prefix_store.items(),
                         key=lambda kv: (-kv[1][1], kv[0]))
        return {'page': self.PAGE,
                'entries': [{'hash': h, 'len': rec[0], 'hits': rec[1]}
                            for h, rec
                            in by_heat[:self.DIGEST_MAX_ENTRIES]]}

    # ----------------------------------------------------------- service
    def enqueue(self, now: float, count: int, prompt_tokens: float,
                gen_tokens: float, tier: str,
                warm_tokens: float = 0.0) -> Optional[SimJob]:
        """Admit a batch; returns the job (with its completion time for
        the fleet to schedule) or None when admission sheds it (queue
        wait beyond the scheduler bound — the 429 path).
        ``warm_tokens`` prompt tokens are already resident in this
        replica's KV pages (a prefix-affinity hit or a migrated chain):
        they skip prefill entirely and the warm TTFT base applies —
        the discount the affinity policy's hit-rate numbers measure."""
        if not self.alive:
            raise SimHTTPError(502, 'replica dead')
        if self.draining:
            raise SimHTTPError(503, 'draining')
        if self.wedged:
            # The gray part of a wedged replica: it still ACCEPTS the
            # work (HTTP alive, queue open) — the job just never
            # finishes. It migrates when the probe escalation finally
            # tears the replica down.
            job = SimJob(job_id=self._next_job, count=count,
                         prompt_tokens=prompt_tokens,
                         gen_tokens=gen_tokens, tier=tier,
                         submit_t=now, ttft_s=float('inf'),
                         finish_t=now + 1e12)
            self._next_job += 1
            self.inflight[job.job_id] = job
            # Admitted (the gray part: the queue IS open) but no
            # latency observation — the request never finishes.
            self._reg.counter(fleet_lib.ADMIT_METRIC,
                              'Requests admitted by the scheduler',
                              tier=tier).inc(count)
            return job
        cold_tokens = max(0.0, prompt_tokens - max(0.0, warm_tokens))
        warm = self.warm or warm_tokens > 0
        svc = self.curve.service_s(cold_tokens, gen_tokens,
                                   warm) * self.slowdown
        wait = max(0.0, self.busy_until - now)
        if wait > self.curve.max_queue_wait_s:
            self._reg.counter(fleet_lib.SHED_METRIC,
                              'Requests shed at admission',
                              tier=tier, reason='queue_wait').inc(count)
            return None
        self.busy_until = (max(now, self.busy_until)
                           + count * svc / self.curve.slots)
        ttft = wait + self.curve.prefill_s(cold_tokens,
                                           warm) * self.slowdown
        job = SimJob(job_id=self._next_job, count=count,
                     prompt_tokens=prompt_tokens,
                     gen_tokens=gen_tokens, tier=tier, submit_t=now,
                     ttft_s=ttft, finish_t=now + wait + svc,
                     wait_s=wait,
                     trace_id=self._mint_trace_id(now))
        self._next_job += 1
        self.inflight[job.job_id] = job
        self._observe_admit(tier, count, ttft)
        return job

    def _mint_trace_id(self, now: float) -> str:
        """Deterministic 128-bit trace id: same seed, same admissions,
        same ids — the sim counterpart of the LB's seeded-RNG mint."""
        raw = f'{self.url}|{self._next_job}|{now:.6f}'.encode()
        return hashlib.md5(raw).hexdigest()

    def _observe_admit(self, tier: str, count: int,
                       ttft_s: float) -> None:
        """Record one admitted batch in the replica's private registry
        using the exact series names the fleet SLO evaluator reads —
        the sim and the live scheduler must agree on the schema."""
        self._reg.counter(fleet_lib.ADMIT_METRIC,
                          'Requests admitted by the scheduler',
                          tier=tier).inc(count)
        ttft_h = self._reg.histogram(fleet_lib.TTFT_METRIC,
                                     'Time to first token (ms)',
                                     tier=tier)
        tpot_h = self._reg.histogram(fleet_lib.TPOT_METRIC,
                                     'Time per output token (ms)',
                                     tier=tier)
        tpot_ms = self.curve.tpot_s * self.slowdown * 1e3
        for _ in range(max(1, int(count))):
            ttft_h.observe(ttft_s * 1e3)
            tpot_h.observe(tpot_ms)

    def complete(self, job: SimJob) -> None:
        self.inflight.pop(job.job_id, None)
        self._record_trace(job)

    def _record_trace(self, job: SimJob) -> None:
        """One completed-trace leg on the VIRTUAL clock: queue-wait /
        prefill / decode spans, shipped to the controller on the next
        ``/telemetry/summary`` scrape. A migrated job keeps its trace
        id, so the controller assembles the legs from every replica
        that served it under one trace."""
        trace = tracing.RequestTrace(job.job_id,
                                     trace_id=job.trace_id)
        # Re-anchor the real-clock stamps the constructor took onto
        # virtual time: span offsets become seconds-since-submit.
        trace.t0 = 0.0
        trace.wall0 = job.submit_t
        prefill_end = min(job.ttft_s, job.finish_t - job.submit_t)
        for name, t0, t1 in (
                ('queue_wait', 0.0, job.wait_s),
                ('prefill', job.wait_s, prefill_end),
                ('decode', prefill_end, job.finish_t - job.submit_t)):
            span = tracing.Span(name, t0, job.submit_t + t0)
            span.t1 = max(t0, t1)
            trace.spans.append(span)
        trace.meta.update(tier=job.tier, count=job.count,
                          replica=self.cluster_name)
        if job.migrated_from is not None:
            trace.meta.update(migrated_from=job.migrated_from,
                              cause='migration')
        trace.done = True
        self._trace_buf.add(trace)

    def kill(self) -> List[SimJob]:
        """Hard death: returns the in-flight jobs the LB must migrate;
        the replica stops answering anything."""
        self.alive = False
        jobs = [j for j in self.inflight.values() if not j.cancelled]
        for j in jobs:
            j.cancelled = True
        self.inflight.clear()
        return jobs

    def queue_tokens_total(self, now: float) -> int:
        """The work-token estimate a live scheduler would publish:
        backlog seconds converted back to decode tokens."""
        backlog_s = max(0.0, self.busy_until - now)
        return int(backlog_s * self.curve.slots / self.curve.tpot_s)

    def kv_pool_tokens_free(self) -> int:
        used = sum(j.count * (j.prompt_tokens + j.gen_tokens)
                   for j in self.inflight.values())
        return max(0, int(self.curve.kv_pool_tokens - used))

    # -------------------------------------------------------------- HTTP
    def handle(self, path: str, payload: Optional[Dict[str, Any]],
               data: Optional[bytes]) -> Any:
        """The model-server contract surface the control plane drives
        (readiness, drain, checkpoint, warmup, metrics JSON)."""
        if not self.alive:
            raise SimHTTPError(502, 'connection refused')
        now = self._now()
        if path == '/readiness':
            if self.wedged:
                # The live model server's wedge watchdog flips
                # readiness to a degraded 503; the probe escalation
                # (NOT_READY -> FAILED_PROBE) then replaces it.
                raise SimHTTPError(503, 'degraded: wedged engine step')
            return {'ready': not self.draining, 'draining': self.draining}
        if path == '/generate':
            # The canary surface: greedy tokens deterministic in the
            # prompt, identical on every healthy replica; a byzantine
            # replica answers perturbed tokens (silent corruption the
            # manager's digest compare must catch).
            prompt = [int(t) for t in (payload or {}).get('prompt', [])]
            n = int((payload or {}).get('max_new_tokens', 8))
            toks = canary_response_tokens(prompt, n)
            if self.byzantine:
                toks = [(t + 1) % 997 for t in toks]
            return {'tokens': toks, 'request_id': 0}
        if path == '/drain':
            if payload is not None or data is not None:   # POST: begin
                if not self.draining:
                    self.draining = True
                    self.drain_started_t = now
                return {'draining': True, 'inflight': len(self.inflight)}
            drained = (self.draining and not self.never_drain
                       and self.busy_until <= now
                       and not self.inflight)
            if drained and not self._drain_observed:
                # The live model server's monitor observes the drain
                # histogram when the scheduler reports drained; the
                # sim replica honors the same telemetry contract.
                self._drain_observed = True
                telemetry.get_registry().histogram(
                    'skytpu_replica_drain_seconds',
                    'Graceful-drain duration: drain start to idle (s)',
                    buckets=telemetry.registry.DEFAULT_SECONDS_BUCKETS,
                ).observe(max(0.0, now - (self.drain_started_t or now)))
            return {'draining': self.draining, 'drained': drained,
                    'inflight': len(self.inflight)}
        if path == '/checkpoint':
            blob = json.dumps({
                'format': 'SIMCKPT', 'source': self.url,
                'exported_t': now, 'warm': True,
                'hot_prefixes': 4,
            }).encode()
            return blob
        if path == '/kv/warmup':
            if not data:
                raise SimHTTPError(400, 'empty warmup body')
            try:
                blob = json.loads(data)
            except (ValueError, UnicodeDecodeError) as e:
                raise SimHTTPError(400, f'bad container: {e}') from e
            if blob.get('format') != 'SIMCKPT':
                raise SimHTTPError(400, 'unknown container format')
            self.warm = True
            return {'warmed_rows': int(blob.get('hot_prefixes', 0))
                    * 128, 'entries': int(blob.get('hot_prefixes', 0))}
        if path == '/telemetry/summary':
            # The fleet scrape surface (round 19): identical shape to
            # the live server's route; 'wall' is the virtual clock, so
            # the controller computes a zero skew offset per source.
            cursor, traces = self._trace_buf.summaries_since(
                self._trace_shipped)
            self._trace_shipped = cursor
            return {'clock': {'wall': now, 'monotonic': now},
                    'registry': self._reg.export_wire(),
                    'traces': traces, 'cursor': cursor}
        if path.startswith('/metrics'):
            return {
                'queue_tokens_total': self.queue_tokens_total(now),
                'kv_pool_tokens_free': self.kv_pool_tokens_free(),
                'mesh': {'tp': self.tp, 'dp': self.dp},
                'disagg': {'role': self.role},
                'prefix_digest': self.prefix_digest(),
            }
        if path == '/gang/status':
            # Adoption probe surface (round 15): a restarted manager
            # recovers gang identity from the live replica.
            if self.gang_id is None:
                raise SimHTTPError(404, 'not a gang member')
            return {'gang_id': self.gang_id, 'rank': self.gang_rank,
                    'world': self.gang_world}
        raise SimHTTPError(404, f'no route {path}')
