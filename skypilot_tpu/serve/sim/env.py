"""SimControlPlaneEnv + SimWorld: the simulated side of the
``serve/control_env.py`` seam.

``SimWorld`` owns the synthetic fleet — replica registry, zone
assignment, provision-latency draws, kill switches for the chaos
scenarios — and serves the replica HTTP surface in-process.
``SimControlPlaneEnv`` adapts it to the :class:`ControlPlaneEnv`
interface the REAL replica manager and controller consume: virtual
clock reads, virtual sleeps, logical-task spawns, instant HTTP
round-trips against :class:`SimReplica` handlers, and cluster
launch/teardown that burns the scenario's provision latency on the
virtual clock. Persistence lands in a WORLD-LOCAL virtual serve DB
(replica rows + lifecycle journal + controller notes — never the
operator's sqlite) that survives a simulated controller crash
(:meth:`SimControlPlaneEnv.halt`), so restart reconciliation runs the
same journal-replay code live and simulated. The fault injector is
the scenario's seeded one.
"""
from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Sequence

from skypilot_tpu import exceptions
from skypilot_tpu.serve import control_env
from skypilot_tpu.serve import faults as faults_lib
from skypilot_tpu.serve.sim import core as sim_core
from skypilot_tpu.serve.sim import replica as sim_replica


class SimWorld:
    """The synthetic fleet: clusters, replicas, zones, and the
    scenario knobs that shape them."""

    def __init__(self, loop: sim_core.EventLoop,
                 curve: sim_replica.ServiceCurve, *, seed: int = 0,
                 n_zones: int = 3, provision_s: float = 30.0,
                 provision_jitter: float = 0.3,
                 never_drain_clusters: Optional[set] = None):
        self.loop = loop
        self.curve = curve
        self.n_zones = max(1, int(n_zones))
        self.provision_s = float(provision_s)
        self.provision_jitter = float(provision_jitter)
        # Scenario knob: cluster names (by launch INDEX spelling
        # 'idx:N') whose replicas ack /drain but never report drained
        # — the deadline-straggler path.
        self.never_drain_clusters = set(never_drain_clusters or ())
        self.rng = random.Random(seed)
        self.replicas: Dict[str, sim_replica.SimReplica] = {}  # by url
        self.by_cluster: Dict[str, sim_replica.SimReplica] = {}
        self._gone_clusters: set = set()
        self._launch_index = 0
        # Fleet hook: called with (replica, jobs) when a replica dies
        # with in-flight work (the LB migration path).
        self.on_replica_killed: Optional[Callable[..., None]] = None
        # The simulated serve DB (round 15): replica rows, the
        # lifecycle journal and controller notes live on the WORLD —
        # not the env — so they survive a simulated controller crash
        # (env.halt()) and feed the restarted controller's
        # reconciliation, exactly like the sqlite tables live.
        self.db_replicas: Dict[int, Dict[str, Any]] = {}
        self.db_ops: List[Dict[str, Any]] = []
        self.db_notes: Dict[str, Any] = {}
        self._db_op_seq = 0

    # ------------------------------------------------------------ launch
    def provision_delay(self) -> float:
        j = self.provision_jitter
        return self.provision_s * (1.0 - j + 2.0 * j * self.rng.random())

    def create_replica(self, cluster_name: str,
                       envs: Dict[str, str], is_spot: bool
                       ) -> sim_replica.SimReplica:
        idx = self._launch_index
        self._launch_index += 1
        port = int(envs.get('SKYTPU_REPLICA_PORT', '8081'))
        url = f'http://10.{(idx >> 14) & 0x3f}.' \
              f'{(idx >> 7) & 0x7f}.{idx & 0x7f}:{port}'
        never_drain = (cluster_name in self.never_drain_clusters
                       or f'idx:{idx}' in self.never_drain_clusters)
        rep = sim_replica.SimReplica(
            cluster_name, url, self.curve, lambda: self.loop.now,
            role=envs.get('SKYTPU_ROLE', 'colocated'),
            zone=f'z{idx % self.n_zones}',
            is_spot=is_spot,
            gang_id=envs.get('SKYTPU_GANG_ID') or None,
            gang_rank=int(envs.get('SKYTPU_RANK', '0')),
            gang_world=int(envs.get('SKYTPU_WORLD', '1')),
            tp=int(envs.get('SKYTPU_TP', '1')),
            dp=int(envs.get('SKYTPU_DP', '1')),
            never_drain=never_drain)
        self.replicas[url] = rep
        self.by_cluster[cluster_name] = rep
        self._gone_clusters.discard(cluster_name)
        return rep

    # -------------------------------------------------------------- kill
    def kill_replica(self, rep: sim_replica.SimReplica) -> None:
        """Hard failure (preemption, zone loss): the cluster is gone
        and every in-flight job needs LB migration."""
        if not rep.alive:
            return
        jobs = rep.kill()
        self._gone_clusters.add(rep.cluster_name)
        if self.on_replica_killed is not None:
            self.on_replica_killed(rep, jobs)

    def live_replicas(self) -> List[sim_replica.SimReplica]:
        return [r for r in self.replicas.values() if r.alive]

    # -------------------------------------------------------------- HTTP
    def request(self, url: str, payload: Optional[Dict[str, Any]],
                data: Optional[bytes]) -> Any:
        base, _, path = url.partition('//')[2].partition('/')
        rep = self.replicas.get(f'http://{base}')
        if rep is None or not rep.alive:
            raise sim_replica.SimHTTPError(502, f'no server at {base}')
        return rep.handle('/' + path.split('?')[0], payload, data)

    def fetch_json(self, url: str) -> Dict[str, Any]:
        """The LB policies' probe transport
        (``configure_transport``)."""
        out = self.request(url, None, None)
        if not isinstance(out, dict):
            raise sim_replica.SimHTTPError(500, 'non-JSON response')
        return out


class SimControlPlaneEnv(control_env.ControlPlaneEnv):
    """Adapts :class:`SimWorld` to the manager/controller seam."""

    name = 'sim'

    def __init__(self, world: SimWorld, *, seed: int = 0,
                 injector: Optional[faults_lib.FaultInjector] = None):
        self._world = world
        self._loop = world.loop
        self._seed = seed
        self._injector = injector
        self._rng_count = 0
        # Simulated controller death (round 15): once halted, every
        # effect the dead controller's lingering logical tasks try to
        # run unwinds them (SimShutdown) or becomes a no-op — a dead
        # process performs no I/O. The WORLD (fleet, virtual DB) lives
        # on; a restarted controller gets a FRESH env over it.
        self._halted = False

    def halt(self) -> None:
        """Kill the controller this env belongs to: its background
        tasks (drain polls, launches, teardowns) unwind at their next
        effect, its persistence writes stop landing."""
        self._halted = True

    def _check_halted(self) -> None:
        if self._halted:
            raise sim_core.SimShutdown()

    # ---------------------------------------------------------------- time
    def time(self) -> float:
        return self._loop.now

    def monotonic(self) -> float:
        return self._loop.now

    def sleep(self, seconds: float) -> None:
        self._check_halted()
        self._loop.sleep(seconds)
        self._check_halted()

    # --------------------------------------------------------- concurrency
    def spawn(self, fn: Callable[..., None], *args: Any) -> None:
        if self._halted:
            return      # a dead process spawns nothing
        self._loop.spawn(fn, *args,
                         name=getattr(fn, '__name__', 'task'))

    def run_parallel(self, fns: Sequence[Callable[[], None]]) -> None:
        # Serialized: the sim's one-runner-at-a-time discipline makes
        # parallel teardown equivalent to sequential teardown.
        for fn in fns:
            fn()

    def rng(self) -> random.Random:
        self._rng_count += 1
        return random.Random(self._seed * 1000003 + self._rng_count)

    # ---------------------------------------------------------------- HTTP
    def http_json(self, url: str, payload: Optional[Dict[str, Any]] = None,
                  timeout: float = 10.0) -> Any:
        del timeout      # virtual round-trips are instantaneous
        self._check_halted()
        return self._world.request(url, payload, None)

    def http_post_bytes(self, url: str, data: bytes,
                        content_type: str = 'application/octet-stream',
                        timeout: float = 30.0) -> bytes:
        del content_type, timeout
        self._check_halted()
        out = self._world.request(url, None, data)
        if isinstance(out, bytes):
            return out
        import json as _json
        return _json.dumps(out).encode()

    def probe_http(self, url: str, post_data: Optional[Dict[str, Any]],
                   timeout: float) -> bool:
        del timeout
        self._check_halted()
        try:
            self._world.request(url, post_data, None)
            return True
        except sim_replica.SimHTTPError:
            return False

    # ----------------------------------------------------------- clusters
    def launch_cluster(self, task: Any, cluster_name: str) -> None:
        self._check_halted()
        # Burn the scenario's provision latency on the virtual clock —
        # the forecast autoscaler's lead-time EWMA learns from exactly
        # this (via the manager's provision observations).
        delay = self._world.provision_delay()
        self._loop.sleep(delay)
        envs = dict(task.envs or {})
        is_spot = any(getattr(r, 'use_spot', False)
                      for r in (task.resources or []))
        self._world.create_replica(cluster_name, envs, is_spot)

    def cluster_head_ip(self, cluster_name: str) -> Optional[str]:
        rep = self._world.by_cluster.get(cluster_name)
        if rep is None or not rep.alive:
            return None
        # url is http://ip:port
        return rep.url.split('//')[1].rsplit(':', 1)[0]

    def down_cluster(self, cluster_name: str) -> None:
        self._check_halted()
        rep = self._world.by_cluster.get(cluster_name)
        if rep is None or cluster_name in self._world._gone_clusters:
            if rep is None:
                raise exceptions.ClusterDoesNotExist(cluster_name)
            return
        self._world.kill_replica(rep)

    def cluster_gone(self, cluster_name: str) -> bool:
        rep = self._world.by_cluster.get(cluster_name)
        return rep is None or not rep.alive

    # -------------------------------------------------------- persistence
    # The virtual serve DB lives on the WORLD (never the operator's
    # sqlite): rows, journal ops and notes survive env.halt() so a
    # restarted simulated controller reconciles against exactly what
    # the dead one persisted.
    def persist_replica(self, service_name: str, replica_id: int,
                        cluster_name: str, status: Any,
                        url: Optional[str], version: int, is_spot: bool,
                        port: int) -> None:
        del service_name
        if self._halted:
            return      # a dead process writes nothing
        self._world.db_replicas[replica_id] = {
            'replica_id': replica_id,
            'cluster_name': cluster_name,
            'status': status,
            'url': url,
            'version': version,
            'is_spot': is_spot,
            'launched_at': self._loop.now,
            'port': port,
        }

    def remove_replica(self, service_name: str, replica_id: int) -> None:
        del service_name
        if self._halted:
            return
        self._world.db_replicas.pop(replica_id, None)

    def load_replica_rows(self, service_name: str
                          ) -> List[Dict[str, Any]]:
        del service_name
        return [dict(self._world.db_replicas[rid])
                for rid in sorted(self._world.db_replicas)]

    # ----------------------------------------------------------- journal
    def journal_op_start(self, service_name: str, kind: str,
                         replica_id: int, gang_id: Optional[str],
                         payload: Optional[Dict[str, Any]] = None,
                         deadline_at: Optional[float] = None) -> int:
        del service_name
        self._check_halted()
        self._world._db_op_seq += 1
        op_id = self._world._db_op_seq
        self._world.db_ops.append({
            'op_id': op_id, 'kind': kind, 'replica_id': replica_id,
            'gang_id': gang_id, 'payload': dict(payload or {}),
            'started_at': self._loop.now, 'deadline_at': deadline_at,
            'state': 'pending',
        })
        return op_id

    def journal_op_finish(self, service_name: str, op_id: int) -> None:
        del service_name
        if self._halted:
            return
        self._world.db_ops = [op for op in self._world.db_ops
                              if op['op_id'] != op_id]

    def pending_ops(self, service_name: str) -> List[Dict[str, Any]]:
        del service_name
        return [dict(op) for op in self._world.db_ops
                if op['state'] == 'pending']

    # ------------------------------------------------------------- notes
    def put_note(self, service_name: str, key: str, value: Any) -> None:
        del service_name
        if self._halted:
            return
        self._world.db_notes[key] = value

    def del_note(self, service_name: str, key: str) -> None:
        del service_name
        if self._halted:
            return
        self._world.db_notes.pop(key, None)

    def get_notes(self, service_name: str) -> Dict[str, Any]:
        del service_name
        return dict(self._world.db_notes)

    # -------------------------------------------------------------- faults
    def fault_injector(self) -> Optional[faults_lib.FaultInjector]:
        return self._injector
