"""Chaos scenario library: named, seeded failure storms over the
fleet simulator.

Each scenario bundles a service spec (which REAL autoscaler runs), a
traffic trace, an LB policy, a fault spec (``serve/faults.py`` rules —
including the sim-targeted sites: correlated spot storms, zone
outages, flaky probes, stragglers, gang churn) and the simulator
knobs. ``run_scenario(name, seed=...)`` is the single entry point the
``skytpu sim`` CLI and the bench's ``sim`` block share.

Scenario service curves are calibrated from the repo's BENCH records
(:func:`calibrated_curve`), scaled to a known per-replica capacity
(``slots`` sized so one replica serves ~2 req/s of anchor-shaped
requests — matching ``target_qps_per_replica: 2`` in the specs, so
autoscaler math and queueing behavior line up the way they do in the
live benches).

``forecast_vs_reactive`` reproduces the PR-10 shed replay as a fleet
scenario: the identical 4-season bursty trace (60 s of 8 QPS per 300 s
season over a 0.5 QPS floor, 30 s provision latency) run once under
the reactive ``RequestRateAutoscaler`` and once under the forecast
autoscaler — the forecast run must shed STRICTLY fewer requests.
"""
from __future__ import annotations

import dataclasses
import glob
import os
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu.serve.service_spec import SkyServiceSpec
from skypilot_tpu.serve.sim import fleet as sim_fleet
from skypilot_tpu.serve.sim import replica as sim_replica
from skypilot_tpu.serve.sim import traffic as sim_traffic

_CURVE_CACHE: Dict[int, sim_replica.ServiceCurve] = {}


def _repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(here))))


def calibrated_curve(slots: int = 10) -> sim_replica.ServiceCurve:
    """A BENCH-calibrated service curve with ``slots`` concurrency
    (slots sized by the scenario so per-replica capacity matches its
    spec's ``target_qps_per_replica``). Reads the newest
    ``BENCH_r*.json`` records from the repo root; falls back to the
    r05 anchors when none parse."""
    if slots in _CURVE_CACHE:
        return _CURVE_CACHE[slots]
    texts: List[str] = []
    try:
        paths = sorted(glob.glob(os.path.join(_repo_root(),
                                              'BENCH_r*.json')),
                       reverse=True)
        for p in paths[:4]:
            with open(p, encoding='utf-8') as f:
                texts.append(f.read())
    except OSError:
        pass
    base = sim_replica.ServiceCurve.from_bench(texts)
    curve = dataclasses.replace(base, slots=slots,
                                kv_pool_tokens=slots * 424)
    _CURVE_CACHE[slots] = curve
    return curve


def _spec(**kw: Any) -> SkyServiceSpec:
    base = dict(readiness_path='/readiness',
                initial_delay_seconds=120.0,
                upscale_delay_seconds=10.0,
                downscale_delay_seconds=60.0)
    base.update(kw)
    return SkyServiceSpec(**base)


@dataclasses.dataclass
class Scenario:
    name: str
    description: str
    spec_fn: Callable[[], SkyServiceSpec]
    trace_fn: Callable[[], sim_traffic.Trace]
    policy: str = 'queue_depth'
    fault_rules: Optional[List[Dict[str, Any]]] = None
    fault_seed: int = 0
    # True when every injected failure is covered by the recovery
    # contract (LB migration + backfill) — the report's ``lost`` count
    # MUST be zero for these.
    recovery_covered: bool = True
    slots: int = 10
    sim_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Custom runner (comparison scenarios); default = single fleet run.
    runner: Optional[Callable[['Scenario', int, Optional[str]],
                              Dict[str, Any]]] = None

    def build(self, seed: int = 0, policy: Optional[str] = None,
              **overrides: Any) -> sim_fleet.FleetSimulator:
        kwargs: Dict[str, Any] = dict(self.sim_kwargs)
        kwargs.update(overrides)
        fault_spec = (
            {'seed': self.fault_seed, 'rules': list(self.fault_rules)}
            if self.fault_rules else None)
        return sim_fleet.FleetSimulator(
            spec=self.spec_fn(), trace=self.trace_fn(), seed=seed,
            policy_name=policy or self.policy,
            curve=calibrated_curve(self.slots),
            fault_spec=fault_spec, **kwargs)

    def run(self, seed: int = 0, policy: Optional[str] = None,
            **overrides: Any) -> Dict[str, Any]:
        if self.runner is not None:
            report = self.runner(self, seed, policy)
        else:
            report = self.build(seed, policy, **overrides).run()
        report['scenario'] = self.name
        report['recovery_covered'] = self.recovery_covered
        return report


# ------------------------------------------------------------- scenarios
def _forecast_vs_reactive_runner(scn: 'Scenario', seed: int,
                                 policy: Optional[str]
                                 ) -> Dict[str, Any]:
    """The PR-10 shed replay at fleet scale: identical trace, reactive
    vs forecast autoscaler, forecast must shed strictly fewer."""
    del scn

    def spec(forecast: bool) -> SkyServiceSpec:
        kw: Dict[str, Any] = dict(
            min_replicas=1, max_replicas=8, target_qps_per_replica=2.0,
            upscale_delay_seconds=10.0, downscale_delay_seconds=60.0)
        if forecast:
            kw.update(forecast_enabled=True,
                      forecast_bucket_seconds=10.0,
                      forecast_season_seconds=300.0,
                      forecast_horizon_seconds=60.0)
        return _spec(**kw)

    def one(forecast: bool) -> Dict[str, Any]:
        sim = sim_fleet.FleetSimulator(
            spec=spec(forecast),
            trace=sim_traffic.bursty(0.5, 8.0, 60.0, 300.0, 4),
            seed=seed, policy_name=policy or 'queue_depth',
            curve=calibrated_curve(10), provision_s=30.0,
            provision_jitter=0.0, sync_s=5.0, tick_s=10.0,
            keep_log=False)
        return sim.run()

    reactive = one(False)
    forecast = one(True)

    def sheds(rep: Dict[str, Any]) -> int:
        return sum(rep['requests']['shed'].values())

    return {
        'seed': seed,
        'trace': 'bursty(0.5->8 qps, 60s bursts, 4x300s seasons)',
        'reactive': {'shed': sheds(reactive),
                     'lost': reactive['requests']['lost'],
                     'chip_seconds': reactive['chip_seconds'],
                     'slo': reactive['slo']},
        'forecast': {'shed': sheds(forecast),
                     'lost': forecast['requests']['lost'],
                     'chip_seconds': forecast['chip_seconds'],
                     'slo': forecast['slo']},
        'requests': {'arrived': reactive['requests']['arrived'],
                     'completed': forecast['requests']['completed'],
                     'shed': {'reactive': sheds(reactive),
                              'forecast': sheds(forecast)},
                     'lost': max(reactive['requests']['lost'],
                                 forecast['requests']['lost']),
                     'migrated': 0},
        'forecast_sheds_strictly_fewer': sheds(forecast)
                                         < sheds(reactive),
        'events': reactive['events'] + forecast['events'],
        'event_log_sha256': reactive['event_log_sha256'],
        'virtual_s': reactive['virtual_s'] + forecast['virtual_s'],
        'chip_seconds': reactive['chip_seconds']
                        + forecast['chip_seconds'],
    }


def _multi_turn_affinity_runner(scn: 'Scenario', seed: int,
                                policy: Optional[str]
                                ) -> Dict[str, Any]:
    """The round-18 routing comparison: the IDENTICAL multi-turn trace
    over the same 1000-replica fleet under ``queue_depth`` (load-only)
    vs ``prefix_affinity`` (digest routing + stickiness + proactive
    migration). Affinity must win BOTH ways: strictly higher warm-TTFT
    hit rate AND strictly fewer prefix-recompute tokens."""
    del policy     # the policy axis IS the comparison

    def one(policy_name: str) -> Dict[str, Any]:
        kwargs: Dict[str, Any] = dict(scn.sim_kwargs)
        kwargs.setdefault('keep_log', False)
        sim = sim_fleet.FleetSimulator(
            spec=scn.spec_fn(), trace=scn.trace_fn(), seed=seed,
            policy_name=policy_name,
            curve=calibrated_curve(scn.slots), **kwargs)
        return sim.run()

    qd = one('queue_depth')
    aff = one('prefix_affinity')

    def view(rep: Dict[str, Any]) -> Dict[str, Any]:
        return {'ttft_hit_rate': rep['affinity']['ttft_hit_rate'],
                'recompute_tokens': rep['affinity']['recompute_tokens'],
                'warm_hits': rep['affinity']['warm_hits'],
                'outcomes': rep['affinity']['outcomes'],
                'prefix_migrations': rep['affinity']
                                        ['prefix_migrations'],
                'shed': sum(rep['requests']['shed'].values()),
                'lost': rep['requests']['lost'],
                'slo': rep['slo']}

    return {
        'seed': seed,
        'trace': aff['trace'],
        'replicas': aff['replicas'],
        'queue_depth': view(qd),
        'prefix_affinity': view(aff),
        'affinity_beats_queue_depth': {
            'ttft_hit_rate': (aff['affinity']['ttft_hit_rate']
                              > qd['affinity']['ttft_hit_rate']),
            'recompute_tokens': (aff['affinity']['recompute_tokens']
                                 < qd['affinity']['recompute_tokens']),
        },
        'requests': {'arrived': aff['requests']['arrived'],
                     'completed': aff['requests']['completed'],
                     'shed': aff['requests']['shed'],
                     'lost': max(aff['requests']['lost'],
                                 qd['requests']['lost']),
                     'migrated': aff['requests']['migrated']},
        'slo': aff['slo'],
        'events': qd['events'] + aff['events'],
        'event_log_sha256': aff['event_log_sha256'],
        'virtual_s': qd['virtual_s'] + aff['virtual_s'],
        'chip_seconds': qd['chip_seconds'] + aff['chip_seconds'],
    }


SCENARIOS: Dict[str, Scenario] = {}


def _register(s: Scenario) -> None:
    SCENARIOS[s.name] = s


_register(Scenario(
    name='smoke',
    description='Tier-1 smoke: 3 replicas, steady traffic, one mid-run '
                'replica kill; must finish in seconds with zero lost.',
    spec_fn=lambda: _spec(min_replicas=3),
    trace_fn=lambda: sim_traffic.constant(4.0, 120.0),
    fault_rules=[{'kind': 'zone_outage', 'site': 'sim_zone_outage',
                  'at': 4, 'zone': 'z2'}],
    sim_kwargs=dict(provision_s=20.0, provision_jitter=0.0,
                    n_zones=3, drain_grace_s=200.0),
))

_register(Scenario(
    name='spot_storm',
    description='Correlated spot-preemption storm: a forecast+fallback '
                'autoscaled spot fleet loses 3 spot replicas at once, '
                'twice; on-demand backfill + migration keep lost at 0.',
    spec_fn=lambda: _spec(
        min_replicas=4, max_replicas=12, target_qps_per_replica=2.0,
        base_ondemand_fallback_replicas=1,
        dynamic_ondemand_fallback=True, forecast_enabled=True,
        forecast_bucket_seconds=10.0, forecast_season_seconds=300.0,
        forecast_horizon_seconds=60.0),
    trace_fn=lambda: sim_traffic.diurnal(2.0, 14.0, 300.0, 3),
    fault_rules=[{'kind': 'preempt_signal', 'site': 'sim_storm',
                  'at': 12, 'n': 3},
                 {'kind': 'preempt_signal', 'site': 'sim_storm',
                  'at': 40, 'n': 3}],
    sim_kwargs=dict(provision_s=25.0, storm_dt=10.0),
))

_register(Scenario(
    name='zone_outage',
    description='Zone z0 drops: every replica in one of three zones '
                'dies in the same instant; survivors absorb, the '
                'autoscaler replaces, zero lost.',
    spec_fn=lambda: _spec(min_replicas=9, max_replicas=15,
                          target_qps_per_replica=2.0),
    trace_fn=lambda: sim_traffic.constant(12.0, 600.0),
    fault_rules=[{'kind': 'zone_outage', 'site': 'sim_zone_outage',
                  'at': 20, 'zone': 'z0'}],
    sim_kwargs=dict(provision_s=30.0, n_zones=3, storm_dt=10.0),
))

_register(Scenario(
    name='flaky_probes',
    description='Flaky/slow readiness probes (20% injected timeouts): '
                'the grace/terminate escalation must not churn the '
                'fleet or lose requests.',
    spec_fn=lambda: _spec(min_replicas=5, max_replicas=8,
                          target_qps_per_replica=2.0),
    trace_fn=lambda: sim_traffic.constant(8.0, 600.0),
    fault_rules=[{'kind': 'probe_timeout', 'site': 'probe',
                  'prob': 0.2, 'delay_s': 0.05}],
    fault_seed=11,
    sim_kwargs=dict(provision_s=25.0),
))

_register(Scenario(
    name='stragglers',
    description='Two replicas silently degrade 6x (slow HBM / noisy '
                'neighbor): queue-depth routing must absorb them with '
                'bounded SLO damage and zero lost.',
    spec_fn=lambda: _spec(min_replicas=6),
    trace_fn=lambda: sim_traffic.constant(8.0, 600.0),
    fault_rules=[{'kind': 'straggler', 'site': 'sim_straggler',
                  'at': 6, 'factor': 6.0},
                 {'kind': 'straggler', 'site': 'sim_straggler',
                  'at': 12, 'factor': 6.0}],
    sim_kwargs=dict(provision_s=20.0, storm_dt=10.0),
))

_register(Scenario(
    name='gang_churn',
    description='Gang-member churn: follower ranks of 2-host gangs '
                'die mid-run; one dead rank fails the whole gang, the '
                'gang is replaced as a unit, leader in-flight work '
                'migrates, zero lost.',
    spec_fn=lambda: _spec(min_replicas=3, max_replicas=5,
                          target_qps_per_replica=2.0, gang_hosts=2),
    trace_fn=lambda: sim_traffic.constant(4.0, 600.0),
    fault_rules=[{'kind': 'replica_crash', 'site': 'sim_gang_churn',
                  'at': 10, 'rank': 1},
                 {'kind': 'replica_crash', 'site': 'sim_gang_churn',
                  'at': 30, 'rank': 1}],
    sim_kwargs=dict(provision_s=25.0, storm_dt=10.0),
))

_register(Scenario(
    name='gray_failure_storm',
    description='Gray-failure storm: one replica wedges (accepts work '
                'that never finishes, readiness degrades), a NaN '
                'burst evicts in-flight requests retryably, a '
                'byzantine replica answers canaries wrong (quarantined '
                'before a second wrong response), and a preemption '
                'checkpoint is bit-flipped in transit (the replacement '
                'must boot cold, never byte-wrong). Zero lost.',
    spec_fn=lambda: _spec(min_replicas=6, max_replicas=10,
                          target_qps_per_replica=2.0),
    trace_fn=lambda: sim_traffic.constant(8.0, 600.0),
    fault_rules=[
        {'kind': 'wedged_step', 'site': 'sim_gray', 'at': 3},
        {'kind': 'nan_logits', 'site': 'sim_gray', 'at': 8, 'n': 4},
        {'kind': 'byzantine_response', 'site': 'sim_gray', 'at': 12},
        # Advance preemption warning -> the manager fetches the
        # replica's checkpoint -> the kv_wire rule flips one byte of
        # it -> the replacement's warmup refuses the container (400)
        # and boots cold.
        {'kind': 'preempt_signal', 'site': 'preempt_warning',
         'at': 120},
        {'kind': 'kv_corruption', 'site': 'kv_wire', 'at': 1},
    ],
    sim_kwargs=dict(provision_s=25.0, storm_dt=10.0, canary_s=30.0,
                    drain_grace_s=400.0),
))

_register(Scenario(
    name='controller_crash_storm',
    description='Controller crash-safety: a spot storm kills 2 '
                'replicas, the CONTROLLER then dies mid-recovery '
                '(env halted: its drains/launches unwind, its writes '
                'stop), the LB serves stale-while-revalidate for 60 '
                'virtual seconds, and a fresh controller boots with '
                'recover=True — journal replay must ADOPT the '
                'surviving fleet (never relaunch it, never tear a '
                'replica down twice), then a second storm proves the '
                'recovered control plane still heals. Zero lost; '
                'same-seed byte-identical.',
    spec_fn=lambda: _spec(
        min_replicas=6, max_replicas=10, target_qps_per_replica=2.0,
        base_ondemand_fallback_replicas=2,
        dynamic_ondemand_fallback=True),
    trace_fn=lambda: sim_traffic.constant(8.0, 600.0),
    fault_rules=[
        {'kind': 'preempt_signal', 'site': 'sim_storm', 'at': 8,
         'n': 2},
        {'kind': 'controller_crash', 'site': 'sim_controller',
         'at': 10},
        {'kind': 'controller_restart', 'site': 'sim_controller',
         'at': 16},
        {'kind': 'preempt_signal', 'site': 'sim_storm', 'at': 30,
         'n': 2},
    ],
    sim_kwargs=dict(provision_s=25.0, storm_dt=10.0,
                    drain_grace_s=400.0),
))

_register(Scenario(
    name='flash_crowd',
    description='Flash crowd: traffic steps 6x with no seasonal '
                'precedent — only the trend term can chase it; '
                'measures shed depth vs provisioning lead.',
    spec_fn=lambda: _spec(
        min_replicas=2, max_replicas=16, target_qps_per_replica=2.0,
        forecast_enabled=True, forecast_bucket_seconds=10.0,
        forecast_season_seconds=600.0, forecast_horizon_seconds=60.0),
    trace_fn=lambda: sim_traffic.flash_crowd(3.0, 18.0, 240.0, 720.0),
    recovery_covered=False,      # sheds expected; nothing is killed
    sim_kwargs=dict(provision_s=25.0),
))

_register(Scenario(
    name='forecast_vs_reactive',
    description='The PR-10 shed replay as a fleet scenario: identical '
                'bursty trace under reactive vs forecast autoscaling; '
                'forecast must shed strictly fewer.',
    spec_fn=lambda: _spec(min_replicas=1),     # per-variant (runner)
    trace_fn=lambda: sim_traffic.bursty(0.5, 8.0, 60.0, 300.0, 4),
    recovery_covered=False,      # sheds are the measurement
    runner=_forecast_vs_reactive_runner,
))

_register(Scenario(
    name='fleet_1k',
    description='Scale proof: 1000 fixed replicas, ~2000 QPS for 10 '
                'virtual minutes (>1M requests), light storm; the '
                'simulator itself must stay fast and deterministic.',
    spec_fn=lambda: _spec(min_replicas=1000),
    # ~0.9x the fleet's rated capacity (1000 replicas x ~2 req/s):
    # loaded enough that queueing is visible, headroom enough that
    # the zone outage is absorbable.
    trace_fn=lambda: sim_traffic.constant(
        1800.0, 600.0,
        sim_traffic.RequestShape(latency_frac=0.3)),
    policy='round_robin',
    fault_rules=[{'kind': 'zone_outage', 'site': 'sim_zone_outage',
                  'at': 30, 'zone': 'z1'}],
    sim_kwargs=dict(provision_s=30.0, n_zones=10, arrival_dt=0.5,
                    max_chunk=16, keep_log=False, storm_dt=10.0,
                    drain_grace_s=300.0),
))


_register(Scenario(
    name='multi_turn_affinity',
    description='Prefix-affinity routing comparison: one multi-turn '
                'trace (800 sessions, prefix-extending prompts) over '
                'a 1000-replica fleet under queue_depth vs '
                'prefix_affinity; affinity must score a strictly '
                'higher warm-TTFT hit rate AND strictly fewer '
                'prefix-recompute tokens.',
    spec_fn=lambda: _spec(min_replicas=1000),
    trace_fn=lambda: sim_traffic.multi_turn(20.0, 240.0, 800, 192),
    policy='prefix_affinity',
    recovery_covered=False,      # nothing is killed; a measurement
    sim_kwargs=dict(provision_s=20.0, provision_jitter=0.0,
                    n_zones=10, keep_log=False, drain_grace_s=200.0),
    runner=_multi_turn_affinity_runner,
))

_register(Scenario(
    name='slo_burst',
    description='SLO burn-rate drill: a fixed 3-replica fleet with a '
                'declared latency-tier TTFT objective serves 55 '
                'virtual minutes of healthy traffic, then every '
                'replica silently degrades 12x for the final stretch. '
                'The fleet aggregator (fed over /telemetry/summary on '
                'the probe path) must flip the 5-minute burn rate '
                'above 1 while the 1-hour window stays below — the '
                'multi-window page/ticket distinction.',
    spec_fn=lambda: _spec(
        min_replicas=3,
        slos={'latency': {'ttft_ms': 2000.0, 'target': 0.9},
              'throughput': {'ttft_ms': 10000.0, 'target': 0.9}}),
    trace_fn=lambda: sim_traffic.constant(4.0, 3600.0),
    fault_rules=[{'kind': 'straggler', 'site': 'sim_straggler',
                  'at': 330, 'factor': 12.0},
                 {'kind': 'straggler', 'site': 'sim_straggler',
                  'at': 331, 'factor': 12.0},
                 {'kind': 'straggler', 'site': 'sim_straggler',
                  'at': 332, 'factor': 12.0}],
    recovery_covered=False,      # nothing dies; latency IS the drill
    sim_kwargs=dict(provision_s=20.0, provision_jitter=0.0,
                    storm_dt=10.0, keep_log=False,
                    drain_grace_s=300.0),
))

_register(Scenario(
    name='lb_crash',
    description='Horizontal LB tier under fire: 2 LB processes share '
                'the sync feed, multi-turn sessions split between '
                'them by client hash; one LB dies mid-run — its '
                'sticky sessions and probe caches are gone, the '
                'survivor re-forms affinity from the replicas\' '
                'advertised digests, and ZERO requests are lost.',
    spec_fn=lambda: _spec(min_replicas=3, max_replicas=6,
                          target_qps_per_replica=2.0),
    trace_fn=lambda: sim_traffic.multi_turn(4.0, 300.0, 40, 192),
    policy='prefix_affinity',
    fault_rules=[{'kind': 'lb_crash', 'site': 'sim_lb_crash',
                  'at': 12}],
    sim_kwargs=dict(provision_s=20.0, provision_jitter=0.0,
                    n_lbs=2, storm_dt=10.0, drain_grace_s=200.0),
))


def get_scenario(name: str) -> Scenario:
    if name not in SCENARIOS:
        raise ValueError(f'unknown scenario {name!r}; choose from '
                         f'{sorted(SCENARIOS)}')
    return SCENARIOS[name]


def run_scenario(name: str, seed: int = 0,
                 policy: Optional[str] = None,
                 **overrides: Any) -> Dict[str, Any]:
    """Run one named scenario; returns its report dict (the CLI prints
    it as JSON; the bench embeds it)."""
    return get_scenario(name).run(seed=seed, policy=policy, **overrides)
