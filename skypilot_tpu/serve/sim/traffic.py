"""Deterministic traffic traces for the fleet simulator.

A trace is a *rate function* ``qps(t)`` plus request-shape parameters;
the fleet integrates it with a fractional-carry accumulator (no RNG in
the arrival counts — byte-identical replays come for free, and the
PR-10 forecast-vs-reactive comparison needs the two autoscalers to see
EXACTLY the same arrivals). Shapes mirror the workloads the serving
benches use: the ShareGPT-like anchor (~220 prompt / ~190 generated
tokens) with a latency/throughput tier mix.

Shipped shapes:

- ``constant`` — steady load (calibration / straggler scenarios).
- ``diurnal`` — a smooth day curve (half-sinusoid on a base), period
  ``season_s``; the forecaster's seasonal-naive component learns it.
- ``bursty`` — the PR-10 replay shape: ``burst_qps`` for the first
  ``burst_s`` of every ``season_s`` period, ``base_qps`` otherwise.
- ``flash_crowd`` — a step to ``peak_qps`` at ``at_s`` (the ramp no
  season predicts; only the trend term can chase it).
- ``multi_turn`` — steady load of MULTI-TURN SESSIONS: arrivals are
  assigned round-robin to ``n_sessions`` conversations, and each visit
  is that session's next turn — its prompt is the whole conversation
  so far (strictly prefix-extending, ``turn_tokens`` new tokens per
  turn). Token ids are a pure function of (session, position): no RNG,
  byte-identical replays, and the prefix-affinity LB's page-grid
  hashes see EXACTLY the chains the simulated replicas advertise.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Callable, Iterator, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class RequestShape:
    prompt_tokens: float = 220.0
    gen_tokens: float = 190.0
    latency_frac: float = 0.3     # fraction routed as the latency tier


@dataclasses.dataclass(frozen=True)
class SessionMix:
    """Multi-turn session structure riding on a trace: arrivals are
    dealt round-robin over ``n_sessions`` conversations whose prompts
    grow by ``turn_tokens`` tokens per turn (each turn's prompt is a
    strict prefix of the next — the property prefix-affinity routing
    exploits)."""
    n_sessions: int
    turn_tokens: int = 192


def session_tokens(session_id: int, n_tokens: int) -> List[int]:
    """Token ``i`` of session ``s`` is a pure function of ``(s, i)`` —
    so turn ``t``'s prompt is automatically a strict prefix of turn
    ``t+1``'s, with zero stored state and zero RNG."""
    base = session_id * 1009 + 7
    return [(base + i * 31) % 50021 for i in range(n_tokens)]


@dataclasses.dataclass(frozen=True)
class Trace:
    """A rate function over [0, duration_s) plus request shapes."""
    name: str
    rate_fn: Callable[[float], float]
    duration_s: float
    shape: RequestShape = RequestShape()
    sessions: Optional[SessionMix] = None   # multi-turn structure

    def arrivals(self, dt: float) -> Iterator[Tuple[float, int]]:
        """Yield ``(t, n)`` arrival batches every ``dt`` seconds with
        fractional carry, so ``sum(n)`` tracks the rate integral
        exactly (no aliasing at low rates)."""
        carry = 0.0
        steps = int(math.ceil(self.duration_s / dt))
        for i in range(steps):
            t = i * dt
            carry += max(0.0, self.rate_fn(t)) * dt
            n = int(carry)
            if n > 0:
                carry -= n
                yield t, n

    def total_requests(self, dt: float) -> int:
        return sum(n for _, n in self.arrivals(dt))


def constant(qps: float, duration_s: float,
             shape: RequestShape = RequestShape()) -> Trace:
    return Trace('constant', lambda t: qps, duration_s, shape)


def diurnal(base_qps: float, peak_qps: float, season_s: float,
            seasons: int,
            shape: RequestShape = RequestShape()) -> Trace:
    def rate(t: float) -> float:
        phase = (t % season_s) / season_s
        return base_qps + (peak_qps - base_qps) * max(
            0.0, math.sin(math.pi * phase))
    return Trace('diurnal', rate, season_s * seasons, shape)


def bursty(base_qps: float, burst_qps: float, burst_s: float,
           season_s: float, seasons: int,
           shape: RequestShape = RequestShape()) -> Trace:
    """The PR-10 replay shape (bench ``_spot_autoscaler_sim``):
    ``burst_qps`` for the first ``burst_s`` of every season."""
    def rate(t: float) -> float:
        return burst_qps if (t % season_s) < burst_s else base_qps
    return Trace('bursty', rate, season_s * seasons, shape)


def flash_crowd(base_qps: float, peak_qps: float, at_s: float,
                duration_s: float,
                shape: RequestShape = RequestShape()) -> Trace:
    def rate(t: float) -> float:
        return peak_qps if t >= at_s else base_qps
    return Trace('flash_crowd', rate, duration_s, shape)


def multi_turn(qps: float, duration_s: float, n_sessions: int,
               turn_tokens: int = 192,
               shape: RequestShape = RequestShape()) -> Trace:
    """Steady ``qps`` of multi-turn session traffic: ~``qps *
    duration_s / n_sessions`` turns per session, prompts growing
    ``turn_tokens`` per turn. ``shape.prompt_tokens`` is ignored for
    session requests (the session's own growing prompt wins);
    ``gen_tokens`` and the tier mix still apply."""
    return Trace('multi_turn', lambda t: qps, duration_s, shape,
                 sessions=SessionMix(n_sessions=max(1, int(n_sessions)),
                                     turn_tokens=max(1,
                                                     int(turn_tokens))))


TRACES = {
    'constant': constant,
    'diurnal': diurnal,
    'bursty': bursty,
    'flash_crowd': flash_crowd,
    'multi_turn': multi_turn,
}
