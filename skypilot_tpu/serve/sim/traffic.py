"""Deterministic traffic traces for the fleet simulator.

A trace is a *rate function* ``qps(t)`` plus request-shape parameters;
the fleet integrates it with a fractional-carry accumulator (no RNG in
the arrival counts — byte-identical replays come for free, and the
PR-10 forecast-vs-reactive comparison needs the two autoscalers to see
EXACTLY the same arrivals). Shapes mirror the workloads the serving
benches use: the ShareGPT-like anchor (~220 prompt / ~190 generated
tokens) with a latency/throughput tier mix.

Shipped shapes:

- ``constant`` — steady load (calibration / straggler scenarios).
- ``diurnal`` — a smooth day curve (half-sinusoid on a base), period
  ``season_s``; the forecaster's seasonal-naive component learns it.
- ``bursty`` — the PR-10 replay shape: ``burst_qps`` for the first
  ``burst_s`` of every ``season_s`` period, ``base_qps`` otherwise.
- ``flash_crowd`` — a step to ``peak_qps`` at ``at_s`` (the ramp no
  season predicts; only the trend term can chase it).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Callable, Iterator, Tuple


@dataclasses.dataclass(frozen=True)
class RequestShape:
    prompt_tokens: float = 220.0
    gen_tokens: float = 190.0
    latency_frac: float = 0.3     # fraction routed as the latency tier


@dataclasses.dataclass(frozen=True)
class Trace:
    """A rate function over [0, duration_s) plus request shapes."""
    name: str
    rate_fn: Callable[[float], float]
    duration_s: float
    shape: RequestShape = RequestShape()

    def arrivals(self, dt: float) -> Iterator[Tuple[float, int]]:
        """Yield ``(t, n)`` arrival batches every ``dt`` seconds with
        fractional carry, so ``sum(n)`` tracks the rate integral
        exactly (no aliasing at low rates)."""
        carry = 0.0
        steps = int(math.ceil(self.duration_s / dt))
        for i in range(steps):
            t = i * dt
            carry += max(0.0, self.rate_fn(t)) * dt
            n = int(carry)
            if n > 0:
                carry -= n
                yield t, n

    def total_requests(self, dt: float) -> int:
        return sum(n for _, n in self.arrivals(dt))


def constant(qps: float, duration_s: float,
             shape: RequestShape = RequestShape()) -> Trace:
    return Trace('constant', lambda t: qps, duration_s, shape)


def diurnal(base_qps: float, peak_qps: float, season_s: float,
            seasons: int,
            shape: RequestShape = RequestShape()) -> Trace:
    def rate(t: float) -> float:
        phase = (t % season_s) / season_s
        return base_qps + (peak_qps - base_qps) * max(
            0.0, math.sin(math.pi * phase))
    return Trace('diurnal', rate, season_s * seasons, shape)


def bursty(base_qps: float, burst_qps: float, burst_s: float,
           season_s: float, seasons: int,
           shape: RequestShape = RequestShape()) -> Trace:
    """The PR-10 replay shape (bench ``_spot_autoscaler_sim``):
    ``burst_qps`` for the first ``burst_s`` of every season."""
    def rate(t: float) -> float:
        return burst_qps if (t % season_s) < burst_s else base_qps
    return Trace('bursty', rate, season_s * seasons, shape)


def flash_crowd(base_qps: float, peak_qps: float, at_s: float,
                duration_s: float,
                shape: RequestShape = RequestShape()) -> Trace:
    def rate(t: float) -> float:
        return peak_qps if t >= at_s else base_qps
    return Trace('flash_crowd', rate, duration_s, shape)


TRACES = {
    'constant': constant,
    'diurnal': diurnal,
    'bursty': bursty,
    'flash_crowd': flash_crowd,
}
