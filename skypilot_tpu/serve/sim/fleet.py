"""FleetSimulator: one simulated service end to end.

Wires the REAL control plane — a :class:`ServeController` (autoscaler
+ forecaster + replica manager on the ``SimControlPlaneEnv`` seam) and
a REAL LB policy object — to a synthetic fleet of
:class:`SimReplica` queueing models and a deterministic traffic trace,
then runs the whole thing on the virtual clock:

- a logical task replays the controller loop (``tick`` every
  ``tick_s`` virtual seconds — probe sweeps, scaling decisions,
  drains, checkpoint/warmup, backfill, all the real code),
- an LB-sync callback every ``sync_s`` mirrors the live
  ``/controller/load_balancer_sync`` round-trip: ready URLs + roles +
  gang blocks into the policy, arrival timestamps + tiers into the
  autoscaler/forecaster,
- arrival callbacks integrate the trace and dispatch batches through
  ``policy.select_replica`` into the replicas' fluid queues,
- a storm callback fires the scenario's ``sim_*`` fault sites
  (correlated spot storms, zone outages, stragglers, gang churn),
- replica deaths migrate in-flight work to survivors (the LB's
  recovery contract: ZERO lost requests whenever any replica
  eventually serves — un-placeable work parks in a retry queue and
  drains on later syncs).

Every event appends one line to the event log; the report carries its
SHA-256 — same seed, byte-identical log (the determinism acceptance
gate). No wall-clock reads anywhere (graftcheck GC117).
"""
from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from skypilot_tpu.serve import controller as controller_lib
from skypilot_tpu.serve import faults as faults_lib
from skypilot_tpu.serve import load_balancing_policies as lb_policies
from skypilot_tpu.serve.service_spec import SkyServiceSpec
from skypilot_tpu.serve.sim import core as sim_core
from skypilot_tpu.serve.sim import env as sim_env
from skypilot_tpu.serve.sim import replica as sim_replica
from skypilot_tpu.serve.sim import traffic as sim_traffic

# Sim fault sites the storm callback evaluates, in a fixed order (the
# order is part of the determinism contract). ``sim_gray`` carries the
# gray-failure kinds: wedged_step / nan_logits / byzantine_response.
# ``sim_controller`` carries the control-plane kinds:
# controller_crash (the controller's env halts — its tasks unwind, its
# writes stop, the LB serves stale) and controller_restart (a fresh
# ServeController boots with recover=True over the same world and
# reconciles the orphaned fleet).
SIM_FAULT_SITES = ('sim_storm', 'sim_zone_outage', 'sim_straggler',
                   'sim_gang_churn', 'sim_gray', 'sim_controller',
                   'sim_lb_crash')

# Per-tier TTFT SLO targets (seconds) — what "attainment" means.
DEFAULT_SLO_TTFT = {'latency': 2.0, 'throughput': 10.0}

_MAX_KEPT_LOG_LINES = 200_000


def _weighted_percentile(samples: List[Tuple[float, int]],
                         q: float) -> float:
    """Percentile of (value, weight) samples (0 when empty)."""
    if not samples:
        return 0.0
    samples = sorted(samples)
    total = sum(w for _, w in samples)
    target = q * total
    acc = 0
    for v, w in samples:
        acc += w
        if acc >= target:
            return v
    return samples[-1][0]


class FleetSimulator:

    def __init__(self, *, spec: SkyServiceSpec,
                 trace: sim_traffic.Trace, seed: int = 0,
                 policy_name: str = 'queue_depth',
                 curve: Optional[sim_replica.ServiceCurve] = None,
                 fault_spec: Optional[Dict[str, Any]] = None,
                 tick_s: float = 10.0, sync_s: float = 5.0,
                 arrival_dt: float = 1.0, max_chunk: int = 8,
                 storm_dt: float = 10.0, provision_s: float = 30.0,
                 provision_jitter: float = 0.3, n_zones: int = 3,
                 slo_ttft: Optional[Dict[str, float]] = None,
                 drain_grace_s: float = 300.0,
                 never_drain_clusters: Optional[set] = None,
                 keep_log: bool = True,
                 canary_s: float = 0.0,
                 n_lbs: int = 1,
                 service_name: str = 'sim-svc'):
        self.spec = spec
        self.trace = trace
        self.seed = seed
        self.policy_name = policy_name
        self.tick_s = tick_s
        self.sync_s = sync_s
        self.arrival_dt = arrival_dt
        self.max_chunk = max(1, int(max_chunk))
        self.storm_dt = storm_dt
        self.slo_ttft = dict(slo_ttft or DEFAULT_SLO_TTFT)
        self.drain_grace_s = drain_grace_s
        self.keep_log = keep_log

        self.loop = sim_core.EventLoop()
        self.curve = curve or sim_replica.ServiceCurve.from_bench()
        self.world = sim_env.SimWorld(
            self.loop, self.curve, seed=seed, n_zones=n_zones,
            provision_s=provision_s, provision_jitter=provision_jitter,
            never_drain_clusters=never_drain_clusters)
        self.injector = (faults_lib.FaultInjector(fault_spec)
                         if fault_spec and fault_spec.get('rules')
                         else None)
        self.canary_s = canary_s
        self.service_name = service_name
        self.env = sim_env.SimControlPlaneEnv(self.world, seed=seed,
                                              injector=self.injector)
        self.controller = controller_lib.ServeController(
            service_name, spec, {'resources': {'cloud': 'sim'}},
            port=1, env=self.env)
        if canary_s > 0:
            # Byzantine-detection canary on the virtual clock: the
            # REAL manager probes each READY replica's /generate with
            # the known-digest prompt; SimReplica answers through
            # canary_response_tokens.
            self.controller.replica_manager.configure_canary(canary_s)
        # Horizontal LB tier: ``n_lbs`` REAL policy instances share the
        # controller sync feed; each session key picks its LB by a
        # deterministic client-side hash (standing in for the live
        # tier's DNS/anycast spread). Single-LB sims keep the exact
        # pre-tier behavior: one policy, zero probe-TTL jitter.
        self.n_lbs = max(1, int(n_lbs))
        self.policies: List[lb_policies.LoadBalancingPolicy] = []
        for i in range(self.n_lbs):
            p = lb_policies.make_policy(policy_name)
            p.configure_transport(fetch_json=self.world.fetch_json,
                                  monotonic=lambda: self.loop.now)
            if self.n_lbs > 1 and hasattr(p, 'set_probe_identity'):
                p.set_probe_identity(f'sim-lb{i}')
            if isinstance(p, lb_policies.PrefixAffinityPolicy):
                p.configure_affinity_observer(self._note_affinity)
                p.configure_migration(self._sim_migrate)
            self.policies.append(p)
        self.policy = self.policies[0]
        self._live_lb_idx: List[int] = list(range(self.n_lbs))
        self.world.on_replica_killed = self._on_replica_killed

        # ---------------------------------------- multi-turn sessions
        self._sess = trace.sessions
        self._arrival_seq = 0
        self._session_turn: Dict[int, int] = {}
        # sid -> (running sha1 over int32 token bytes, cumulative
        # page-chain hash hexes) — extended incrementally per turn,
        # matching the engine recipe the LB policy hashes against.
        self._session_chain: Dict[int, Tuple[Any, List[str]]] = {}
        # sid -> pages ever computed anywhere (recompute baseline).
        self._session_done_pages: Dict[int, int] = {}

        # ------------------------------------------------------- metrics
        self.arrived = 0
        self.completed = 0
        self.sheds: Dict[str, int] = {'no_replica': 0, 'overload': 0}
        self.migrated = 0
        self.slo_met: Dict[str, int] = {}
        self.slo_total: Dict[str, int] = {}
        self.ttft_samples: Dict[str, List[Tuple[float, int]]] = {}
        self.recovery_samples: List[Tuple[float, int]] = []
        self.chip_seconds = 0.0
        self.peak_ready = 0
        self.ready_now = 0
        # Controller failure-domain bookkeeping (round 15).
        self._controller_down = False
        self.controller_crashes = 0
        self.controller_restarts = 0
        self.reconcile_stats: Dict[str, int] = {}
        # Prefix-affinity accounting (round 18).
        self.session_requests = 0
        self.warm_hits = 0
        self.recompute_tokens = 0
        self.affinity_outcomes: Dict[str, int] = {
            'hit': 0, 'miss': 0, 'migrated': 0}
        self.prefix_migrations = 0
        self.lb_crashes = 0
        self.lb_reroutes = 0
        self._inflight = 0
        self._retry_q: List[Tuple[int, str, float, float,
                                  Optional[float],
                                  Optional[Dict[str, Any]]]] = []
        self._pending_ts: List[float] = []
        self._pending_tiers: List[str] = []
        self._tier_carry = 0.0
        self._stop = False
        self._n_events = 0
        self._log_hash = hashlib.sha256()
        self._log_lines: List[str] = []
        self._log_truncated = False

    # ------------------------------------------------------------ logging
    def _log(self, kind: str, detail: str) -> None:
        line = f'{self.loop.now:.6f}|{kind}|{detail}\n'
        self._n_events += 1
        self._log_hash.update(line.encode())
        if self.keep_log:
            if len(self._log_lines) < _MAX_KEPT_LOG_LINES:
                self._log_lines.append(line)
            else:
                self._log_truncated = True

    # ------------------------------------------------------- control loop
    def _controller_loop(self, controller, env) -> None:
        """One controller process's tick loop: bound to ITS controller
        and env, so a crash (env halt) unwinds exactly this loop and a
        restarted controller gets a fresh one."""
        while not self._stop:
            controller.tick(sync_state=False)
            env.sleep(self.tick_s)

    def _crash_controller(self) -> None:
        if self._controller_down:
            return
        self.controller_crashes += 1
        self._controller_down = True
        # Halt the dead controller's env: its tick loop and every
        # background task (drain polls, launches, teardowns) unwind at
        # their next effect; its persistence writes stop landing. The
        # WORLD — live replicas, virtual serve DB — survives.
        self.env.halt()
        self._log('ctrl_crash', f'ready_at_crash={self.ready_now}')

    def _restart_controller(self) -> None:
        if not self._controller_down:
            return
        self.controller_restarts += 1
        # A fresh process: new env over the SAME world (the virtual
        # serve DB it reconciles from), deterministic RNG stream keyed
        # by the restart ordinal.
        self.env = sim_env.SimControlPlaneEnv(
            self.world, seed=self.seed + 7919 * self.controller_restarts,
            injector=self.injector)
        self.controller = controller_lib.ServeController(
            self.service_name, self.spec,
            {'resources': {'cloud': 'sim'}}, port=1, env=self.env,
            recover=True)
        if self.canary_s > 0:
            self.controller.replica_manager.configure_canary(
                self.canary_s)
        stats = dict(self.controller.last_reconcile)
        for key, val in stats.items():
            self.reconcile_stats[key] = (
                self.reconcile_stats.get(key, 0) + val)
        self._controller_down = False
        self._log('ctrl_restart',
                  'reconciled=' + ','.join(
                      f'{k}:{v}' for k, v in sorted(stats.items())
                      if v))
        self.loop.spawn(self._controller_loop, self.controller,
                        self.env, name='controller')

    def _lb_sync(self) -> None:
        if self._controller_down:
            # Stale-while-revalidate: the sync fails, the LB keeps
            # serving its last-synced view (dead replicas leave it
            # through the dispatch loop's local eviction — the
            # transparent-retry exclusion), and the arrival signal
            # queues BOUNDED for when the controller returns.
            if len(self._pending_ts) > 100_000:
                self._pending_ts = self._pending_ts[-100_000:]
                self._pending_tiers = self._pending_tiers[-100_000:]
            self._log('sync_stale', f'ready={self.ready_now}')
            self.chip_seconds += (self.ready_now
                                  * self.controller.replica_manager
                                  .parallelism_plan().chips
                                  * self.sync_s)
            self._drain_retry_queue()
            if not self._stop:
                self.loop.schedule(self.sync_s, self._lb_sync)
            return
        mgr = self.controller.replica_manager
        urls = mgr.ready_urls()
        roles = mgr.replica_roles()
        gangs = mgr.replica_gangs()
        for i in self._live_lb_idx:
            p = self.policies[i]
            p.set_ready_replicas(urls)
            p.set_replica_roles(roles)
            p.set_replica_gangs(gangs)
        self.controller.autoscaler.collect_request_information(
            self._pending_ts, self._pending_tiers)
        self._pending_ts, self._pending_tiers = [], []
        self.ready_now = len(urls)
        self.peak_ready = max(self.peak_ready, self.ready_now)
        plan = mgr.parallelism_plan()
        self.chip_seconds += (self.ready_now * plan.chips
                              * max(1, plan.hosts) * self.sync_s)
        self._log('sync', f'ready={self.ready_now}')
        self._drain_retry_queue()
        if not self._stop:
            self.loop.schedule(self.sync_s, self._lb_sync)

    # ------------------------------------------------------------ arrivals
    def _start_arrivals(self) -> None:
        self._arrivals = self.trace.arrivals(self.arrival_dt)
        self._schedule_next_arrival()

    def _schedule_next_arrival(self) -> None:
        batch = next(self._arrivals, None)
        if batch is None:
            return
        t, n = batch
        self.loop.schedule_at(t, self._arrive, n)

    def _arrive(self, n: int) -> None:
        now = self.loop.now
        self.arrived += n
        # Deterministic tier split with fractional carry.
        self._tier_carry += n * self.trace.shape.latency_frac
        n_lat = int(self._tier_carry)
        self._tier_carry -= n_lat
        self._pending_ts.extend([now] * n)
        self._pending_tiers.extend(
            ['latency'] * n_lat + ['throughput'] * (n - n_lat))
        if self._sess is not None:
            # Session traffic dispatches per-request (each turn has its
            # own prompt identity); the tier split is the same
            # fractional-carry order as the batched path.
            for i in range(n):
                tier = 'latency' if i < n_lat else 'throughput'
                self._dispatch(1, tier, migrated_from=None,
                               failed_at=None,
                               session=self._next_session_turn())
        else:
            for tier, count in (('latency', n_lat),
                                ('throughput', n - n_lat)):
                while count > 0:
                    chunk = min(count, self.max_chunk)
                    count -= chunk
                    self._dispatch(chunk, tier, migrated_from=None,
                                   failed_at=None)
        self._schedule_next_arrival()

    def _next_session_turn(self) -> Dict[str, Any]:
        """Deal the next arrival to its session (round-robin) and
        materialize that session's next turn: full-conversation prompt
        tokens plus the cumulative page-chain hashes, extended
        incrementally with the engine's exact recipe (sha1 over int32
        page bytes)."""
        sess = self._sess
        assert sess is not None
        # Hash-scrambled session pick (deterministic, no RNG): plain
        # round-robin would revisit each session at the SAME position
        # of every arrival batch, and the fluid model's lockstep then
        # makes ANY load-ranking policy accidentally sticky — the
        # scramble gives real interleaving, like live traffic.
        sid = int.from_bytes(
            hashlib.sha1(str(self._arrival_seq).encode()).digest()[:4],
            'big') % sess.n_sessions
        self._arrival_seq += 1
        turn = self._session_turn.get(sid, 0)
        self._session_turn[sid] = turn + 1
        n_tok = sess.turn_tokens * (turn + 1)
        tokens = sim_traffic.session_tokens(sid, n_tok)
        page = sim_replica.SimReplica.PAGE
        full = (n_tok - 1) // page
        chain = self._session_chain.get(sid)
        if chain is None:
            chain = (hashlib.sha1(), [])
            self._session_chain[sid] = chain
        h, hashes = chain
        while len(hashes) < full:
            k = len(hashes)
            h.update(np.asarray(tokens[k * page:(k + 1) * page],
                                np.int32).tobytes())
            hashes.append(h.hexdigest())
        return {'sid': sid, 'key': f's{sid}', 'turn': turn,
                'tokens': tokens, 'hashes': hashes[:full],
                'n_tok': n_tok, 'page': page}

    # ------------------------------------------------------------ dispatch
    def _lb_for_key(self, key: str) -> int:
        """The LB a client would hit for ``key``: sha1 spread over the
        FULL tier; when the home LB is dead, the deterministic re-pick
        lands on a survivor (counted as a reroute)."""
        h = int.from_bytes(hashlib.sha1(key.encode()).digest()[:4],
                           'big')
        home = h % self.n_lbs
        if home in self._live_lb_idx:
            return home
        self.lb_reroutes += 1
        return self._live_lb_idx[h % len(self._live_lb_idx)]

    def _dispatch(self, count: int, tier: str, *,
                  migrated_from: Optional[str],
                  failed_at: Optional[float],
                  exclude: Optional[Set[str]] = None,
                  session: Optional[Dict[str, Any]] = None,
                  trace_id: Optional[str] = None) -> None:
        now = self.loop.now
        shape = self.trace.shape
        exclude = set(exclude or ())
        if session is not None:
            lb_idx = self._lb_for_key(session['key'])
            ctx: Optional[Dict[str, Any]] = {
                'tokens': session['tokens'],
                'request_key': session['key']}
            prompt_tokens = float(session['n_tok'])
        else:
            lb_idx = self._live_lb_idx[0]
            ctx = None
            prompt_tokens = shape.prompt_tokens
        policy = self.policies[lb_idx]
        while True:
            url = policy.select_replica(exclude=exclude or None,
                                        context=ctx)
            if url is None:
                if migrated_from is not None:
                    # Zero-lost contract: migrated work is never
                    # dropped — park it until capacity returns.
                    self._retry_q.append((count, tier, now,
                                          shape.gen_tokens, failed_at,
                                          session))
                    self._log('park', f'n={count} tier={tier}')
                else:
                    self.sheds['no_replica'] += count
                    self._log('shed',
                              f'reason=no_replica n={count} '
                              f'tier={tier}')
                return
            rep = self.world.replicas.get(url)
            if rep is None:
                exclude.add(url)
                continue
            warm_pages = 0
            if session is not None and session['hashes']:
                warm_pages = rep.match_prefix(session['hashes'])
            try:
                job = rep.enqueue(now, count, prompt_tokens,
                                  shape.gen_tokens, tier,
                                  warm_tokens=float(
                                      warm_pages * sim_replica
                                      .SimReplica.PAGE))
            except sim_replica.SimHTTPError:
                # Stale policy view (dead or draining replica): the
                # live LB's transparent retry — exclude and re-select.
                exclude.add(url)
                continue
            if job is None:
                self.sheds['overload'] += count
                self._log('shed', f'reason=overload n={count} '
                                  f'tier={tier} url={url}')
                return
            job.migrated_from = migrated_from
            job.failed_at = failed_at
            job.lb_idx = lb_idx
            job.session = session
            if trace_id is not None:
                # A migration leg joins the original request's trace.
                job.trace_id = trace_id
            policy.pre_execute(url)
            self._inflight += count
            if session is not None:
                self._account_session_dispatch(session, rep,
                                               warm_pages)
                self._log('dispatch',
                          f'n={count} tier={tier} url={url} '
                          f'ttft={job.ttft_s:.4f} '
                          f'key={session["key"]} warm={warm_pages}')
            else:
                self._log('dispatch',
                          f'n={count} tier={tier} url={url} '
                          f'ttft={job.ttft_s:.4f}')
            self.loop.schedule(max(0.0, job.finish_t - now),
                               self._complete, url, job)
            return

    def _account_session_dispatch(self, session: Dict[str, Any],
                                  rep: sim_replica.SimReplica,
                                  warm_pages: int) -> None:
        """Warm-hit / recompute bookkeeping for one session dispatch,
        plus the replica-side residency update (after prefill the
        replica holds the request's whole page-grid chain)."""
        page = session['page']
        full = len(session['hashes'])
        sid = session['sid']
        self.session_requests += 1
        if warm_pages > 0:
            self.warm_hits += 1
        done = self._session_done_pages.get(sid, 0)
        # Pages some replica already computed for this session but the
        # CHOSEN replica has to redo — the waste affinity routing (and
        # proactive migration) exists to avoid.
        self.recompute_tokens += max(0, min(done, full)
                                     - warm_pages) * page
        if full > 0:
            rep.note_prefix(session['hashes'][full - 1], full * page)
        self._session_done_pages[sid] = max(done, full)

    def _note_affinity(self, outcome: str, recompute_tokens: int) -> None:
        del recompute_tokens   # fleet computes its own (ground truth)
        if outcome in self.affinity_outcomes:
            self.affinity_outcomes[outcome] += 1

    def _sim_migrate(self, src: str, dst: str, chain_hash: str,
                     n_tokens: int) -> bool:
        """The simulator's migration executor: the live LB ships a
        CRC-checked SKPF blob src -> dst; here the chain simply becomes
        resident at ``dst`` (same observable effect: the next matching
        request prefills warm there)."""
        src_rep = self.world.replicas.get(src)
        dst_rep = self.world.replicas.get(dst)
        if (src_rep is None or dst_rep is None or not dst_rep.alive
                or src_rep.match_prefix([chain_hash]) == 0):
            return False
        dst_rep.note_prefix(chain_hash, n_tokens)
        self.prefix_migrations += 1
        self._log('prefix_migrate',
                  f'src={src} dst={dst} len={n_tokens}')
        return True

    def _complete(self, url: str, job: sim_replica.SimJob) -> None:
        if job.cancelled:
            return
        rep = self.world.replicas.get(url)
        if rep is not None:
            rep.complete(job)
        self.policies[job.lb_idx].post_execute(url)
        self._inflight -= job.count
        self.completed += job.count
        tier = job.tier
        target = self.slo_ttft.get(tier, 10.0)
        self.slo_total[tier] = self.slo_total.get(tier, 0) + job.count
        if job.ttft_s <= target:
            self.slo_met[tier] = self.slo_met.get(tier, 0) + job.count
        self.ttft_samples.setdefault(tier, []).append(
            (job.ttft_s, job.count))
        if job.failed_at is not None:
            self.recovery_samples.append(
                (self.loop.now - job.failed_at, job.count))
        self._log('complete', f'n={job.count} tier={tier} url={url}')

    # ----------------------------------------------------------- failures
    def _on_replica_killed(self, rep: sim_replica.SimReplica,
                           jobs: List[sim_replica.SimJob]) -> None:
        self._log('replica_killed',
                  f'url={rep.url} zone={rep.zone} '
                  f'inflight_jobs={len(jobs)}')
        for job in jobs:
            self.policies[job.lb_idx].post_execute(rep.url)
            self._inflight -= job.count
            self.migrated += job.count
            failed_at = (job.failed_at if job.failed_at is not None
                         else self.loop.now)
            self._dispatch(job.count, job.tier,
                           migrated_from=rep.url, failed_at=failed_at,
                           exclude={rep.url}, session=job.session,
                           trace_id=job.trace_id)

    def _drain_retry_queue(self) -> None:
        if not self._retry_q:
            return
        pending, self._retry_q = self._retry_q, []
        for count, tier, _, _, failed_at, session in pending:
            self._dispatch(count, tier, migrated_from='retry-queue',
                           failed_at=failed_at, session=session)

    # -------------------------------------------------------------- storms
    def _storm_check(self) -> None:
        inj = self.injector
        assert inj is not None
        for site in SIM_FAULT_SITES:
            rule = inj.fire(site)
            if rule is not None:
                self._apply_sim_fault(site, rule)
        if not self._stop:
            self.loop.schedule(self.storm_dt, self._storm_check)

    def _apply_sim_fault(self, site: str,
                         rule: faults_lib.FaultRule) -> None:
        live = self.world.live_replicas()
        if site == 'sim_storm':
            # Correlated spot storm: the n newest spot replicas die in
            # the same instant (registry order = launch order).
            victims = [r for r in live if r.is_spot][-rule.n:]
            self._log('storm', f'n={len(victims)}')
            for r in victims:
                self.world.kill_replica(r)
        elif site == 'sim_zone_outage':
            zone = rule.zone or 'z0'
            victims = [r for r in live if r.zone == zone]
            self._log('zone_outage', f'zone={zone} n={len(victims)}')
            for r in victims:
                self.world.kill_replica(r)
        elif site == 'sim_straggler':
            for r in live:
                if r.slowdown == 1.0 and r.gang_rank == 0:
                    r.slowdown = max(1.0, rule.factor)
                    self._log('straggler',
                              f'url={r.url} factor={r.slowdown}')
                    break
        elif site == 'sim_gang_churn':
            want_rank = rule.rank if rule.rank is not None else 1
            for r in live:
                if r.gang_id is not None and r.gang_rank == want_rank:
                    self._log('gang_churn',
                              f'gang={r.gang_id} rank={r.gang_rank}')
                    self.world.kill_replica(r)
                    break
        elif site == 'sim_gray':
            self._apply_gray_fault(rule, live)
        elif site == 'sim_controller':
            if rule.kind == 'controller_crash':
                self._crash_controller()
            elif rule.kind == 'controller_restart':
                self._restart_controller()
        elif site == 'sim_lb_crash':
            self._crash_lb()

    def _crash_lb(self) -> None:
        """Kill one live LB process (highest index first): its policy
        state — probe caches, sticky sessions — is gone; the
        deterministic client re-pick routes its keys to survivors. The
        last LB never dies (the scenario would just be an outage)."""
        if len(self._live_lb_idx) <= 1:
            self._log('lb_crash', 'skipped: last live lb')
            return
        idx = self._live_lb_idx.pop()
        self.lb_crashes += 1
        self._log('lb_crash',
                  f'lb={idx} live={len(self._live_lb_idx)}')

    def _apply_gray_fault(self, rule: faults_lib.FaultRule,
                          live) -> None:
        """Gray failures: the replica stays HTTP-alive while serving
        wrong bytes or nothing — detection belongs to the watchdog /
        sentinel / canary layers this storm drills."""
        if rule.kind == 'wedged_step':
            for r in live:
                if (not r.wedged and not r.byzantine
                        and r.gang_rank == 0):
                    r.wedged = True
                    self._log('wedge', f'url={r.url}')
                    break
        elif rule.kind == 'nan_logits':
            # Evict up to ``n`` in-flight requests with retryable
            # errors (the live path: device sentinel -> per-request
            # outbox failure -> LB resubmit); the rest of the batch
            # continues untouched.
            victims = [r for r in live
                       if r.inflight and not r.wedged]
            if not victims:
                return
            rep = max(victims, key=lambda r: len(r.inflight))
            jobs = [j for j in list(rep.inflight.values())
                    if not j.cancelled][:max(1, rule.n)]
            self._log('nan_evict', f'url={rep.url} n={len(jobs)}')
            now = self.loop.now
            for job in jobs:
                job.cancelled = True
                rep.inflight.pop(job.job_id, None)
                self.policies[job.lb_idx].post_execute(rep.url)
                self._inflight -= job.count
                self.migrated += job.count
                self._dispatch(job.count, job.tier,
                               migrated_from=rep.url, failed_at=now,
                               session=job.session,
                               trace_id=job.trace_id)
        elif rule.kind == 'byzantine_response':
            for r in live:
                if (not r.byzantine and not r.wedged
                        and r.gang_rank == 0):
                    r.byzantine = True
                    self._log('byzantine', f'url={r.url}')
                    break

    # ----------------------------------------------------------------- run
    def _outstanding(self) -> int:
        return self._inflight + sum(c for c, *_ in self._retry_q)

    def run(self) -> Dict[str, Any]:
        self.loop.spawn(self._controller_loop, self.controller,
                        self.env, name='controller')
        self.loop.schedule(0.0, self._lb_sync)
        self._start_arrivals()
        if self.injector is not None and any(
                r.site in SIM_FAULT_SITES
                for r in self.injector._rules):
            self.loop.schedule(self.storm_dt, self._storm_check)
        self.loop.run_until(self.trace.duration_s)
        # End-of-trace drain: no new arrivals; completions, retries,
        # drains and backfills keep running until outstanding work
        # clears (or the grace window expires — the remainder is LOST,
        # which recovery-covered scenarios assert to be zero).
        t_limit = self.loop.now + self.drain_grace_s
        self.loop.run_while(lambda: self._outstanding() > 0, t_limit)
        lost = self._outstanding()
        self._stop = True
        virtual_s = self.loop.now
        self.loop.shutdown()
        return self._report(lost, virtual_s)

    # -------------------------------------------------------------- report
    def _report(self, lost: int, virtual_s: float) -> Dict[str, Any]:
        slo = {}
        for tier in sorted(self.slo_total):
            total = self.slo_total[tier]
            met = self.slo_met.get(tier, 0)
            samples = self.ttft_samples.get(tier, [])
            slo[tier] = {
                'completed': total, 'met': met,
                'attainment': round(met / total, 4) if total else 1.0,
                'ttft_p50_s': round(
                    _weighted_percentile(samples, 0.5), 4),
                'ttft_p90_s': round(
                    _weighted_percentile(samples, 0.9), 4),
            }
        faults_fired: Dict[str, int] = {}
        if self.injector is not None:
            for rule in self.injector._rules:
                if rule.fired:
                    key = f'{rule.site}:{rule.kind}'
                    faults_fired[key] = (faults_fired.get(key, 0)
                                         + rule.fired)
        mgr = self.controller.replica_manager
        return {
            'seed': self.seed,
            'policy': self.policy_name,
            'trace': self.trace.name,
            'virtual_s': round(virtual_s, 3),
            'requests': {
                'arrived': self.arrived,
                'completed': self.completed,
                'shed': dict(self.sheds),
                'migrated': self.migrated,
                'lost': lost,
            },
            'slo': slo,
            'recovery_s': {
                'n': sum(w for _, w in self.recovery_samples),
                'p50': round(_weighted_percentile(
                    self.recovery_samples, 0.5), 3),
                'p90': round(_weighted_percentile(
                    self.recovery_samples, 0.9), 3),
            },
            'chip_seconds': round(self.chip_seconds, 1),
            'replicas': {
                'launched': self.world._launch_index,
                'peak_ready': self.peak_ready,
                'target_final': self.controller.autoscaler
                                .target_num_replicas,
                'tracked_final': len(mgr.replicas()),
                'quarantined': mgr.quarantined_count,
            },
            'controller': {
                'crashes': self.controller_crashes,
                'restarts': self.controller_restarts,
                'reconciled': dict(sorted(
                    self.reconcile_stats.items())),
            },
            'affinity': {
                'session_requests': self.session_requests,
                'warm_hits': self.warm_hits,
                'ttft_hit_rate': (
                    round(self.warm_hits / self.session_requests, 4)
                    if self.session_requests else 0.0),
                'recompute_tokens': self.recompute_tokens,
                'outcomes': dict(self.affinity_outcomes),
                'prefix_migrations': self.prefix_migrations,
            },
            'lbs': {
                'n': self.n_lbs,
                'live': len(self._live_lb_idx),
                'crashed': self.lb_crashes,
                'reroutes': self.lb_reroutes,
            },
            # The controller-side aggregation plane (round 19): what
            # ``GET /fleet/metrics`` would serve live — sources scraped
            # over /telemetry/summary on the probe path, SLO burn
            # rates/attainment evaluated on the virtual clock.
            'fleet': {
                'sources': self.controller.fleet.source_count(),
                'slo': self.controller.fleet.slo_status(),
            },
            'faults_fired': faults_fired,
            'events': self._n_events,
            'event_log_sha256': self._log_hash.hexdigest(),
            'event_log_truncated': self._log_truncated,
        }

    def event_log(self) -> str:
        return ''.join(self._log_lines)
