"""Fleet-scale discrete-event simulator for the serve control plane.

Drives the REAL, unmodified policy objects — the autoscalers +
forecaster, ``placement.role_for_new_replica``, the LB policies, and
the replica manager's launch/probe/drain/checkpoint/warmup/backfill
state machines (behind ``serve/control_env.py``'s simulator-or-live
seam) — against simulated replicas with service curves calibrated from
the repo's BENCH engine numbers, on a virtual clock: 100–1000 replicas
and millions of requests in seconds of wall time, deterministic to the
byte for a fixed seed.

Entry points: :func:`skypilot_tpu.serve.sim.scenarios.run_scenario`
(the ``skytpu sim`` CLI and the bench's ``sim`` block both call it)
and :class:`skypilot_tpu.serve.sim.fleet.FleetSimulator` for custom
harnesses. graftcheck GC117 bans every wall-clock read under this
package — the virtual clock is the only time axis.
"""
from skypilot_tpu.serve.sim.core import EventLoop, SimShutdown
from skypilot_tpu.serve.sim.replica import ServiceCurve, SimReplica
from skypilot_tpu.serve.sim.scenarios import (SCENARIOS, get_scenario,
                                              run_scenario)

__all__ = ['EventLoop', 'SimShutdown', 'ServiceCurve', 'SimReplica',
           'SCENARIOS', 'get_scenario', 'run_scenario']
