"""Deterministic discrete-event core: virtual clock, event heap, and
*logical tasks* that let real blocking-style control-plane code (the
replica manager's drain polls, launch flows, probe sweeps) run
unmodified on virtual time.

Two execution shapes share one time axis:

- **Callbacks** — pure event handlers (arrivals, completions, LB
  syncs, storm checks). Scheduled with :meth:`EventLoop.schedule`; run
  inline in the loop thread; MUST NOT sleep.
- **Logical tasks** — real functions containing ``env.sleep`` calls
  (the manager's ``_drain_then_down``, ``_launch_replica``, the
  controller tick loop). Spawned with :meth:`EventLoop.spawn`; each
  runs on its own OS thread, but the loop enforces strict
  one-runner-at-a-time token handoff: a task runs until it sleeps or
  finishes, the loop resumes only then, and a sleeping task wakes
  exactly at its virtual deadline in heap order. Execution is
  therefore fully serialized and **deterministic** — same seed, same
  schedule, byte-identical event sequence — while the manager's
  threading.Lock/RLock discipline keeps working untouched (locks are
  simply never contended).

Determinism contract: ties on the virtual timestamp break by schedule
order (a monotone sequence number); no wall-clock reads anywhere
(graftcheck GC117 gates the whole ``serve/sim/`` package); randomness
only ever comes from seeds the caller passes in.

The real-time ``timeout=`` arguments on the internal handoff waits are
deadlock insurance, not a time source: a task that blocks on something
the loop can never produce raises :class:`SimWedged` instead of
hanging the test suite.
"""
from __future__ import annotations

import heapq
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

# Real-seconds bound on one scheduler<->task handoff. Generous: a
# handoff is one context switch plus the task's pure-Python run slice
# (no I/O, no device work). Hitting it means a logical task wedged on
# something outside the loop — a bug, surfaced as SimWedged instead of
# a hung pytest.
_HANDOFF_TIMEOUT_S = 120.0


class SimShutdown(BaseException):
    """Raised inside a logical task when the loop shuts down while the
    task is parked (BaseException so ``except Exception`` retry loops
    in control-plane code can't swallow the unwind)."""


class SimWedged(RuntimeError):
    """A scheduler<->task handoff timed out in real time."""


class _Task:
    """One logical task: a real thread, token-stepped by the loop."""

    __slots__ = ('name', '_fn', '_args', '_go', '_yielded', 'finished',
                 'error', '_shutdown', '_thread')

    def __init__(self, name: str, fn: Callable[..., None],
                 args: Tuple[Any, ...]):
        self.name = name
        self._fn = fn
        self._args = args
        self._go = threading.Event()        # loop -> task: run
        self._yielded = threading.Event()   # task -> loop: parked/done
        self.finished = False
        self.error: Optional[BaseException] = None
        self._shutdown = False
        self._thread = threading.Thread(
            target=self._body, name=f'sim:{name}', daemon=True)
        self._thread.start()

    def _body(self) -> None:
        self._wait_go()
        try:
            self._fn(*self._args)
        except SimShutdown:
            pass
        except BaseException as e:  # pylint: disable=broad-except
            self.error = e
        self.finished = True
        self._yielded.set()

    def _wait_go(self) -> None:
        if not self._go.wait(timeout=_HANDOFF_TIMEOUT_S):
            # The loop abandoned us (test aborted mid-sim); unwind.
            raise SimShutdown()
        self._go.clear()
        if self._shutdown:
            raise SimShutdown()

    def park(self) -> None:
        """Called from the task thread: yield to the loop, then block
        until the loop hands the token back."""
        self._yielded.set()
        self._wait_go()

    def step(self) -> None:
        """Called from the loop thread: run the task until it parks or
        finishes."""
        self._yielded.clear()
        self._go.set()
        if not self._yielded.wait(timeout=_HANDOFF_TIMEOUT_S):
            raise SimWedged(f'logical task {self.name!r} did not yield '
                            f'within {_HANDOFF_TIMEOUT_S:.0f} real '
                            'seconds — it is blocked on something the '
                            'simulator can never produce')
        if self.error is not None:
            err, self.error = self.error, None
            raise err


class EventLoop:
    """The virtual clock + event heap. Single-owner: exactly one
    thread (the one calling :meth:`run_until`) drives it; logical
    tasks only touch it through :meth:`sleep`/:meth:`spawn`/
    :meth:`schedule` while they hold the run token, so no internal
    locking is needed and ordering is exactly heap order."""

    def __init__(self) -> None:
        self.now = 0.0
        self._seq = 0
        # (time, seq, ('call', fn, args) | ('wake', task))
        self._heap: List[Tuple[float, int, Tuple]] = []
        self._tasks_by_ident: Dict[int, _Task] = {}
        self._live_tasks: List[_Task] = []
        self._shutdown = False

    # ----------------------------------------------------------- schedule
    def _push(self, at: float, item: Tuple) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (max(at, self.now), self._seq, item))

    def schedule(self, delay: float, fn: Callable[..., None],
                 *args: Any) -> None:
        """Run ``fn(*args)`` as a callback at ``now + delay`` (a
        callback must not sleep — spawn a task for that)."""
        self._push(self.now + max(0.0, delay), ('call', fn, args))

    def schedule_at(self, at: float, fn: Callable[..., None],
                    *args: Any) -> None:
        self._push(at, ('call', fn, args))

    def spawn(self, fn: Callable[..., None], *args: Any,
              name: str = 'task') -> None:
        """Start a logical task at the current virtual time (it begins
        running when its start event pops, in schedule order)."""
        task = _Task(name, fn, args)
        self._tasks_by_ident[task._thread.ident] = task
        self._live_tasks.append(task)
        self._push(self.now, ('wake', task))

    # -------------------------------------------------------------- sleep
    def sleep(self, seconds: float) -> None:
        """Virtual sleep — legal only inside a logical task."""
        task = self._tasks_by_ident.get(threading.get_ident())
        if task is None:
            raise RuntimeError(
                'EventLoop.sleep called outside a logical task '
                '(callbacks must not sleep; use spawn for blocking '
                'flows)')
        self._push(self.now + max(0.0, seconds), ('wake', task))
        task.park()

    # ---------------------------------------------------------------- run
    def run_until(self, t_end: float) -> None:
        """Process events up to and including virtual time ``t_end``."""
        while self._heap:
            at, seq, item = self._heap[0]
            if at > t_end:
                break
            heapq.heappop(self._heap)
            self.now = max(self.now, at)
            if item[0] == 'call':
                _, fn, args = item
                fn(*args)
            else:
                task = item[1]
                if task.finished:
                    continue
                task.step()
        self.now = max(self.now, t_end)
        self._reap()

    def run_while(self, cond: Callable[[], bool],
                  t_limit: float) -> None:
        """Process events while ``cond()`` holds, up to ``t_limit``
        (the end-of-run drain: keep going until in-flight work clears
        or the grace window expires)."""
        while self._heap and cond():
            at, _, _ = self._heap[0]
            if at > t_limit:
                break
            self.run_until(at)

    def pending(self) -> int:
        return len(self._heap)

    def _reap(self) -> None:
        self._live_tasks = [t for t in self._live_tasks
                            if not t.finished]

    def shutdown(self) -> None:
        """Unwind every parked logical task (their threads exit via
        SimShutdown) — call when a run ends so abandoned drain/launch
        tasks don't linger for the handoff timeout."""
        self._shutdown = True
        for task in self._live_tasks:
            if task.finished:
                continue
            task._shutdown = True
            task._go.set()
            task._yielded.wait(timeout=5.0)
        self._reap()
