"""Load-balancing policies (reference
``sky/serve/load_balancing_policies.py``: ``RoundRobinPolicy`` ``:89``,
``LeastLoadPolicy`` ``:115``). Pure selection logic over the ready-replica
URL list the LB syncs from the controller — plus
:class:`QueueDepthPolicy`, which load-ranks replicas by the work-token
estimate their SLO scheduler publishes at ``/metrics?format=json``."""
from __future__ import annotations

import json
import threading
import urllib.request
from typing import Callable, Dict, List, Optional, Set, Tuple

from skypilot_tpu import tpu_logging
from skypilot_tpu.telemetry import clock

logger = tpu_logging.init_logger(__name__)


class LoadBalancingPolicy:

    def __init__(self) -> None:
        self.ready_replicas: List[str] = []
        self._lock = threading.Lock()
        # Simulator-or-live transport seam (serve/sim/): probing
        # policies fetch replica /metrics JSON through ``_fetch_json``
        # and age their probe caches on ``_monotonic``. The defaults
        # are the live urllib/clock paths; ``configure_transport``
        # swaps both so the UNMODIFIED selection/scoring logic runs
        # against simulated replicas on a virtual clock.
        self._fetch_json: Optional[Callable[[str], Dict]] = None
        self._monotonic: Callable[[], float] = clock.monotonic

    def configure_transport(
            self, fetch_json: Optional[Callable[[str], Dict]] = None,
            monotonic: Optional[Callable[[], float]] = None) -> None:
        """Inject the probe transport and clock (fleet simulator /
        tests). ``fetch_json(url)`` returns the parsed JSON a live
        probe would (and raises on failure); ``monotonic`` must never
        step backwards."""
        if fetch_json is not None:
            self._fetch_json = fetch_json
        if monotonic is not None:
            self._monotonic = monotonic

    def set_ready_replicas(self, urls: List[str]) -> None:
        with self._lock:
            if set(urls) != set(self.ready_replicas):
                self._on_replicas_changed(urls)
            self.ready_replicas = list(urls)

    def _on_replicas_changed(self, urls: List[str]) -> None:
        del urls

    def select_replica(self,
                       exclude: Optional[Set[str]] = None
                       ) -> Optional[str]:
        """Pick a ready replica, skipping ``exclude`` (URLs that already
        failed this request — the LB's transparent retry)."""
        raise NotImplementedError

    def _candidates_locked(self,
                           exclude: Optional[Set[str]]) -> List[str]:
        """Routable candidates (callers hold ``self._lock``): ready
        replicas minus ``exclude`` minus gang follower URLs — a gang's
        only endpoint is its rank 0."""
        followers = self._followers_locked()
        return [u for u in self.ready_replicas
                if u not in followers
                and (not exclude or u not in exclude)]

    def pre_execute(self, url: str) -> None:
        """Called when a request is dispatched to ``url``."""
        del url

    def post_execute(self, url: str) -> None:
        """Called when the request to ``url`` completes."""
        del url

    def replica_meshes(self) -> Dict[str, Dict]:
        """url -> live-probed mesh shape, for policies that probe the
        replicas' /metrics JSON (queue_depth). Empty for the rest —
        the LB's replica view then falls back to the controller plan."""
        return {}

    def set_replica_roles(self, roles: Optional[Dict[str, str]]) -> None:
        """Controller-planned replica roles (url -> prefill/decode/
        colocated), refreshed on every LB sync. Policies that route by
        phase use them as the fallback when live probes are cold."""
        del roles

    def set_replica_gangs(self, gangs: Optional[Dict[str, Dict]]
                          ) -> None:
        """Gang health blocks (rank0 url -> {gang_id, world,
        follower_urls, statuses}), refreshed on every LB sync. A gang
        presents exactly ONE routable endpoint (rank 0): follower
        URLs must be excluded from selection and probe sweeps — but
        stay visible in health accounting (:meth:`gang_view`)."""
        with self._lock:
            gangs = gangs or {}
            self._gangs = dict(gangs)
            self._follower_urls = {
                u for g in gangs.values()
                for u in (g.get('follower_urls') or []) if u}

    def gang_view(self) -> Dict[str, Dict]:
        """The last-synced gang blocks (health accounting for ranks
        that have no routable endpoint of their own)."""
        with self._lock:
            return dict(getattr(self, '_gangs', {}) or {})

    def _followers_locked(self) -> set:
        """Follower URLs to exclude (callers hold ``self._lock``)."""
        return getattr(self, '_follower_urls', set())

    def handoff_target(self, exclude: Optional[Set[str]] = None
                       ) -> Optional[str]:
        """The decode worker a prefill replica should stream finished
        KV to (disaggregated serving) — None for phase-unaware
        policies (the prefill replica then decodes locally or uses its
        static peer list)."""
        del exclude
        return None


class RoundRobinPolicy(LoadBalancingPolicy):

    def __init__(self) -> None:
        super().__init__()
        self._index = 0

    def _on_replicas_changed(self, urls: List[str]) -> None:
        # Hook invoked by set_ready_replicas WITH self._lock held; the
        # static checker cannot see the cross-method lock context.
        self._index = 0     # graftcheck: disable=GC101

    def select_replica(self,
                       exclude: Optional[Set[str]] = None
                       ) -> Optional[str]:
        with self._lock:
            candidates = self._candidates_locked(exclude)
            if not candidates:
                return None
            url = candidates[self._index % len(candidates)]
            self._index += 1
            return url


class LeastLoadPolicy(LoadBalancingPolicy):
    """Route to the replica with the fewest in-flight requests."""

    def __init__(self) -> None:
        super().__init__()
        self._inflight: Dict[str, int] = {}

    def select_replica(self,
                       exclude: Optional[Set[str]] = None
                       ) -> Optional[str]:
        with self._lock:
            candidates = self._candidates_locked(exclude)
            if not candidates:
                return None
            return min(candidates,
                       key=lambda u: self._inflight.get(u, 0))

    def pre_execute(self, url: str) -> None:
        with self._lock:
            self._inflight[url] = self._inflight.get(url, 0) + 1

    def post_execute(self, url: str) -> None:
        with self._lock:
            self._inflight[url] = max(0, self._inflight.get(url, 0) - 1)


class QueueDepthPolicy(LoadBalancingPolicy):
    """Route to the replica with the least estimated work AHEAD of a
    new request, read from the replica model server's
    ``/metrics?format=json`` ``queue_tokens_total`` gauge (the SLO
    scheduler's queued work tokens + the engine's in-flight prefill
    tails and decode budgets). Token-denominated load ranking sees a
    replica digesting three 4k-token prompts as busier than one
    serving thirty 20-token chats — the distinction request-count
    policies miss.

    Probes run OUTSIDE the policy lock with a short timeout and are
    cached for :attr:`PROBE_TTL_S`; between probes the score advances
    by :attr:`EST_TOKENS_PER_REQUEST` per in-flight dispatch so a
    burst landing within one TTL window still spreads. A replica whose
    probe fails scores by dispatch count alone (graceful least-load
    degradation; the LB's transparent retry covers replicas that are
    actually dead)."""

    PROBE_TTL_S = 1.0
    PROBE_TIMEOUT_S = 0.5
    # Work-token haircut per in-flight dispatch between probes (about
    # one anchor-shaped request: ~220 prompt + ~190 decode tokens).
    EST_TOKENS_PER_REQUEST = 400

    def __init__(self) -> None:
        super().__init__()
        self._inflight: Dict[str, int] = {}
        # url -> (monotonic expiry, queue_tokens_total or None=failed)
        self._cache: Dict[str, Tuple[float, Optional[int]]] = {}
        # url -> last-probed mesh shape block (the same /metrics JSON
        # carries it — the LB's replica view reads this for free).
        self._mesh: Dict[str, Dict] = {}
        # url -> last-probed disagg view ({'role', 'kv_free'}) — the
        # phase-aware subclass routes and picks handoff targets from
        # this; the base policy just keeps it fresh for free.
        self._disagg: Dict[str, Dict] = {}

    def _probe(self, url: str) -> Tuple[Optional[int], Optional[Dict]]:
        """One replica's /metrics JSON: (queue_tokens_total, payload).
        ``None`` tokens = probe failed (the replica scores by dispatch
        count alone)."""
        try:
            if self._fetch_json is not None:
                payload = self._fetch_json(f'{url}/metrics?format=json')
            else:
                with urllib.request.urlopen(
                        f'{url}/metrics?format=json',
                        timeout=self.PROBE_TIMEOUT_S) as resp:
                    payload = json.loads(resp.read())
            return int(payload['queue_tokens_total']), payload
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'queue-depth probe failed for {url}: '
                         f'{type(e).__name__}: {e}')
            return None, None

    def _refresh(self, candidates) -> None:
        """Refresh stale probe caches for ``candidates``. Probes run
        with the lock RELEASED: a slow replica must not serialize every
        concurrent select behind its timeout. Gang follower URLs are
        never probed — a gang's one endpoint is rank 0; sweeping every
        rank would double-count the gang's load and hammer processes
        that serve no HTTP at all."""
        with self._lock:
            now = self._monotonic()
            followers = self._followers_locked()
            stale = [u for u in candidates
                     if u not in followers
                     and self._cache.get(u, (0.0, None))[0] <= now]
        fresh = {u: self._probe(u) for u in stale}
        with self._lock:
            expiry = self._monotonic() + self.PROBE_TTL_S
            for u, (tokens, payload) in fresh.items():
                self._cache[u] = (expiry, tokens)
                if payload is not None:
                    if payload.get('mesh') is not None:
                        self._mesh[u] = payload['mesh']
                    disagg = payload.get('disagg') or {}
                    self._disagg[u] = {
                        'role': disagg.get('role'),
                        'kv_free': int(payload.get(
                            'kv_pool_tokens_free', 0)),
                    }

    def _score_locked(self, u: str) -> int:
        tokens = self._cache.get(u, (0.0, None))[1]
        return ((tokens if tokens is not None else 0)
                + self.EST_TOKENS_PER_REQUEST
                * self._inflight.get(u, 0))

    def select_replica(self,
                       exclude: Optional[Set[str]] = None
                       ) -> Optional[str]:
        with self._lock:
            candidates = self._candidates_locked(exclude)
        if not candidates:
            return None
        self._refresh(candidates)
        with self._lock:
            return min(candidates, key=self._score_locked)

    def pre_execute(self, url: str) -> None:
        with self._lock:
            self._inflight[url] = self._inflight.get(url, 0) + 1

    def post_execute(self, url: str) -> None:
        with self._lock:
            self._inflight[url] = max(0, self._inflight.get(url, 0) - 1)

    def replica_meshes(self) -> Dict[str, Dict]:
        with self._lock:
            return dict(self._mesh)


class PhaseAwarePolicy(QueueDepthPolicy):
    """Disaggregation-aware routing (ThunderServe-style): new requests
    are PREFILL-BOUND — they go to the prefill pool ranked by queued
    work tokens (the queue-depth score), falling back to colocated
    replicas when the prefill pool is empty, and to anything ready as
    the last resort (a decode-only fleet must still answer). The
    handoff target for a finished prefill is the decode worker with
    the most free KV-pool tokens (``kv_pool_tokens_free`` from the
    same ``/metrics?format=json`` probes, haircut by in-flight
    dispatches) — the LB stamps it on the proxied request as
    ``X-Handoff-Target``.

    Roles come from the live probes (the ``disagg.role`` block every
    model server publishes); the controller's planned roles — shipped
    on every LB sync — are the fallback for replicas whose probe is
    cold or failing."""

    def __init__(self) -> None:
        super().__init__()
        self._planned_roles: Dict[str, str] = {}

    def set_replica_roles(self, roles: Optional[Dict[str, str]]) -> None:
        with self._lock:
            self._planned_roles = dict(roles or {})

    def _role_locked(self, u: str) -> Optional[str]:
        probed = self._disagg.get(u, {}).get('role')
        return probed or self._planned_roles.get(u)

    def select_replica(self,
                       exclude: Optional[Set[str]] = None
                       ) -> Optional[str]:
        with self._lock:
            candidates = self._candidates_locked(exclude)
        if not candidates:
            return None
        self._refresh(candidates)
        with self._lock:
            prefill = [u for u in candidates
                       if self._role_locked(u) == 'prefill']
            colocated = [u for u in candidates
                         if self._role_locked(u) in (None, 'colocated')]
            pool = prefill or colocated or candidates
            return min(pool, key=self._score_locked)

    def handoff_target(self, exclude: Optional[Set[str]] = None
                       ) -> Optional[str]:
        with self._lock:
            candidates = self._candidates_locked(exclude)
        if not candidates:
            return None
        self._refresh(candidates)
        with self._lock:
            decode = [u for u in candidates
                      if self._role_locked(u) == 'decode']
            if not decode:
                return None

            def headroom(u: str) -> int:
                free = self._disagg.get(u, {}).get('kv_free', 0)
                return (int(free) - self.EST_TOKENS_PER_REQUEST
                        * self._inflight.get(u, 0))

            return max(decode, key=headroom)


POLICIES = {
    'round_robin': RoundRobinPolicy,
    'least_load': LeastLoadPolicy,
    'queue_depth': QueueDepthPolicy,
    'phase_aware': PhaseAwarePolicy,
}


def make_policy(name: str) -> LoadBalancingPolicy:
    if name not in POLICIES:
        raise ValueError(f'Unknown load balancing policy: {name!r}; '
                         f'choose from {sorted(POLICIES)}')
    return POLICIES[name]()
