"""Load-balancing policies (reference
``sky/serve/load_balancing_policies.py``: ``RoundRobinPolicy`` ``:89``,
``LeastLoadPolicy`` ``:115``). Pure selection logic over the ready-replica
URL list the LB syncs from the controller — plus
:class:`QueueDepthPolicy`, which load-ranks replicas by the work-token
estimate their SLO scheduler publishes at ``/metrics?format=json``, and
:class:`PrefixAffinityPolicy`, which routes multi-turn sessions to the
replica that already holds their KV prefix (longest match against the
replicas' hot-prefix digests, load-aware tie-breaking, proactive SKPF
migration when affinity and load disagree too far)."""
from __future__ import annotations

import collections
import hashlib
import json
import os
import threading
import urllib.request
from typing import (Any, Callable, Dict, List, Optional, Set, Tuple)

import numpy as np

from skypilot_tpu import tpu_logging
from skypilot_tpu.telemetry import clock

logger = tpu_logging.init_logger(__name__)

# Per-URL maps are bounded by the fleet in practice; the cap is the
# loud backstop against a controller bug feeding unbounded URL churn.
_FLEET_CAP = 4096

_MISSING = object()


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, '') or default)
    except ValueError:
        return default


def _seeded_jitter(seed: str, frac: float = 0.2) -> float:
    """Deterministic per-LB fraction in [-frac, +frac]: sha1 of the
    LB's identity, no RNG — N LBs probing the same fleet spread their
    probe-cache expiries instead of stampeding in lockstep, and the
    same identity always yields the same offset (sim replays stay
    byte-identical)."""
    if not seed:
        return 0.0
    h = int.from_bytes(hashlib.sha1(seed.encode()).digest()[:4], 'big')
    return (h / 0xFFFFFFFF * 2.0 - 1.0) * frac


class BoundedStore:
    """The ONE sanctioned mutable map on LB hot paths (graftcheck
    GC122): TTL aging plus an LRU cap, evictions counted loudly. Every
    per-request / per-replica table the policies grow at runtime goes
    through this helper — a raw ``self._x[k] = v`` in this module is a
    slow memory leak on a box that sees millions of sessions, and the
    gate hard-fails it. NOT internally locked: callers hold their
    policy lock, exactly like the plain dicts this replaces."""

    def __init__(self, cap: int, ttl_s: Optional[float] = None,
                 monotonic: Optional[Callable[[], float]] = None,
                 name: str = '') -> None:
        self._cap = max(1, int(cap))
        self._ttl = ttl_s
        self._mono = monotonic or clock.monotonic
        self._name = name or 'store'
        # key -> (expiry-or-None, value); OrderedDict recency = LRU.
        self._data: 'collections.OrderedDict[Any, Tuple[Optional[float], Any]]' = (
            collections.OrderedDict())
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, key: Any) -> bool:
        return self.get(key, _MISSING) is not _MISSING

    def get(self, key: Any, default: Any = None) -> Any:
        rec = self._data.get(key)
        if rec is None:
            return default
        expiry, value = rec
        if expiry is not None and expiry <= self._mono():
            del self._data[key]
            return default
        self._data.move_to_end(key)
        return value

    def put(self, key: Any, value: Any) -> None:
        if key in self._data:
            del self._data[key]
        elif len(self._data) >= self._cap:
            evicted, _ = self._data.popitem(last=False)
            self.evictions += 1
            logger.debug(
                f'BoundedStore[{self._name}]: cap {self._cap} hit, '
                f'LRU-evicted {evicted!r} '
                f'(eviction #{self.evictions})')
        expiry = (self._mono() + self._ttl
                  if self._ttl is not None else None)
        self._data[key] = (expiry, value)

    def pop(self, key: Any, default: Any = None) -> Any:
        value = self.get(key, _MISSING)
        if value is _MISSING:
            return default
        del self._data[key]
        return value

    def incr(self, key: Any, delta: int,
             floor: Optional[int] = None) -> int:
        value = int(self.get(key, 0)) + delta
        if floor is not None:
            value = max(floor, value)
        self.put(key, value)
        return value

    def items(self) -> List[Tuple[Any, Any]]:
        """Live (key, value) snapshot; expired entries pruned."""
        now = self._mono()
        out = []
        for key, (expiry, value) in list(self._data.items()):
            if expiry is not None and expiry <= now:
                del self._data[key]
            else:
                out.append((key, value))
        return out

    def clear(self) -> None:
        self._data.clear()


class LoadBalancingPolicy:

    def __init__(self) -> None:
        self.ready_replicas: List[str] = []
        self._lock = threading.Lock()
        # Simulator-or-live transport seam (serve/sim/): probing
        # policies fetch replica /metrics JSON through ``_fetch_json``
        # and age their probe caches on ``_monotonic``. The defaults
        # are the live urllib/clock paths; ``configure_transport``
        # swaps both so the UNMODIFIED selection/scoring logic runs
        # against simulated replicas on a virtual clock.
        self._fetch_json: Optional[Callable[[str], Dict]] = None
        self._monotonic: Callable[[], float] = clock.monotonic

    def configure_transport(
            self, fetch_json: Optional[Callable[[str], Dict]] = None,
            monotonic: Optional[Callable[[], float]] = None) -> None:
        """Inject the probe transport and clock (fleet simulator /
        tests). ``fetch_json(url)`` returns the parsed JSON a live
        probe would (and raises on failure); ``monotonic`` must never
        step backwards."""
        if fetch_json is not None:
            self._fetch_json = fetch_json
        if monotonic is not None:
            self._monotonic = monotonic

    def set_ready_replicas(self, urls: List[str]) -> None:
        with self._lock:
            if set(urls) != set(self.ready_replicas):
                self._on_replicas_changed(urls)
            self.ready_replicas = list(urls)

    def _on_replicas_changed(self, urls: List[str]) -> None:
        del urls

    def select_replica(self,
                       exclude: Optional[Set[str]] = None,
                       context: Optional[Dict[str, Any]] = None
                       ) -> Optional[str]:
        """Pick a ready replica, skipping ``exclude`` (URLs that already
        failed this request — the LB's transparent retry). ``context``
        is the optional request identity affinity policies route by
        (``{'tokens': [...], 'request_key': str}``); load-only
        policies ignore it."""
        raise NotImplementedError

    def _candidates_locked(self,
                           exclude: Optional[Set[str]]) -> List[str]:
        """Routable candidates (callers hold ``self._lock``): ready
        replicas minus ``exclude`` minus gang follower URLs — a gang's
        only endpoint is its rank 0."""
        followers = self._followers_locked()
        return [u for u in self.ready_replicas
                if u not in followers
                and (not exclude or u not in exclude)]

    def pre_execute(self, url: str) -> None:
        """Called when a request is dispatched to ``url``."""
        del url

    def post_execute(self, url: str) -> None:
        """Called when the request to ``url`` completes."""
        del url

    def replica_meshes(self) -> Dict[str, Dict]:
        """url -> live-probed mesh shape, for policies that probe the
        replicas' /metrics JSON (queue_depth). Empty for the rest —
        the LB's replica view then falls back to the controller plan."""
        return {}

    def set_replica_roles(self, roles: Optional[Dict[str, str]]) -> None:
        """Controller-planned replica roles (url -> prefill/decode/
        colocated), refreshed on every LB sync. Policies that route by
        phase use them as the fallback when live probes are cold."""
        del roles

    def set_replica_gangs(self, gangs: Optional[Dict[str, Dict]]
                          ) -> None:
        """Gang health blocks (rank0 url -> {gang_id, world,
        follower_urls, statuses}), refreshed on every LB sync. A gang
        presents exactly ONE routable endpoint (rank 0): follower
        URLs must be excluded from selection and probe sweeps — but
        stay visible in health accounting (:meth:`gang_view`)."""
        with self._lock:
            gangs = gangs or {}
            self._gangs = dict(gangs)
            self._follower_urls = {
                u for g in gangs.values()
                for u in (g.get('follower_urls') or []) if u}

    def gang_view(self) -> Dict[str, Dict]:
        """The last-synced gang blocks (health accounting for ranks
        that have no routable endpoint of their own)."""
        with self._lock:
            return dict(getattr(self, '_gangs', {}) or {})

    def _followers_locked(self) -> set:
        """Follower URLs to exclude (callers hold ``self._lock``)."""
        return getattr(self, '_follower_urls', set())

    def handoff_target(self, exclude: Optional[Set[str]] = None
                       ) -> Optional[str]:
        """The decode worker a prefill replica should stream finished
        KV to (disaggregated serving) — None for phase-unaware
        policies (the prefill replica then decodes locally or uses its
        static peer list)."""
        del exclude
        return None


class RoundRobinPolicy(LoadBalancingPolicy):

    def __init__(self) -> None:
        super().__init__()
        self._index = 0

    def _on_replicas_changed(self, urls: List[str]) -> None:
        # Hook invoked by set_ready_replicas WITH self._lock held; the
        # static checker cannot see the cross-method lock context.
        self._index = 0     # graftcheck: disable=GC101

    def select_replica(self,
                       exclude: Optional[Set[str]] = None,
                       context: Optional[Dict[str, Any]] = None
                       ) -> Optional[str]:
        del context
        with self._lock:
            candidates = self._candidates_locked(exclude)
            if not candidates:
                return None
            url = candidates[self._index % len(candidates)]
            self._index += 1
            return url


class LeastLoadPolicy(LoadBalancingPolicy):
    """Route to the replica with the fewest in-flight requests."""

    def __init__(self) -> None:
        super().__init__()
        self._inflight = BoundedStore(_FLEET_CAP, name='inflight')

    def select_replica(self,
                       exclude: Optional[Set[str]] = None,
                       context: Optional[Dict[str, Any]] = None
                       ) -> Optional[str]:
        del context
        with self._lock:
            candidates = self._candidates_locked(exclude)
            if not candidates:
                return None
            return min(candidates,
                       key=lambda u: self._inflight.get(u, 0))

    def pre_execute(self, url: str) -> None:
        with self._lock:
            self._inflight.incr(url, 1)

    def post_execute(self, url: str) -> None:
        with self._lock:
            self._inflight.incr(url, -1, floor=0)


class QueueDepthPolicy(LoadBalancingPolicy):
    """Route to the replica with the least estimated work AHEAD of a
    new request, read from the replica model server's
    ``/metrics?format=json`` ``queue_tokens_total`` gauge (the SLO
    scheduler's queued work tokens + the engine's in-flight prefill
    tails and decode budgets). Token-denominated load ranking sees a
    replica digesting three 4k-token prompts as busier than one
    serving thirty 20-token chats — the distinction request-count
    policies miss.

    Probes run OUTSIDE the policy lock with a short timeout and are
    cached for :attr:`probe_ttl_s` — the ``SKYTPU_LB_PROBE_TTL_S``
    knob (default :attr:`PROBE_TTL_S`), jittered deterministically
    per LB identity so a horizontal LB tier doesn't probe the fleet
    in lockstep. Between probes the score advances by
    :attr:`EST_TOKENS_PER_REQUEST` per in-flight dispatch so a burst
    landing within one TTL window still spreads. A replica whose
    probe fails scores by dispatch count alone (graceful least-load
    degradation; the LB's transparent retry covers replicas that are
    actually dead)."""

    PROBE_TTL_S = 1.0
    PROBE_TIMEOUT_S = 0.5
    # Work-token haircut per in-flight dispatch between probes (about
    # one anchor-shaped request: ~220 prompt + ~190 decode tokens).
    EST_TOKENS_PER_REQUEST = 400

    def __init__(self) -> None:
        super().__init__()
        self._inflight = BoundedStore(_FLEET_CAP, name='inflight')
        # url -> (monotonic expiry, queue_tokens_total or None=failed).
        # Expiry marks STALENESS (reprobe due), not eviction — a stale
        # score still ranks until its refresh lands.
        self._cache = BoundedStore(_FLEET_CAP, name='probe_cache')
        # url -> last-probed mesh shape block (the same /metrics JSON
        # carries it — the LB's replica view reads this for free).
        self._mesh = BoundedStore(_FLEET_CAP, name='mesh')
        # url -> last-probed disagg view ({'role', 'kv_free'}) — the
        # phase-aware subclass routes and picks handoff targets from
        # this; the base policy just keeps it fresh for free.
        self._disagg = BoundedStore(_FLEET_CAP, name='disagg')
        self._base_probe_ttl_s = _env_float('SKYTPU_LB_PROBE_TTL_S',
                                            self.PROBE_TTL_S)
        self.probe_ttl_s = self._base_probe_ttl_s
        self.set_probe_identity(os.environ.get('SKYTPU_LB_ID', ''))

    def set_probe_identity(self, lb_id: str) -> None:
        """Derive this LB's jittered probe TTL from its identity
        (``SKYTPU_LB_ID`` env by default; the multi-LB runner and the
        simulator set it explicitly). Deterministic — the same id
        always yields the same TTL."""
        self.probe_ttl_s = max(
            0.05,
            self._base_probe_ttl_s * (1.0 + _seeded_jitter(lb_id)))

    def _probe(self, url: str) -> Tuple[Optional[int], Optional[Dict]]:
        """One replica's /metrics JSON: (queue_tokens_total, payload).
        ``None`` tokens = probe failed (the replica scores by dispatch
        count alone)."""
        try:
            if self._fetch_json is not None:
                payload = self._fetch_json(f'{url}/metrics?format=json')
            else:
                with urllib.request.urlopen(
                        f'{url}/metrics?format=json',
                        timeout=self.PROBE_TIMEOUT_S) as resp:
                    payload = json.loads(resp.read())
            return int(payload['queue_tokens_total']), payload
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'queue-depth probe failed for {url}: '
                         f'{type(e).__name__}: {e}')
            return None, None

    def _note_payload_locked(self, url: str, payload: Dict) -> None:
        """Stash the non-score blocks a fresh probe carried (callers
        hold ``self._lock``). Subclasses extend this to harvest their
        own blocks from the SAME probe — one scrape feeds every
        policy layer."""
        if payload.get('mesh') is not None:
            self._mesh.put(url, payload['mesh'])
        disagg = payload.get('disagg') or {}
        self._disagg.put(url, {
            'role': disagg.get('role'),
            'kv_free': int(payload.get('kv_pool_tokens_free', 0)),
        })

    def _refresh(self, candidates) -> None:
        """Refresh stale probe caches for ``candidates``. Probes run
        with the lock RELEASED: a slow replica must not serialize every
        concurrent select behind its timeout. Gang follower URLs are
        never probed — a gang's one endpoint is rank 0; sweeping every
        rank would double-count the gang's load and hammer processes
        that serve no HTTP at all."""
        with self._lock:
            now = self._monotonic()
            followers = self._followers_locked()
            stale = [u for u in candidates
                     if u not in followers
                     and self._cache.get(u, (0.0, None))[0] <= now]
        fresh = {u: self._probe(u) for u in stale}
        with self._lock:
            expiry = self._monotonic() + self.probe_ttl_s
            for u, (tokens, payload) in fresh.items():
                self._cache.put(u, (expiry, tokens))
                if payload is not None:
                    self._note_payload_locked(u, payload)

    def _score_locked(self, u: str) -> int:
        tokens = self._cache.get(u, (0.0, None))[1]
        return ((tokens if tokens is not None else 0)
                + self.EST_TOKENS_PER_REQUEST
                * self._inflight.get(u, 0))

    def select_replica(self,
                       exclude: Optional[Set[str]] = None,
                       context: Optional[Dict[str, Any]] = None
                       ) -> Optional[str]:
        del context
        with self._lock:
            candidates = self._candidates_locked(exclude)
        if not candidates:
            return None
        self._refresh(candidates)
        with self._lock:
            return min(candidates, key=self._score_locked)

    def pre_execute(self, url: str) -> None:
        with self._lock:
            self._inflight.incr(url, 1)

    def post_execute(self, url: str) -> None:
        with self._lock:
            self._inflight.incr(url, -1, floor=0)

    def replica_meshes(self) -> Dict[str, Dict]:
        with self._lock:
            return dict(self._mesh.items())


class PhaseAwarePolicy(QueueDepthPolicy):
    """Disaggregation-aware routing (ThunderServe-style): new requests
    are PREFILL-BOUND — they go to the prefill pool ranked by queued
    work tokens (the queue-depth score), falling back to colocated
    replicas when the prefill pool is empty, and to anything ready as
    the last resort (a decode-only fleet must still answer). The
    handoff target for a finished prefill is the decode worker with
    the most free KV-pool tokens (``kv_pool_tokens_free`` from the
    same ``/metrics?format=json`` probes, haircut by in-flight
    dispatches) — the LB stamps it on the proxied request as
    ``X-Handoff-Target``.

    Roles come from the live probes (the ``disagg.role`` block every
    model server publishes); the controller's planned roles — shipped
    on every LB sync — are the fallback for replicas whose probe is
    cold or failing."""

    def __init__(self) -> None:
        super().__init__()
        self._planned_roles: Dict[str, str] = {}

    def set_replica_roles(self, roles: Optional[Dict[str, str]]) -> None:
        with self._lock:
            self._planned_roles = dict(roles or {})

    def _role_locked(self, u: str) -> Optional[str]:
        probed = self._disagg.get(u, {}).get('role')
        return probed or self._planned_roles.get(u)

    def select_replica(self,
                       exclude: Optional[Set[str]] = None,
                       context: Optional[Dict[str, Any]] = None
                       ) -> Optional[str]:
        del context
        with self._lock:
            candidates = self._candidates_locked(exclude)
        if not candidates:
            return None
        self._refresh(candidates)
        with self._lock:
            prefill = [u for u in candidates
                       if self._role_locked(u) == 'prefill']
            colocated = [u for u in candidates
                         if self._role_locked(u) in (None, 'colocated')]
            pool = prefill or colocated or candidates
            return min(pool, key=self._score_locked)

    def handoff_target(self, exclude: Optional[Set[str]] = None
                       ) -> Optional[str]:
        with self._lock:
            candidates = self._candidates_locked(exclude)
        if not candidates:
            return None
        self._refresh(candidates)
        with self._lock:
            decode = [u for u in candidates
                      if self._role_locked(u) == 'decode']
            if not decode:
                return None

            def headroom(u: str) -> int:
                free = self._disagg.get(u, {}).get('kv_free', 0)
                return (int(free) - self.EST_TOKENS_PER_REQUEST
                        * self._inflight.get(u, 0))

            return max(decode, key=headroom)


class PrefixAffinityPolicy(QueueDepthPolicy):
    """Cache-locality routing for multi-turn sessions: every replica's
    ``/metrics?format=json`` probe now carries its hot-prefix digest
    (``prefix_digest``: the engine heat tracker's hottest chains as
    (sha1-hash, token-length) pairs at the page grid). This policy
    hashes each request's prompt at the SAME page grid and routes to
    the replica holding the LONGEST matching chain — the follow-up
    turn of a 30k-token session lands where its 30k KV rows already
    sit instead of recomputing them wherever the queue happens to be
    shortest.

    Load still matters three ways: ties between equally-matched
    replicas break on the queue-depth score; a request with no match
    anywhere routes purely by load; and when the affinity winner is
    overloaded past ``migrate_threshold_tokens`` relative to the load
    winner, the request routes to the LOAD winner and the prefix is
    *proactively migrated* — the configured migration executor ships
    the chain's CRC-checked SKPF blob from the affinity replica's
    ``/kv/prefix/export`` to the target's ``/kv/warmup``, so the
    prefix is warm there without recomputation.

    Session stickiness pins on ``request_key``: a key that routed once
    keeps routing to the same replica while it stays ready (bounded
    TTL+LRU table — stickiness is a hint, never a leak). Every map
    here is a :class:`BoundedStore`; graftcheck GC122 gates that."""

    # Longest prompt prefix the LB hashes, in pages: bounds per-select
    # CPU at ~64 sha1 updates regardless of prompt length.
    MAX_MATCH_PAGES = 64
    # Digest entries outlive their probe by this factor — a replica
    # whose probe is briefly failing keeps its affinity standing.
    DIGEST_TTL_FACTOR = 10.0

    def __init__(self) -> None:
        super().__init__()
        mono = lambda: self._monotonic()  # noqa: E731 — late-bound:
        # configure_transport swaps _monotonic after construction.
        self._digests = BoundedStore(
            _FLEET_CAP,
            ttl_s=max(self.probe_ttl_s * self.DIGEST_TTL_FACTOR, 10.0),
            monotonic=mono, name='prefix_digests')
        self._sessions = BoundedStore(
            int(_env_float('SKYTPU_LB_SESSION_CAP', 4096)),
            ttl_s=_env_float('SKYTPU_LB_SESSION_TTL_S', 600.0),
            monotonic=mono, name='sessions')
        self.migrate_threshold_tokens = int(_env_float(
            'SKYTPU_LB_MIGRATE_THRESHOLD', 1600))
        # (src_url, dst_url, chain_hash, n_tokens) -> bool. Installed
        # by the LB (live HTTP SKPF ship) or the simulator; None =
        # never migrate, just eat the recompute.
        self._migrate: Optional[
            Callable[[str, str, str, int], bool]] = None
        # (outcome, recompute_tokens) observer — the LB binds its
        # affinity counters here; the simulator its report accounting.
        self._on_affinity: Optional[Callable[[str, int], None]] = None

    def configure_migration(
            self, migrate: Optional[Callable[[str, str, str, int],
                                             bool]]) -> None:
        self._migrate = migrate

    def configure_affinity_observer(
            self, fn: Optional[Callable[[str, int], None]]) -> None:
        self._on_affinity = fn

    def _note_payload_locked(self, url: str, payload: Dict) -> None:
        super()._note_payload_locked(url, payload)
        digest = payload.get('prefix_digest') or {}
        try:
            page = int(digest.get('page') or 0)
        except (TypeError, ValueError):
            page = 0
        if page <= 0:
            return
        hashes: Dict[str, int] = {}
        for entry in (digest.get('entries') or []):
            try:
                hashes[str(entry['hash'])] = int(entry['len'])
            except (KeyError, TypeError, ValueError):
                continue
        self._digests.put(url, {'page': page, 'hashes': hashes})

    def _page_grid_hashes(self, tokens: List[int],
                          page: int) -> Dict[str, int]:
        """hash-hex -> covered-token-length for every page-grid prefix
        of ``tokens`` — the engine's exact recipe (sha1 over int32
        bytes of ``tokens[:k*page]``), computed incrementally: one
        sha1 update per page, not one pass per prefix."""
        full = min((len(tokens) - 1) // page, self.MAX_MATCH_PAGES)
        out: Dict[str, int] = {}
        h = hashlib.sha1()
        for k in range(1, full + 1):
            h.update(np.asarray(tokens[(k - 1) * page:k * page],
                                np.int32).tobytes())
            out[h.hexdigest()] = k * page
        return out

    def select_replica(self,
                       exclude: Optional[Set[str]] = None,
                       context: Optional[Dict[str, Any]] = None
                       ) -> Optional[str]:
        with self._lock:
            candidates = self._candidates_locked(exclude)
        if not candidates:
            return None
        self._refresh(candidates)
        context = context or {}
        tokens = context.get('tokens')
        request_key = context.get('request_key')
        migration = None
        with self._lock:
            if not tokens:
                # No prompt identity (text prompts, health canaries):
                # pure queue-depth, but stickiness still records so a
                # later keyed turn finds its session.
                choice = min(candidates, key=self._score_locked)
                outcome, recompute = None, 0
            else:
                choice, outcome, recompute, migration = (
                    self._select_affinity_locked(
                        candidates, list(tokens), request_key))
            if request_key:
                self._sessions.put(request_key, choice)
        observer = self._on_affinity
        migrate = self._migrate
        # Migration + observation run OUTSIDE the lock: the executor
        # may do (simulated or real) network work.
        if migration is not None and migrate is not None:
            src, dst, chain_hash, n_tokens = migration
            try:
                migrate(src, dst, chain_hash, n_tokens)
            except Exception as e:  # pylint: disable=broad-except
                logger.debug(
                    f'prefix migration {src} -> {dst} failed: '
                    f'{type(e).__name__}: {e}')
        if observer is not None and outcome is not None:
            observer(outcome, recompute)
        return choice

    def _select_affinity_locked(self, candidates: List[str],
                                tokens: List[int],
                                request_key: Optional[str]):
        """Longest-digest-match selection (callers hold ``self._lock``).
        Returns ``(choice, outcome, recompute_tokens, migration)``
        where migration is ``(src, dst, hash, n_tokens)`` or None.
        ``recompute_tokens`` counts prefix tokens the CHOSEN replica
        must recompute even though some other replica had them cached
        (0 on hit/miss; the affinity-vs-load gap only when routing
        away without a migration executor)."""
        sticky = (self._sessions.get(request_key)
                  if request_key else None)
        if sticky not in candidates:
            sticky = None
        grids: Dict[int, Dict[str, int]] = {}
        best: Dict[str, Tuple[int, Optional[str]]] = {}
        for u in candidates:
            m_len, m_hash = 0, None
            d = self._digests.get(u)
            if d:
                page = d['page']
                if page not in grids:
                    grids[page] = self._page_grid_hashes(tokens, page)
                grid = grids[page]
                # Iterate the replica's digest (<=16 entries), not the
                # request grid (<=64): per-select cost stays O(fleet)
                # even on thousand-replica fleets.
                for hhex in d['hashes']:
                    length = grid.get(hhex)
                    if length is not None and length > m_len:
                        m_len, m_hash = length, hhex
            if u == sticky:
                # The session's replica holds its whole prefix by
                # construction — even before the digest catches up.
                m_len = max(m_len, len(tokens) - 1)
            best[u] = (m_len, m_hash)
        best_len = max(m for m, _ in best.values())
        load_best = min(candidates, key=self._score_locked)
        if best_len <= 0:
            return load_best, 'miss', 0, None
        aff_pool = [u for u in candidates if best[u][0] == best_len]
        aff = min(aff_pool, key=self._score_locked)
        gap = self._score_locked(aff) - self._score_locked(load_best)
        chain_hash = best[aff][1]
        if (gap > self.migrate_threshold_tokens
                and load_best not in aff_pool
                and chain_hash is not None):
            migration = (aff, load_best, chain_hash, best_len)
            recompute = (0 if self._migrate is not None
                         else best_len - best[load_best][0])
            return load_best, 'migrated', recompute, migration
        return aff, 'hit', 0, None


POLICIES = {
    'round_robin': RoundRobinPolicy,
    'least_load': LeastLoadPolicy,
    'queue_depth': QueueDepthPolicy,
    'phase_aware': PhaseAwarePolicy,
    'prefix_affinity': PrefixAffinityPolicy,
}


def make_policy(name: str) -> LoadBalancingPolicy:
    if name not in POLICIES:
        raise ValueError(f'Unknown load balancing policy: {name!r}; '
                         f'choose from {sorted(POLICIES)}')
    return POLICIES[name]()
