"""Load-balancing policies (reference
``sky/serve/load_balancing_policies.py``: ``RoundRobinPolicy`` ``:89``,
``LeastLoadPolicy`` ``:115``). Pure selection logic over the ready-replica
URL list the LB syncs from the controller."""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set


class LoadBalancingPolicy:

    def __init__(self) -> None:
        self.ready_replicas: List[str] = []
        self._lock = threading.Lock()

    def set_ready_replicas(self, urls: List[str]) -> None:
        with self._lock:
            if set(urls) != set(self.ready_replicas):
                self._on_replicas_changed(urls)
            self.ready_replicas = list(urls)

    def _on_replicas_changed(self, urls: List[str]) -> None:
        del urls

    def select_replica(self,
                       exclude: Optional[Set[str]] = None
                       ) -> Optional[str]:
        """Pick a ready replica, skipping ``exclude`` (URLs that already
        failed this request — the LB's transparent retry)."""
        raise NotImplementedError

    def pre_execute(self, url: str) -> None:
        """Called when a request is dispatched to ``url``."""
        del url

    def post_execute(self, url: str) -> None:
        """Called when the request to ``url`` completes."""
        del url


class RoundRobinPolicy(LoadBalancingPolicy):

    def __init__(self) -> None:
        super().__init__()
        self._index = 0

    def _on_replicas_changed(self, urls: List[str]) -> None:
        # Hook invoked by set_ready_replicas WITH self._lock held; the
        # static checker cannot see the cross-method lock context.
        self._index = 0     # graftcheck: disable=GC101

    def select_replica(self,
                       exclude: Optional[Set[str]] = None
                       ) -> Optional[str]:
        with self._lock:
            candidates = [u for u in self.ready_replicas
                          if not exclude or u not in exclude]
            if not candidates:
                return None
            url = candidates[self._index % len(candidates)]
            self._index += 1
            return url


class LeastLoadPolicy(LoadBalancingPolicy):
    """Route to the replica with the fewest in-flight requests."""

    def __init__(self) -> None:
        super().__init__()
        self._inflight: Dict[str, int] = {}

    def select_replica(self,
                       exclude: Optional[Set[str]] = None
                       ) -> Optional[str]:
        with self._lock:
            candidates = [u for u in self.ready_replicas
                          if not exclude or u not in exclude]
            if not candidates:
                return None
            return min(candidates,
                       key=lambda u: self._inflight.get(u, 0))

    def pre_execute(self, url: str) -> None:
        with self._lock:
            self._inflight[url] = self._inflight.get(url, 0) + 1

    def post_execute(self, url: str) -> None:
        with self._lock:
            self._inflight[url] = max(0, self._inflight.get(url, 0) - 1)


POLICIES = {
    'round_robin': RoundRobinPolicy,
    'least_load': LeastLoadPolicy,
}


def make_policy(name: str) -> LoadBalancingPolicy:
    if name not in POLICIES:
        raise ValueError(f'Unknown load balancing policy: {name!r}; '
                         f'choose from {sorted(POLICIES)}')
    return POLICIES[name]()
