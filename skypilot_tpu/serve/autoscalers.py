"""Autoscalers: replica-count decisions from request telemetry.

Role of reference ``sky/serve/autoscalers.py`` (``Autoscaler`` ``:115``,
``RequestRateAutoscaler`` ``:431``, ``FallbackRequestRateAutoscaler``
``:546``): the controller feeds request timestamps (reported by the load
balancer) and current replica states in; scaling decisions come out.
Hysteresis: a scale-up/-down target must persist for
``upscale_delay_seconds`` / ``downscale_delay_seconds`` of consecutive
evaluations before it is acted on — QPS spikes don't thrash whole TPU
slices.

Pure, clock-injectable logic (no I/O) so tests drive it with synthetic
timestamps (reference pattern: ``tests/test_serve_autoscaler.py``).
Decision paths never read the wall clock directly — ``now`` is passed
in or drawn from the injected ``clock`` (graftcheck GC115 gates this
for both this module and ``serve/forecaster.py``), so a recorded trace
replays to identical decisions.

Forecast-aware scaling (SageServe-style, ``serve/forecaster.py``):
``ForecastRequestRateAutoscaler`` pre-scales *ahead* of traffic ramps
by the learned provisioning lead time (EWMA of observed replica READY
latencies, fed by the controller from
``skytpu_replica_provision_seconds`` observations), and refuses to
scale down while the forecast over that same lead window still needs
the capacity — never drain mid-burst.

Telemetry (stable schema, registered at construction):
``skytpu_autoscaler_target_replicas{kind}`` for kind in
``('applied', 'reactive', 'forecast')`` — zeros from the first scrape.
"""
from __future__ import annotations

import dataclasses
import enum
import math
import time
import typing
from typing import Any, Callable, Dict, List, Optional, Sequence

from skypilot_tpu import telemetry

if typing.TYPE_CHECKING:
    from skypilot_tpu.serve.service_spec import SkyServiceSpec

# Stable label set of skytpu_autoscaler_target_replicas{kind}:
# 'applied' is the hysteresis-filtered target the controller acts on;
# 'reactive' the raw QPS-window target; 'forecast' the lead-time-ahead
# forecast target (0 on non-forecast autoscalers).
TARGET_KINDS = ('applied', 'reactive', 'forecast')


class DecisionOperator(enum.Enum):
    SCALE_UP = 'scale_up'
    SCALE_DOWN = 'scale_down'


@dataclasses.dataclass
class ScalingDecision:
    operator: DecisionOperator
    # SCALE_UP: {'use_spot': bool}; SCALE_DOWN: {'replica_id': int}
    target: Dict[str, Any]


# Minimal view of a replica the autoscaler needs (the controller builds
# these from serve_state rows; tests build them directly).
@dataclasses.dataclass
class ReplicaView:
    replica_id: int
    is_ready: bool
    is_spot: bool
    is_terminal: bool = False     # preempted/failed: replaced, not counted
    is_draining: bool = False     # graceful scale-down in progress
    version: int = 1              # service version this replica runs


class Autoscaler:
    """Base: fixed replica count (no QPS signal)."""

    def __init__(self, spec: 'SkyServiceSpec',
                 clock: Callable[[], float] = time.time) -> None:
        self.spec = spec
        self.target_num_replicas = spec.min_replicas
        self.latest_version: int = 1
        # Injected clock: decision paths call self._clock() (or take an
        # explicit ``now``), never time.time() directly — GC115.
        self._clock = clock
        reg = telemetry.get_registry()
        self._g_target = {
            kind: reg.gauge(
                'skytpu_autoscaler_target_replicas',
                'Autoscaler replica targets (applied = hysteresis-'
                'filtered; reactive = raw QPS window; forecast = '
                'lead-time-ahead forecast)', kind=kind)
            for kind in TARGET_KINDS}

    def update_spec(self, spec: 'SkyServiceSpec', version: int) -> None:
        """Service update: new spec takes effect on the next evaluation.
        ``max_replicas is None`` means UNBOUNDED — the current target is
        only re-clamped from below (min) and, when a bound exists, from
        above; it must never silently collapse to ``min_replicas``."""
        self.spec = spec
        self.latest_version = version
        target = max(self.target_num_replicas, spec.min_replicas)
        if spec.max_replicas is not None:
            target = min(target, spec.max_replicas)
        self.target_num_replicas = target

    def collect_request_information(
            self, request_timestamps: List[float],
            request_tiers: Optional[Sequence[str]] = None) -> None:
        del request_timestamps, request_tiers

    # --------------------------------------------------------- snapshots
    # Crash-safety (round 15): the controller persists this each tick
    # and restores it on a recovery boot, so a restart never resets
    # the applied target to min_replicas (scaling the fleet down into
    # live traffic) and the forecast autoscaler keeps its seasonal
    # rings + learned provisioning lead.
    def export_state(self) -> Dict[str, Any]:
        return {'target_num_replicas': self.target_num_replicas}

    def restore_state(self, state: Dict[str, Any]) -> None:
        try:
            target = int(state.get('target_num_replicas',
                                   self.target_num_replicas))
        except (TypeError, ValueError):
            return
        target = max(target, self.spec.min_replicas)
        if self.spec.max_replicas is not None:
            target = min(target, self.spec.max_replicas)
        self.target_num_replicas = target

    def note_provision_seconds(self, seconds: float) -> None:
        """Observed replica provision latency (scale-up issued ->
        READY). The forecast autoscaler learns its pre-scaling lead
        time from these; the base classes ignore them."""
        del seconds

    def evaluate_scaling(
            self, replicas: List[ReplicaView],
            now: Optional[float] = None) -> List[ScalingDecision]:
        # Blue-green: only latest-version replicas count toward the
        # target, so an update launches replacements while the old
        # version keeps serving (the controller drains old replicas once
        # enough new ones are READY).
        alive = [r for r in replicas if not r.is_terminal
                 and r.version == self.latest_version]
        self._g_target['applied'].set(self.target_num_replicas)
        decisions: List[ScalingDecision] = []
        for _ in range(self.target_num_replicas - len(alive)):
            decisions.append(ScalingDecision(
                DecisionOperator.SCALE_UP, {'use_spot': self._use_spot()}))
        if len(alive) > self.target_num_replicas:
            for rep in self._downscale_candidates(
                    alive, len(alive) - self.target_num_replicas):
                decisions.append(ScalingDecision(
                    DecisionOperator.SCALE_DOWN,
                    {'replica_id': rep.replica_id}))
        return decisions

    def _use_spot(self) -> bool:
        return False

    @staticmethod
    def _downscale_candidates(alive: List[ReplicaView],
                              count: int) -> List[ReplicaView]:
        """Prefer replicas already draining (the decision is in flight
        — re-issuing it is an idempotent no-op, never a second
        victim), then not-ready ones, then highest ids (newest)."""
        return sorted(alive, key=lambda r: (not r.is_draining,
                                            r.is_ready,
                                            -r.replica_id))[:count]

    @classmethod
    def from_spec(cls, spec: 'SkyServiceSpec',
                  clock: Callable[[], float] = time.time) -> 'Autoscaler':
        if spec.autoscaling_enabled:
            fallback = (spec.base_ondemand_fallback_replicas > 0
                        or spec.dynamic_ondemand_fallback)
            if spec.forecast_enabled:
                return (ForecastFallbackAutoscaler(spec, clock) if fallback
                        else ForecastRequestRateAutoscaler(spec, clock))
            if fallback:
                return FallbackRequestRateAutoscaler(spec, clock)
            return RequestRateAutoscaler(spec, clock)
        return Autoscaler(spec, clock)


class RequestRateAutoscaler(Autoscaler):
    """QPS-driven: target = ceil(qps / target_qps_per_replica), bounded
    below by ``min_replicas`` and above by ``max_replicas`` when one is
    set (``None`` = unbounded), applied only after the hysteresis delay
    (reference ``sky/serve/autoscalers.py:431``, hysteresis ``:348``)."""

    QPS_WINDOW_SECONDS = 60.0
    # Between-trim bound on the pending timestamp list: a burst between
    # controller ticks must not hold an unbounded list (the window trim
    # only runs when the QPS is read). 100k timestamps ≈ 1.6k QPS
    # sustained over the 60 s window — far past anything one controller
    # serves; beyond it only the newest are kept.
    MAX_PENDING_TIMESTAMPS = 100_000

    def __init__(self, spec: 'SkyServiceSpec',
                 clock: Callable[[], float] = time.time) -> None:
        super().__init__(spec, clock)
        self._request_timestamps: List[float] = []
        # Hysteresis is wall-clock-based (first moment the raw target
        # breached the current one), NOT eval-count-based: the controller
        # tick is configurable (SKYTPU_SERVE_TICK), and counting evals
        # would silently rescale the configured delays with it.
        self._upscale_breach_since: Optional[float] = None
        self._downscale_breach_since: Optional[float] = None

    # ------------------------------------------------------------- signal
    def collect_request_information(
            self, request_timestamps: List[float],
            request_tiers: Optional[Sequence[str]] = None) -> None:
        del request_tiers
        self._request_timestamps.extend(request_timestamps)
        if len(self._request_timestamps) > self.MAX_PENDING_TIMESTAMPS:
            # Trim against the newest timestamp seen (no wall-clock
            # read on this path — GC115): first drop what the window
            # would drop anyway, then hard-cap to the newest entries.
            self._trim_window(max(self._request_timestamps))
            if len(self._request_timestamps) > self.MAX_PENDING_TIMESTAMPS:
                self._request_timestamps = sorted(
                    self._request_timestamps)[-self.MAX_PENDING_TIMESTAMPS:]

    def _trim_window(self, now: float) -> None:
        cutoff = now - self.QPS_WINDOW_SECONDS
        self._request_timestamps = [
            t for t in self._request_timestamps if t >= cutoff]

    def current_qps(self, now: Optional[float] = None) -> float:
        now = self._clock() if now is None else now
        self._trim_window(now)
        return len(self._request_timestamps) / self.QPS_WINDOW_SECONDS

    # ------------------------------------------------------------ evaluate
    def _bound_target(self, target: int) -> int:
        """Clamp to [min_replicas, max_replicas]; ``max_replicas is
        None`` bounds only from below (unbounded scale-up) — it must
        never collapse the target to ``min_replicas``."""
        target = max(target, self.spec.min_replicas)
        if self.spec.max_replicas is not None:
            target = min(target, self.spec.max_replicas)
        return target

    def _reactive_target(self, now: float) -> int:
        qps = self.current_qps(now)
        assert self.spec.target_qps_per_replica is not None
        target = self._bound_target(
            math.ceil(qps / self.spec.target_qps_per_replica))
        self._g_target['reactive'].set(target)
        return target

    def _raw_target(self, now: float) -> int:
        return self._reactive_target(now)

    def _downscale_allowed(self, raw: int, now: float) -> bool:
        """Hook: the forecast autoscaler vetoes downscales the forecast
        window still needs (never drain mid-burst)."""
        del raw, now
        return True

    def _update_target(self, now: float) -> None:
        raw = self._raw_target(now)
        if raw > self.target_num_replicas:
            self._downscale_breach_since = None
            if self._upscale_breach_since is None:
                self._upscale_breach_since = now
            if (now - self._upscale_breach_since
                    >= self.spec.upscale_delay_seconds):
                self.target_num_replicas = raw
                self._upscale_breach_since = None
        elif raw < self.target_num_replicas:
            self._upscale_breach_since = None
            if not self._downscale_allowed(raw, now):
                # The forecast window still needs this capacity: hold,
                # and restart the downscale clock so the drain only
                # begins once the forecast has cleared for the full
                # hysteresis delay.
                self._downscale_breach_since = None
                return
            if self._downscale_breach_since is None:
                self._downscale_breach_since = now
            if (now - self._downscale_breach_since
                    >= self.spec.downscale_delay_seconds):
                self.target_num_replicas = raw
                self._downscale_breach_since = None
        else:
            self._upscale_breach_since = None
            self._downscale_breach_since = None

    def evaluate_scaling(
            self, replicas: List[ReplicaView],
            now: Optional[float] = None) -> List[ScalingDecision]:
        now = self._clock() if now is None else now
        self._update_target(now)
        return super().evaluate_scaling(replicas, now)

    def _use_spot(self) -> bool:
        # Plain request-rate autoscaler follows the task's own use_spot.
        return False


class FallbackRequestRateAutoscaler(RequestRateAutoscaler):
    """Spot + on-demand mix (reference ``:546``): keep
    ``base_ondemand_fallback_replicas`` on-demand replicas as ballast; the
    remainder of the target runs on preemptible capacity. With
    ``dynamic_ondemand_fallback``, a preempted spot replica is temporarily
    backfilled on-demand (decided by the controller passing terminal spot
    replicas here)."""

    def evaluate_scaling(
            self, replicas: List[ReplicaView],
            now: Optional[float] = None) -> List[ScalingDecision]:
        now = self._clock() if now is None else now
        self._update_target(now)
        alive = [r for r in replicas if not r.is_terminal
                 and r.version == self.latest_version]
        self._g_target['applied'].set(self.target_num_replicas)
        base = min(self.spec.base_ondemand_fallback_replicas,
                   self.target_num_replicas)
        want_od = base
        want_spot = self.target_num_replicas - base
        have_od = sum(1 for r in alive if not r.is_spot)
        have_spot = sum(1 for r in alive if r.is_spot)
        if self.spec.dynamic_ondemand_fallback:
            # Backfill not-yet-ready spot capacity (preempted or still
            # provisioning) with temporary on-demand replicas; they are
            # scaled back down as spot replicas turn READY.
            ready_spot = sum(1 for r in alive if r.is_spot and r.is_ready)
            want_od = min(self.target_num_replicas,
                          base + max(0, want_spot - ready_spot))

        decisions: List[ScalingDecision] = []
        for _ in range(want_od - have_od):
            decisions.append(ScalingDecision(DecisionOperator.SCALE_UP,
                                             {'use_spot': False}))
        for _ in range(want_spot - have_spot):
            decisions.append(ScalingDecision(DecisionOperator.SCALE_UP,
                                             {'use_spot': True}))
        for kind_spot, excess in ((False, have_od - want_od),
                                  (True, have_spot - want_spot)):
            if excess <= 0:
                continue
            pool = [r for r in alive if r.is_spot == kind_spot]
            for rep in self._downscale_candidates(pool, excess):
                decisions.append(ScalingDecision(
                    DecisionOperator.SCALE_DOWN,
                    {'replica_id': rep.replica_id}))
        return decisions


class _ForecastMixin:
    """Forecast-aware behavior layered over the request-rate
    autoscalers (SageServe-style): the raw target is the max of the
    reactive QPS target and the forecast target at ``now + lead``
    (lead = EWMA of observed replica provision latencies, default the
    spec's ``initial_delay_seconds``), so scale-up decisions fire
    *before* the ramp arrives; downscales are vetoed while the peak
    forecast inside the lead window still needs the capacity."""

    # EWMA weight for provision-latency observations.
    LEAD_EWMA_ALPHA = 0.3

    def __init__(self, spec: 'SkyServiceSpec',
                 clock: Callable[[], float] = time.time) -> None:
        super().__init__(spec, clock)  # type: ignore[call-arg]
        from skypilot_tpu.serve import forecaster as forecaster_lib
        self.forecaster = forecaster_lib.TrafficForecaster(
            bucket_s=spec.forecast_bucket_seconds,
            season_s=spec.forecast_season_seconds,
            horizon_s=spec.forecast_horizon_seconds,
            clock=clock)
        self._g_forecast = forecaster_lib.register_metrics()
        self._lead_s: Optional[float] = None

    # ------------------------------------------------------------- signal
    def collect_request_information(
            self, request_timestamps: List[float],
            request_tiers: Optional[Sequence[str]] = None) -> None:
        super().collect_request_information(  # type: ignore[misc]
            request_timestamps)
        self.forecaster.observe(request_timestamps, request_tiers)

    def note_provision_seconds(self, seconds: float) -> None:
        if self._lead_s is None:
            self._lead_s = float(seconds)
        else:
            a = self.LEAD_EWMA_ALPHA
            self._lead_s = a * float(seconds) + (1 - a) * self._lead_s

    def export_state(self) -> Dict[str, Any]:
        state = super().export_state()  # type: ignore[misc]
        state['lead_s'] = self._lead_s
        state['forecaster'] = self.forecaster.snapshot()
        return state

    def restore_state(self, state: Dict[str, Any]) -> None:
        super().restore_state(state)  # type: ignore[misc]
        if state.get('lead_s') is not None:
            try:
                self._lead_s = float(state['lead_s'])
            except (TypeError, ValueError):
                pass
        snap = state.get('forecaster')
        if isinstance(snap, dict):
            self.forecaster.restore(snap)

    def provision_lead_s(self) -> float:
        """The pre-scaling lead time: learned from READY latencies once
        any replica has provisioned, the spec's probe allowance before
        that, always at least one forecast bucket (a zero lead would
        degenerate to reactive scaling)."""
        lead = (self._lead_s if self._lead_s is not None
                else self.spec.initial_delay_seconds)
        return max(lead, self.forecaster.bucket_s)

    # ------------------------------------------------------------ targets
    def _forecast_target(self, now: float) -> int:
        lead = self.provision_lead_s()
        fq = self.forecaster.forecast_qps(lead, 'all', now)
        for tier in ('all', 'latency', 'throughput'):
            self._g_forecast['now'][tier].set(
                self.forecaster.qps(tier, now))
            self._g_forecast['lead'][tier].set(
                self.forecaster.forecast_qps(lead, tier, now))
        assert self.spec.target_qps_per_replica is not None
        target = self._bound_target(
            math.ceil(fq / self.spec.target_qps_per_replica))
        self._g_target['forecast'].set(target)
        return target

    def _raw_target(self, now: float) -> int:
        return max(self._reactive_target(now),
                   self._forecast_target(now))

    def _downscale_allowed(self, raw: int, now: float) -> bool:
        """Never drain mid-burst: hold the capacity while the PEAK
        forecast anywhere inside the provisioning lead window still
        exceeds what the proposed smaller target serves."""
        assert self.spec.target_qps_per_replica is not None
        peak = self.forecaster.peak_forecast_qps(
            self.provision_lead_s(), 'all', now)
        return peak <= raw * self.spec.target_qps_per_replica


class ForecastRequestRateAutoscaler(_ForecastMixin,
                                    RequestRateAutoscaler):
    """Forecast-aware QPS autoscaler (single capacity kind)."""


class ForecastFallbackAutoscaler(_ForecastMixin,
                                 FallbackRequestRateAutoscaler):
    """Forecast-aware spot + on-demand mix: pre-scales ahead of ramps
    AND keeps the fallback/backfill policy for preemptible capacity —
    the spot-serving default (``forecast:`` + fallback knobs in the
    ``replica_policy`` yaml)."""
