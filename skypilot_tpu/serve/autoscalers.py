"""Autoscalers: replica-count decisions from request telemetry.

Role of reference ``sky/serve/autoscalers.py`` (``Autoscaler`` ``:115``,
``RequestRateAutoscaler`` ``:431``, ``FallbackRequestRateAutoscaler``
``:546``): the controller feeds request timestamps (reported by the load
balancer) and current replica states in; scaling decisions come out.
Hysteresis: a scale-up/-down target must persist for
``upscale_delay_seconds`` / ``downscale_delay_seconds`` of consecutive
evaluations before it is acted on — QPS spikes don't thrash whole TPU
slices.

Pure, clock-injectable logic (no I/O) so tests drive it with synthetic
timestamps (reference pattern: ``tests/test_serve_autoscaler.py``).
"""
from __future__ import annotations

import dataclasses
import enum
import math
import time
import typing
from typing import Any, Dict, List, Optional

if typing.TYPE_CHECKING:
    from skypilot_tpu.serve.service_spec import SkyServiceSpec


class DecisionOperator(enum.Enum):
    SCALE_UP = 'scale_up'
    SCALE_DOWN = 'scale_down'


@dataclasses.dataclass
class ScalingDecision:
    operator: DecisionOperator
    # SCALE_UP: {'use_spot': bool}; SCALE_DOWN: {'replica_id': int}
    target: Dict[str, Any]


# Minimal view of a replica the autoscaler needs (the controller builds
# these from serve_state rows; tests build them directly).
@dataclasses.dataclass
class ReplicaView:
    replica_id: int
    is_ready: bool
    is_spot: bool
    is_terminal: bool = False     # preempted/failed: replaced, not counted
    is_draining: bool = False     # graceful scale-down in progress
    version: int = 1              # service version this replica runs


class Autoscaler:
    """Base: fixed replica count (no QPS signal)."""

    def __init__(self, spec: 'SkyServiceSpec') -> None:
        self.spec = spec
        self.target_num_replicas = spec.min_replicas
        self.latest_version: int = 1

    def update_spec(self, spec: 'SkyServiceSpec', version: int) -> None:
        """Service update: new spec takes effect on the next evaluation."""
        self.spec = spec
        self.latest_version = version
        self.target_num_replicas = min(
            max(self.target_num_replicas, spec.min_replicas),
            spec.max_replicas if spec.max_replicas is not None
            else spec.min_replicas)

    def collect_request_information(
            self, request_timestamps: List[float]) -> None:
        del request_timestamps

    def evaluate_scaling(
            self, replicas: List[ReplicaView],
            now: Optional[float] = None) -> List[ScalingDecision]:
        # Blue-green: only latest-version replicas count toward the
        # target, so an update launches replacements while the old
        # version keeps serving (the controller drains old replicas once
        # enough new ones are READY).
        alive = [r for r in replicas if not r.is_terminal
                 and r.version == self.latest_version]
        decisions: List[ScalingDecision] = []
        for _ in range(self.target_num_replicas - len(alive)):
            decisions.append(ScalingDecision(
                DecisionOperator.SCALE_UP, {'use_spot': self._use_spot()}))
        if len(alive) > self.target_num_replicas:
            for rep in self._downscale_candidates(
                    alive, len(alive) - self.target_num_replicas):
                decisions.append(ScalingDecision(
                    DecisionOperator.SCALE_DOWN,
                    {'replica_id': rep.replica_id}))
        return decisions

    def _use_spot(self) -> bool:
        return False

    @staticmethod
    def _downscale_candidates(alive: List[ReplicaView],
                              count: int) -> List[ReplicaView]:
        """Prefer replicas already draining (the decision is in flight
        — re-issuing it is an idempotent no-op, never a second
        victim), then not-ready ones, then highest ids (newest)."""
        return sorted(alive, key=lambda r: (not r.is_draining,
                                            r.is_ready,
                                            -r.replica_id))[:count]

    @classmethod
    def from_spec(cls, spec: 'SkyServiceSpec') -> 'Autoscaler':
        if spec.autoscaling_enabled:
            if spec.base_ondemand_fallback_replicas > 0 or \
                    spec.dynamic_ondemand_fallback:
                return FallbackRequestRateAutoscaler(spec)
            return RequestRateAutoscaler(spec)
        return Autoscaler(spec)


class RequestRateAutoscaler(Autoscaler):
    """QPS-driven: target = ceil(qps / target_qps_per_replica), bounded to
    [min_replicas, max_replicas], applied only after the hysteresis delay
    (reference ``sky/serve/autoscalers.py:431``, hysteresis ``:348``)."""

    QPS_WINDOW_SECONDS = 60.0

    def __init__(self, spec: 'SkyServiceSpec') -> None:
        super().__init__(spec)
        self._request_timestamps: List[float] = []
        # Hysteresis is wall-clock-based (first moment the raw target
        # breached the current one), NOT eval-count-based: the controller
        # tick is configurable (SKYTPU_SERVE_TICK), and counting evals
        # would silently rescale the configured delays with it.
        self._upscale_breach_since: Optional[float] = None
        self._downscale_breach_since: Optional[float] = None

    # ------------------------------------------------------------- signal
    def collect_request_information(
            self, request_timestamps: List[float]) -> None:
        self._request_timestamps.extend(request_timestamps)

    def _trim_window(self, now: float) -> None:
        cutoff = now - self.QPS_WINDOW_SECONDS
        self._request_timestamps = [
            t for t in self._request_timestamps if t >= cutoff]

    def current_qps(self, now: Optional[float] = None) -> float:
        now = time.time() if now is None else now
        self._trim_window(now)
        return len(self._request_timestamps) / self.QPS_WINDOW_SECONDS

    # ------------------------------------------------------------ evaluate
    def _raw_target(self, now: float) -> int:
        qps = self.current_qps(now)
        assert self.spec.target_qps_per_replica is not None
        target = math.ceil(qps / self.spec.target_qps_per_replica)
        lo = self.spec.min_replicas
        hi = self.spec.max_replicas
        return min(max(target, lo), hi if hi is not None else lo)

    def _update_target(self, now: float) -> None:
        raw = self._raw_target(now)
        if raw > self.target_num_replicas:
            self._downscale_breach_since = None
            if self._upscale_breach_since is None:
                self._upscale_breach_since = now
            if (now - self._upscale_breach_since
                    >= self.spec.upscale_delay_seconds):
                self.target_num_replicas = raw
                self._upscale_breach_since = None
        elif raw < self.target_num_replicas:
            self._upscale_breach_since = None
            if self._downscale_breach_since is None:
                self._downscale_breach_since = now
            if (now - self._downscale_breach_since
                    >= self.spec.downscale_delay_seconds):
                self.target_num_replicas = raw
                self._downscale_breach_since = None
        else:
            self._upscale_breach_since = None
            self._downscale_breach_since = None

    def evaluate_scaling(
            self, replicas: List[ReplicaView],
            now: Optional[float] = None) -> List[ScalingDecision]:
        now = time.time() if now is None else now
        self._update_target(now)
        return super().evaluate_scaling(replicas, now)

    def _use_spot(self) -> bool:
        # Plain request-rate autoscaler follows the task's own use_spot.
        return False


class FallbackRequestRateAutoscaler(RequestRateAutoscaler):
    """Spot + on-demand mix (reference ``:546``): keep
    ``base_ondemand_fallback_replicas`` on-demand replicas as ballast; the
    remainder of the target runs on preemptible capacity. With
    ``dynamic_ondemand_fallback``, a preempted spot replica is temporarily
    backfilled on-demand (decided by the controller passing terminal spot
    replicas here)."""

    def evaluate_scaling(
            self, replicas: List[ReplicaView],
            now: Optional[float] = None) -> List[ScalingDecision]:
        now = time.time() if now is None else now
        self._update_target(now)
        alive = [r for r in replicas if not r.is_terminal
                 and r.version == self.latest_version]
        base = min(self.spec.base_ondemand_fallback_replicas,
                   self.target_num_replicas)
        want_od = base
        want_spot = self.target_num_replicas - base
        have_od = sum(1 for r in alive if not r.is_spot)
        have_spot = sum(1 for r in alive if r.is_spot)
        if self.spec.dynamic_ondemand_fallback:
            # Backfill not-yet-ready spot capacity (preempted or still
            # provisioning) with temporary on-demand replicas; they are
            # scaled back down as spot replicas turn READY.
            ready_spot = sum(1 for r in alive if r.is_spot and r.is_ready)
            want_od = min(self.target_num_replicas,
                          base + max(0, want_spot - ready_spot))

        decisions: List[ScalingDecision] = []
        for _ in range(want_od - have_od):
            decisions.append(ScalingDecision(DecisionOperator.SCALE_UP,
                                             {'use_spot': False}))
        for _ in range(want_spot - have_spot):
            decisions.append(ScalingDecision(DecisionOperator.SCALE_UP,
                                             {'use_spot': True}))
        for kind_spot, excess in ((False, have_od - want_od),
                                  (True, have_spot - want_spot)):
            if excess <= 0:
                continue
            pool = [r for r in alive if r.is_spot == kind_spot]
            for rep in self._downscale_candidates(pool, excess):
                decisions.append(ScalingDecision(
                    DecisionOperator.SCALE_DOWN,
                    {'replica_id': rep.replica_id}))
        return decisions
