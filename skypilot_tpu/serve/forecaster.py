"""Short-horizon traffic forecaster for forecast-aware autoscaling.

SageServe-style (PAPERS.md): reactive autoscaling pays the provisioning
lead time *after* a ramp arrives — every burst eats the full replica
boot latency as shed (429) or queue-blowout TTFT. A short-horizon
forecast moves the scale-up decision *ahead* of the ramp by exactly
that lead time, so capacity is READY when the traffic lands.

Model — deliberately tiny, exact, and clock-injectable (no sklearn, no
sleeps, GC115 bans wall-clock reads in here):

- Arrivals are bucketed into a bounded ring of per-tier counts
  (``bucket_s`` seconds per bucket, ``ring_buckets`` buckets retained).
  The feed is the LB's request timestamps (optionally tier-tagged)
  relayed through the controller sync — the same signal the reactive
  QPS window uses, so the two autoscalers are comparable on identical
  traces.
- **Seasonal-naive** component: the rate observed one season ago at the
  forecast target time (``season_s``; diurnal traffic repeats, so
  yesterday-at-this-time — or ten-minutes-ago for short test seasons —
  is a strong prior).
- **EWMA level + trend** (Holt) component over the most recent
  ``trend_buckets`` complete buckets: captures ramps the season has
  never seen.
- The forecast is the **max** of the two: scaling up early is cheap
  (one replica-hour), scaling up late is an SLO breach — the asymmetry
  makes the conservative-up combination the right default.

Everything takes an explicit ``now`` (or the injected ``clock``), so
tests replay synthetic diurnal/bursty traces deterministically —
``graftcheck`` GC115 gates that no decision path in this module or
``serve/autoscalers.py`` ever reads the wall clock directly.

Telemetry (stable schema, registered at construction):
``skytpu_forecast_qps{tier,horizon}`` for every tier in :data:`TIERS`
and horizon in ``('now', 'lead')`` — zeros from the first scrape.
"""
from __future__ import annotations

import collections
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence

from skypilot_tpu import telemetry

# Stable tier label set of skytpu_forecast_qps{tier,horizon}. Every
# arrival counts into 'all'; arrivals with an explicit SLO tier also
# count into their own series.
TIERS = ('all', 'latency', 'throughput')
HORIZONS = ('now', 'lead')


def register_metrics() -> Dict[str, Dict[str, 'telemetry.Gauge']]:
    """Register the forecast gauge family up front (zeros from the
    first scrape — the stable-schema contract) and return the
    ``{horizon: {tier: gauge}}`` table the forecaster writes."""
    reg = telemetry.get_registry()
    return {
        horizon: {
            tier: reg.gauge(
                'skytpu_forecast_qps',
                'Forecast arrival rate (requests/s) at the given '
                'horizon ("now" = current level, "lead" = the learned '
                'provisioning lead time ahead)',
                tier=tier, horizon=horizon)
            for tier in TIERS
        } for horizon in HORIZONS
    }


class TrafficForecaster:
    """Seasonal-naive + EWMA-trend forecaster over a bounded ring of
    per-tier arrival counts.

    Pure host-side state; not thread-safe by itself — the controller
    tick (the single caller) serializes ``observe``/``forecast_qps``.
    """

    def __init__(self, *, bucket_s: float = 10.0,
                 season_s: float = 600.0,
                 horizon_s: float = 120.0,
                 ring_buckets: int = 720,
                 ewma_alpha: float = 0.4,
                 trend_buckets: int = 6,
                 clock: Callable[[], float] = time.time):
        if bucket_s <= 0:
            raise ValueError('bucket_s must be positive')
        if season_s < bucket_s:
            raise ValueError('season_s must cover at least one bucket')
        self.bucket_s = float(bucket_s)
        self.season_s = float(season_s)
        self.horizon_s = float(horizon_s)
        self.ring_buckets = int(ring_buckets)
        self.ewma_alpha = float(ewma_alpha)
        self.trend_buckets = int(trend_buckets)
        self._clock = clock
        # tier -> {bucket_index: count}; bounded to ring_buckets per
        # tier (oldest evicted), so a long-lived controller holds a
        # fixed-size signal no matter the traffic volume.
        self._counts: Dict[str, 'collections.OrderedDict[int, int]'] = {
            t: collections.OrderedDict() for t in TIERS}

    # --------------------------------------------------------------- feed
    def _bucket(self, ts: float) -> int:
        return int(ts // self.bucket_s)

    def observe(self, timestamps: Sequence[float],
                tiers: Optional[Sequence[str]] = None) -> None:
        """Fold a batch of arrival timestamps into the ring. ``tiers``
        (parallel to ``timestamps``) tags arrivals with their SLO tier
        when the LB knew it; unknown/missing tiers count into 'all'
        only."""
        for i, ts in enumerate(timestamps):
            b = self._bucket(float(ts))
            self._bump('all', b)
            tier = tiers[i] if tiers is not None and i < len(tiers) \
                else None
            if tier in ('latency', 'throughput'):
                self._bump(tier, b)

    def _bump(self, tier: str, bucket: int) -> None:
        ring = self._counts[tier]
        ring[bucket] = ring.get(bucket, 0) + 1
        while len(ring) > self.ring_buckets:
            ring.popitem(last=False)

    # --------------------------------------------------------- snapshots
    def snapshot(self) -> Dict[str, object]:
        """JSON-able snapshot of the arrival rings — persisted by the
        controller each tick so a restart keeps its seasonal memory
        (round 15: a forecaster that reboots empty would scale the
        fleet DOWN into the very burst it had already learned)."""
        return {
            'bucket_s': self.bucket_s,
            'counts': {tier: [[int(b), int(n)]
                              for b, n in ring.items()]
                       for tier, ring in self._counts.items()},
        }

    def restore(self, snap: Dict[str, object]) -> None:
        """Merge a :meth:`snapshot` back in (bucket geometry must
        match — a spec update that changed ``bucket_s`` makes the old
        ring meaningless and it is dropped)."""
        if float(snap.get('bucket_s', self.bucket_s)) != self.bucket_s:
            return
        for tier, items in (snap.get('counts') or {}).items():
            if tier not in self._counts:
                continue
            ring = self._counts[tier]
            for bucket, count in items:
                ring[int(bucket)] = max(ring.get(int(bucket), 0),
                                        int(count))
            # Re-sort by bucket so the ring's eviction order stays
            # oldest-first, then re-bound it.
            ordered = collections.OrderedDict(sorted(ring.items()))
            while len(ordered) > self.ring_buckets:
                ordered.popitem(last=False)
            self._counts[tier] = ordered

    # ------------------------------------------------------------ queries
    def _recent_rates(self, tier: str, now: float,
                      n: int) -> List[float]:
        """Rates (req/s) of the last ``n`` COMPLETE buckets, oldest
        first (the in-progress bucket is excluded — its count is
        partial and would bias the level down)."""
        ring = self._counts[tier]
        cur = self._bucket(now)
        return [ring.get(cur - i, 0) / self.bucket_s
                for i in range(n, 0, -1)]

    def level_and_trend(self, tier: str = 'all',
                        now: Optional[float] = None):
        """Holt smoothing over the recent complete buckets: (level
        req/s, trend req/s per bucket)."""
        now = self._clock() if now is None else now
        rates = self._recent_rates(tier, now, self.trend_buckets)
        if not rates:
            return 0.0, 0.0
        level = rates[0]
        trend = 0.0
        a = self.ewma_alpha
        for prev, rate in zip(rates, rates[1:]):
            trend = a * (rate - prev) + (1 - a) * trend
            level = a * rate + (1 - a) * (level + trend)
        return level, trend

    def qps(self, tier: str = 'all',
            now: Optional[float] = None) -> float:
        """Current smoothed arrival rate (req/s)."""
        now = self._clock() if now is None else now
        return max(0.0, self.level_and_trend(tier, now)[0])

    def seasonal_qps(self, horizon_s: float, tier: str = 'all',
                     now: Optional[float] = None) -> Optional[float]:
        """The rate observed one season before ``now + horizon_s``
        (None when that bucket predates the ring / was never seen
        alongside any neighbor — no seasonal evidence yet)."""
        now = self._clock() if now is None else now
        ring = self._counts[tier]
        if not ring:
            return None
        target = self._bucket(now + horizon_s - self.season_s)
        oldest = next(iter(ring))
        if target < oldest or target > self._bucket(now):
            return None
        # Average over a 3-bucket neighborhood: a single seasonal
        # bucket is noisy at low rates.
        vals = [ring.get(target + d, 0) for d in (-1, 0, 1)]
        return sum(vals) / (3 * self.bucket_s)

    def forecast_qps(self, horizon_s: float, tier: str = 'all',
                     now: Optional[float] = None) -> float:
        """Arrival-rate forecast ``horizon_s`` seconds ahead: the max
        of the seasonal-naive rate and the Holt level+trend projection
        (conservative-up — see module docstring)."""
        now = self._clock() if now is None else now
        level, trend = self.level_and_trend(tier, now)
        projected = level + trend * (horizon_s / self.bucket_s)
        seasonal = self.seasonal_qps(horizon_s, tier, now)
        out = max(0.0, projected)
        if seasonal is not None:
            out = max(out, seasonal)
        return out

    def peak_forecast_qps(self, horizon_s: float, tier: str = 'all',
                          now: Optional[float] = None,
                          points: int = 4) -> float:
        """Max forecast over ``[now, now + horizon_s]`` sampled at
        ``points`` evenly spaced horizons — the scale-DOWN guard: a
        replica is only released when no point inside the provisioning
        lead window forecasts needing it back (never drain mid-burst,
        since an undone drain pays the full relaunch latency)."""
        now = self._clock() if now is None else now
        if points < 2 or horizon_s <= 0:
            return self.forecast_qps(max(0.0, horizon_s), tier, now)
        return max(self.forecast_qps(horizon_s * i / (points - 1),
                                     tier, now)
                   for i in range(points))
