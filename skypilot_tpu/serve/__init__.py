"""SkyServe-equivalent: autoscaled model serving on TPU slices.

Lazy exports (importing the engine pulls jax; the spec layer must not).
"""
from typing import Any

_LAZY = {
    'up': ('skypilot_tpu.serve.core', 'up'),
    'update': ('skypilot_tpu.serve.core', 'update'),
    'down': ('skypilot_tpu.serve.core', 'down'),
    'status': ('skypilot_tpu.serve.core', 'status'),
    'tail_logs': ('skypilot_tpu.serve.core', 'tail_logs'),
    'SkyServiceSpec': ('skypilot_tpu.serve.service_spec', 'SkyServiceSpec'),
}


def __getattr__(name: str) -> Any:
    if name in _LAZY:
        import importlib
        module_name, attr = _LAZY[name]
        value = getattr(importlib.import_module(module_name), attr)
        globals()[name] = value
        return value
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')


__all__ = list(_LAZY)
