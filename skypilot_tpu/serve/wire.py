"""The trace-propagating HTTP request helper for the serve tier.

Every outbound HTTP call under ``serve/`` that carries a request body
to another skytpu process goes through here (graftcheck GC123 gates
it): the helper is the ONE place the ``X-Skytpu-Trace`` hop header is
attached, so a hop added later can never silently drop the
cross-process trace chain. Read-only liveness probes
(``ControlPlaneEnv.probe_http``) are exempt — they are not requests.

``trace`` accepts the formatted header value (str), a parsed context
dict (``{'trace_id', 'parent_span'}``), or None (no header — e.g. a
call that genuinely has no request identity, like a bulk sync).
"""
from __future__ import annotations

import json
import urllib.request
from typing import Any, Dict, Optional, Union

from skypilot_tpu.telemetry import tracing

TRACE_HEADER = tracing.TRACE_HEADER

TraceArg = Optional[Union[str, Dict[str, Any]]]


def trace_header_value(trace: TraceArg) -> Optional[str]:
    """Normalize a ``trace`` argument to the wire header value."""
    if not trace:
        return None
    if isinstance(trace, str):
        return trace
    tid = trace.get('trace_id')
    if not tid:
        return None
    return tracing.format_trace_header(tid, trace.get('parent_span'))


def build_request(url: str, *, data: Optional[bytes] = None,
                  headers: Optional[Dict[str, str]] = None,
                  method: Optional[str] = None,
                  trace: TraceArg = None) -> urllib.request.Request:
    """An outbound request with the trace hop header attached (unless
    the caller's headers already carry one — a proxied client header
    wins over a re-mint)."""
    headers = dict(headers or {})
    value = trace_header_value(trace)
    if value is not None and not any(
            k.lower() == TRACE_HEADER.lower() for k in headers):
        headers[TRACE_HEADER] = value
    return urllib.request.Request(url, data=data, headers=headers,
                                  method=method)


def urlopen(url_or_req, *, data: Optional[bytes] = None,
            headers: Optional[Dict[str, str]] = None,
            method: Optional[str] = None, trace: TraceArg = None,
            timeout: float = 30.0):
    """Open an outbound hop (returns the live response object — the
    caller streams/closes it). Accepts a prebuilt request from
    :func:`build_request` or a URL plus the same keywords."""
    if isinstance(url_or_req, urllib.request.Request):
        req = url_or_req
    else:
        req = build_request(url_or_req, data=data, headers=headers,
                            method=method, trace=trace)
    return urllib.request.urlopen(req, timeout=timeout)


def post_json(url: str, payload: Dict[str, Any], *,
              timeout: float = 10.0, trace: TraceArg = None,
              headers: Optional[Dict[str, str]] = None
              ) -> Dict[str, Any]:
    """POST a JSON body to another skytpu process; parsed JSON reply."""
    headers = dict(headers or {})
    headers.setdefault('Content-Type', 'application/json')
    body = json.dumps(payload).encode()
    with urlopen(url, data=body, headers=headers, trace=trace,
                 timeout=timeout) as resp:
        return json.loads(resp.read())


def post_bytes(url: str, blob: bytes, *, timeout: float = 30.0,
               trace: TraceArg = None,
               headers: Optional[Dict[str, str]] = None
               ) -> Dict[str, Any]:
    """POST an opaque blob (KV snapshots, SKPF prefix containers)."""
    headers = dict(headers or {})
    headers.setdefault('Content-Type', 'application/octet-stream')
    with urlopen(url, data=blob, headers=headers, trace=trace,
                 timeout=timeout) as resp:
        return json.loads(resp.read())


def get_json(url: str, *, timeout: float = 10.0) -> Dict[str, Any]:
    """GET a JSON document (no body, no trace hop — reads only)."""
    with urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())
