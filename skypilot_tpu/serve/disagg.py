"""Disaggregated prefill/decode serving: roles, handoff targets,
telemetry.

ThunderServe-style phase disaggregation (PAPERS.md): dedicated
*prefill* workers run admission + chunked prefill, then stream each
finished request's KV — int8 codes + scales staying int8 on the wire
(``inference/kv_transfer.py``) — to high-batch *decode* workers through
a ``POST /kv/ingest`` handoff whose response IS the continuation token
stream. A *colocated* replica (the default) interleaves both phases on
one chip exactly as before.

This module holds the pieces shared across the serve stack:

- **Roles.** ``resolve_role`` maps the ``--role`` flag / ``SKYTPU_ROLE``
  launch env to one of :data:`ROLES`. The controller assigns roles per
  replica from the service spec's ``disaggregation:`` block
  (``serve/placement.py::role_for_new_replica``) and exports them via
  the launch env, the same contract as the adaptive-TP plan.
- **Handoff targets.** A prefill worker sends each finished prefill to
  the decode worker named by the LB's ``X-Handoff-Target`` header (the
  phase-aware routing policy picks it by live KV-pool headroom), or —
  absent an LB — to the best of its static ``--handoff-targets`` /
  ``SKYTPU_HANDOFF_TARGETS`` peers, ranked by the same
  ``/metrics?format=json`` headroom probe. No target ⇒ the request
  simply decodes locally (colocated fallback).
- **Telemetry.** The stable-schema disagg series, registered up front
  so every label renders as zero from the first scrape:
  ``skytpu_kv_transfer_bytes_total{direction}``,
  ``skytpu_kv_transfer_seconds``,
  ``skytpu_disagg_handoff_total{outcome}``, and
  ``skytpu_replica_role{role}`` (1 on the active role, 0 elsewhere).
"""
from __future__ import annotations

import json
import os
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

from skypilot_tpu import telemetry
from skypilot_tpu import tpu_logging

logger = tpu_logging.init_logger(__name__)

ROLES: Tuple[str, ...] = ('colocated', 'prefill', 'decode')
ROLE_ENV = 'SKYTPU_ROLE'
TARGETS_ENV = 'SKYTPU_HANDOFF_TARGETS'

# Stable label set of skytpu_disagg_handoff_total{outcome}. Prefill
# side: sent (ingest accepted), completed (continuation relayed to the
# client's end), failed (target unreachable / stream broke),
# fallback_local (no target or refused — decoded locally). Decode
# side: ingested (seated), rejected (malformed/mismatched — HTTP 400),
# no_capacity (retryable refusal — HTTP 503).
HANDOFF_OUTCOMES: Tuple[str, ...] = (
    'sent', 'completed', 'failed', 'fallback_local',
    'ingested', 'rejected', 'no_capacity')

KV_TRANSFER_DIRECTIONS: Tuple[str, ...] = ('export', 'ingest')


def resolve_role(role: Optional[str]) -> str:
    """Effective replica role: explicit argument wins, then the
    ``SKYTPU_ROLE`` launch env (the controller's disaggregation plan),
    else ``colocated``. Unknown values raise ``ValueError``."""
    if role in (None, ''):
        role = os.environ.get(ROLE_ENV) or 'colocated'
    if role not in ROLES:
        raise ValueError(f'unknown replica role {role!r}; supported: '
                         f'{", ".join(ROLES)}')
    return role


def static_targets(targets: Optional[Sequence[str]] = None) -> List[str]:
    """Normalized static handoff-target URLs: the explicit list, else
    the comma-separated ``SKYTPU_HANDOFF_TARGETS`` env."""
    if targets is None:
        raw = os.environ.get(TARGETS_ENV, '')
        targets = [t for t in raw.split(',') if t.strip()]
    return [t.strip().rstrip('/') for t in targets if t.strip()]


def probe_headroom(url: str, timeout: float = 0.5
                   ) -> Optional[Dict[str, object]]:
    """One replica's live ``/metrics?format=json`` disagg view:
    ``{'role', 'kv_free', 'queue_tokens'}`` — or None when the probe
    fails (the replica is dead or not a model server)."""
    try:
        with urllib.request.urlopen(f'{url}/metrics?format=json',
                                    timeout=timeout) as resp:
            payload = json.loads(resp.read())
        return {
            'role': (payload.get('disagg') or {}).get('role'),
            'kv_free': int(payload.get('kv_pool_tokens_free', 0)),
            'queue_tokens': int(payload.get('queue_tokens_total', 0)),
        }
    except Exception as e:  # pylint: disable=broad-except
        logger.debug(f'handoff headroom probe failed for {url}: '
                     f'{type(e).__name__}: {e}')
        return None


def pick_target(header_value: Optional[str],
                targets: Sequence[str]) -> Optional[str]:
    """The decode worker one handoff should go to: the router's
    ``X-Handoff-Target`` header wins (the phase-aware LB policy already
    ranked the decode pool by KV headroom); otherwise the static peer
    with the most free KV-pool tokens by live probe. None ⇒ decode
    locally."""
    if header_value:
        return header_value.strip().rstrip('/')
    best, best_free = None, -1
    for url in targets:
        info = probe_headroom(url)
        if info is None:
            continue
        free = int(info['kv_free'])
        if free > best_free:
            best, best_free = url, free
    return best


def register_metrics(role: Optional[str] = None) -> None:
    """Register the stable-schema disagg series (zeros from the first
    scrape). With ``role`` given, the ``skytpu_replica_role`` gauge is
    set to 1 on that role's series and 0 on the others."""
    reg = telemetry.get_registry()
    for direction in KV_TRANSFER_DIRECTIONS:
        reg.counter('skytpu_kv_transfer_bytes_total',
                    'KV handoff bytes moved on the wire',
                    direction=direction)
    reg.histogram('skytpu_kv_transfer_seconds',
                  'KV handoff transfer time: encode + POST to first '
                  'response byte (export) / receive + land (ingest)',
                  buckets=telemetry.registry.DEFAULT_SECONDS_BUCKETS)
    for outcome in HANDOFF_OUTCOMES:
        reg.counter('skytpu_disagg_handoff_total',
                    'Prefill→decode KV handoffs by outcome',
                    outcome=outcome)
    for r in ROLES:
        g = reg.gauge('skytpu_replica_role',
                      'Replica phase role (1 = active role)', role=r)
        if role is not None:
            g.set(1.0 if r == role else 0.0)


def handoff_counter(outcome: str) -> 'telemetry.Counter':
    return telemetry.get_registry().counter(
        'skytpu_disagg_handoff_total',
        'Prefill→decode KV handoffs by outcome', outcome=outcome)


def transfer_bytes_counter(direction: str) -> 'telemetry.Counter':
    return telemetry.get_registry().counter(
        'skytpu_kv_transfer_bytes_total',
        'KV handoff bytes moved on the wire', direction=direction)


def transfer_seconds() -> 'telemetry.Histogram':
    return telemetry.get_registry().histogram(
        'skytpu_kv_transfer_seconds',
        'KV handoff transfer time: encode + POST to first '
        'response byte (export) / receive + land (ingest)',
        buckets=telemetry.registry.DEFAULT_SECONDS_BUCKETS)


def json_block(role: str) -> Dict[str, object]:
    """The stable-schema ``disagg`` block of ``/metrics?format=json``:
    every key always present and numeric (zeros when the feature is
    idle), so scrapers and the phase-aware LB policy see one schema
    from the first request."""
    reg = telemetry.get_registry()

    def count(name: str, **labels: str) -> int:
        m = reg.get(name, **labels)
        return int(m.value) if m is not None else 0

    return {
        'role': role,
        'handoffs': {o: count('skytpu_disagg_handoff_total', outcome=o)
                     for o in HANDOFF_OUTCOMES},
        'kv_transfer_bytes': {
            d: count('skytpu_kv_transfer_bytes_total', direction=d)
            for d in KV_TRANSFER_DIRECTIONS},
    }
