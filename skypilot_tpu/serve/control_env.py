"""ControlPlaneEnv: the simulator-or-live seam of the serve control
plane.

Every control-plane policy object (autoscalers, forecaster, placement,
LB policies) is already pure and clock-injectable (graftcheck GC115).
The *state machines* around them — the replica manager's launch /
probe / drain / checkpoint / warmup / backfill flows and the
controller tick — were not: they read the wall clock, slept, spawned
threads, spoke HTTP to replica model servers and drove real cluster
launches inline. This module is the one-time refactor ROADMAP item 5
names as the unlock: the manager and controller take every one of
those effects through a :class:`ControlPlaneEnv`, so the SAME
unmodified state machines run either

- **live** (:class:`LiveControlPlaneEnv`, the default — byte-for-byte
  the calls the manager made before this refactor), or
- **simulated** (``serve/sim/``'s ``SimControlPlaneEnv``): a virtual
  clock, an event heap, synthetic replicas with calibrated service
  curves, and deterministic seeded fault storms — 1000 replicas and
  millions of requests in seconds of wall time.

The seam is deliberately *effect-shaped*, not mock-shaped: methods
are the irreducible outside-world touches (time, sleep, spawn, HTTP
round-trips, cluster lifecycle, row persistence, fault-injector
resolution), so the manager's logic — ordering, locking, status
transitions, dedupe, backoff — is identical in both worlds and a sim
regression is evidence about production behavior.
"""
from __future__ import annotations

import json
import random
import threading
import time
import typing
from typing import Any, Callable, Dict, List, Optional, Sequence

if typing.TYPE_CHECKING:
    from skypilot_tpu.serve import faults as faults_lib
    from skypilot_tpu.task import Task


class ControlPlaneEnv:
    """Abstract effect surface of the serve control plane. Subclasses
    implement the actual I/O; the manager/controller never touch the
    wall clock, a socket, or a cluster API directly."""

    name = 'abstract'

    # ---------------------------------------------------------------- time
    def time(self) -> float:
        """Wall-clock seconds (virtual in sim). The autoscaler /
        forecaster clocks are wired to this, so scaling decisions and
        replica bookkeeping share one time axis."""
        raise NotImplementedError

    def monotonic(self) -> float:
        """Monotonic seconds for durations (same axis as :meth:`time`
        in sim — the virtual clock never steps backwards)."""
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError

    # --------------------------------------------------------- concurrency
    def spawn(self, fn: Callable[..., None], *args: Any) -> None:
        """Run ``fn(*args)`` as a background task (a daemon thread
        live; a virtual-time logical thread in sim)."""
        raise NotImplementedError

    def run_parallel(self, fns: Sequence[Callable[[], None]]) -> None:
        """Run every fn and return once ALL have finished
        (terminate_all's fan-out teardown)."""
        raise NotImplementedError

    def rng(self) -> random.Random:
        """RNG for jitter (launch backoff). Live: OS-seeded; sim: the
        scenario seed, so backoff jitter replays deterministically."""
        return random.Random()

    # ---------------------------------------------------------------- HTTP
    def http_json(self, url: str, payload: Optional[Dict[str, Any]] = None,
                  timeout: float = 10.0) -> Any:
        """One JSON round-trip against a replica model server: GET when
        ``payload`` is None, else POST. Raises on transport errors and
        non-2xx, exactly like ``urllib`` — the manager's error handling
        is part of the state machine under test."""
        raise NotImplementedError

    def http_post_bytes(self, url: str, data: bytes,
                        content_type: str = 'application/octet-stream',
                        timeout: float = 30.0) -> bytes:
        """POST raw bytes, return the raw response body (checkpoint
        fetch / warmup push)."""
        raise NotImplementedError

    def probe_http(self, url: str, post_data: Optional[Dict[str, Any]],
                   timeout: float) -> bool:
        """One readiness probe: True iff the endpoint answered 2xx."""
        raise NotImplementedError

    # ----------------------------------------------------------- clusters
    def launch_cluster(self, task: 'Task', cluster_name: str) -> None:
        """Provision the replica's cluster (blocking; raises on
        failure). In sim this burns the scenario's provision latency
        on the virtual clock and registers a synthetic replica."""
        raise NotImplementedError

    def cluster_head_ip(self, cluster_name: str) -> Optional[str]:
        """Head IP of a launched cluster (None: launch raced a
        teardown and the handle is already gone)."""
        raise NotImplementedError

    def down_cluster(self, cluster_name: str) -> None:
        """Tear the cluster down (raises ClusterDoesNotExist when it
        is already gone — callers treat that as success)."""
        raise NotImplementedError

    def cluster_gone(self, cluster_name: str) -> bool:
        """Preemption ground truth: True when the cluster no longer
        exists or is not UP; False on a transient status-refresh
        failure (keep probing)."""
        raise NotImplementedError

    # -------------------------------------------------------- persistence
    def persist_replica(self, service_name: str, replica_id: int,
                        cluster_name: str, status: Any,
                        url: Optional[str], version: int, is_spot: bool,
                        port: int) -> None:
        """Write the replica row (sqlite live; a world-local table in
        sim — a simulated fleet must never touch the operator's serve
        DB, but a simulated controller RESTART must still find rows to
        reconcile against)."""
        raise NotImplementedError

    def remove_replica(self, service_name: str, replica_id: int) -> None:
        raise NotImplementedError

    def load_replica_rows(self, service_name: str) -> List[Dict[str, Any]]:
        """Every persisted replica row (reconciliation input after a
        controller restart), sorted by replica id."""
        raise NotImplementedError

    # ----------------------------------------------------------- journal
    # The WAL-style lifecycle journal (serve_state.lifecycle_ops live;
    # a world-local list in sim): every multi-step op is journaled
    # BEFORE it starts and finished when acked, so a controller crash
    # at any point leaves a pending row the restart replays.
    def journal_op_start(self, service_name: str, kind: str,
                         replica_id: int, gang_id: Optional[str],
                         payload: Optional[Dict[str, Any]] = None,
                         deadline_at: Optional[float] = None) -> int:
        raise NotImplementedError

    def journal_op_finish(self, service_name: str, op_id: int) -> None:
        raise NotImplementedError

    def pending_ops(self, service_name: str) -> List[Dict[str, Any]]:
        raise NotImplementedError

    # ------------------------------------------------------------- notes
    # Durable controller facts that are not rows or ops: checkpoint
    # dedupe keys, learned canary digests, autoscaler/forecaster
    # snapshots. JSON values.
    def put_note(self, service_name: str, key: str, value: Any) -> None:
        raise NotImplementedError

    def del_note(self, service_name: str, key: str) -> None:
        raise NotImplementedError

    def get_notes(self, service_name: str) -> Dict[str, Any]:
        raise NotImplementedError

    # -------------------------------------------------------------- faults
    def fault_injector(self) -> Optional['faults_lib.FaultInjector']:
        """The deterministic fault injector components resolve once at
        construction (None = hooks cost one attribute check). Live:
        SKYTPU_FAULT_SPEC; sim: the scenario's injector."""
        raise NotImplementedError


class LiveControlPlaneEnv(ControlPlaneEnv):
    """The production environment: exactly the calls
    ``replica_managers.py`` made before the env refactor, verbatim."""

    name = 'live'

    # ---------------------------------------------------------------- time
    def time(self) -> float:
        return time.time()

    def monotonic(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)

    # --------------------------------------------------------- concurrency
    def spawn(self, fn: Callable[..., None], *args: Any) -> None:
        threading.Thread(target=fn, args=args, daemon=True).start()

    def run_parallel(self, fns: Sequence[Callable[[], None]]) -> None:
        threads = [threading.Thread(target=fn) for fn in fns]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    # ---------------------------------------------------------------- HTTP
    def http_json(self, url: str, payload: Optional[Dict[str, Any]] = None,
                  timeout: float = 10.0) -> Any:
        # Body-carrying control-plane hops ride the trace-propagating
        # helper (graftcheck GC123); plain GETs read through it too so
        # the control plane has ONE outbound HTTP seam.
        from skypilot_tpu.serve import wire
        if payload is None:
            return wire.get_json(url, timeout=timeout)
        return wire.post_json(url, payload, timeout=timeout)

    def http_post_bytes(self, url: str, data: bytes,
                        content_type: str = 'application/octet-stream',
                        timeout: float = 30.0) -> bytes:
        from skypilot_tpu.serve import wire
        with wire.urlopen(url, data=data,
                          headers={'Content-Type': content_type},
                          timeout=timeout) as resp:
            return resp.read()

    def probe_http(self, url: str, post_data: Optional[Dict[str, Any]],
                   timeout: float) -> bool:
        import urllib.request
        if post_data is not None:
            req = urllib.request.Request(
                url, data=json.dumps(post_data).encode(),
                headers={'Content-Type': 'application/json'})
        else:
            req = urllib.request.Request(url)
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return 200 <= r.status < 300

    # ----------------------------------------------------------- clusters
    def launch_cluster(self, task: 'Task', cluster_name: str) -> None:
        from skypilot_tpu import execution
        execution.launch(task, cluster_name=cluster_name,
                         detach_run=True, retry_until_up=False)

    def cluster_head_ip(self, cluster_name: str) -> Optional[str]:
        from skypilot_tpu import global_state
        handle = global_state.get_handle_from_cluster_name(cluster_name)
        if handle is None:
            return None
        return handle.cluster_info.hosts[0].internal_ip

    def down_cluster(self, cluster_name: str) -> None:
        from skypilot_tpu import core
        core.down(cluster_name)

    def cluster_gone(self, cluster_name: str) -> bool:
        from skypilot_tpu import global_state
        from skypilot_tpu import tpu_logging
        record = global_state.get_cluster_from_name(cluster_name)
        if record is None:
            return True
        from skypilot_tpu.backend import backend_utils
        try:
            rec, _ = backend_utils.refresh_cluster_status(cluster_name)
        except Exception as e:  # pylint: disable=broad-except
            tpu_logging.init_logger(__name__).debug(
                f'Status refresh of {cluster_name} failed (transient; '
                f'keep probing): {type(e).__name__}: {e}')
            return False
        from skypilot_tpu import global_state as gs
        return rec is None or rec['status'] != gs.ClusterStatus.UP

    # -------------------------------------------------------- persistence
    def persist_replica(self, service_name: str, replica_id: int,
                        cluster_name: str, status: Any,
                        url: Optional[str], version: int, is_spot: bool,
                        port: int) -> None:
        from skypilot_tpu.serve import serve_state
        serve_state.add_or_update_replica(
            service_name, replica_id, cluster_name, status, url,
            version, is_spot, port=port)

    def remove_replica(self, service_name: str, replica_id: int) -> None:
        from skypilot_tpu.serve import serve_state
        serve_state.remove_replica(service_name, replica_id)

    def load_replica_rows(self, service_name: str
                          ) -> List[Dict[str, Any]]:
        from skypilot_tpu.serve import serve_state
        return serve_state.get_replicas(service_name)

    # ----------------------------------------------------------- journal
    def journal_op_start(self, service_name: str, kind: str,
                         replica_id: int, gang_id: Optional[str],
                         payload: Optional[Dict[str, Any]] = None,
                         deadline_at: Optional[float] = None) -> int:
        from skypilot_tpu.serve import serve_state
        return serve_state.journal_op_start(
            service_name, kind, replica_id, gang_id, payload,
            deadline_at=deadline_at)

    def journal_op_finish(self, service_name: str, op_id: int) -> None:
        from skypilot_tpu.serve import serve_state
        serve_state.journal_op_finish(service_name, op_id)

    def pending_ops(self, service_name: str) -> List[Dict[str, Any]]:
        from skypilot_tpu.serve import serve_state
        return serve_state.pending_ops(service_name)

    # ------------------------------------------------------------- notes
    def put_note(self, service_name: str, key: str, value: Any) -> None:
        from skypilot_tpu.serve import serve_state
        serve_state.put_note(service_name, key, value)

    def del_note(self, service_name: str, key: str) -> None:
        from skypilot_tpu.serve import serve_state
        serve_state.del_note(service_name, key)

    def get_notes(self, service_name: str) -> Dict[str, Any]:
        from skypilot_tpu.serve import serve_state
        return serve_state.get_notes(service_name)

    # -------------------------------------------------------------- faults
    def fault_injector(self) -> Optional['faults_lib.FaultInjector']:
        from skypilot_tpu.serve import faults as faults_lib
        return faults_lib.get_injector()


_DEFAULT_ENV: Optional[LiveControlPlaneEnv] = None
_DEFAULT_ENV_LOCK = threading.Lock()


def default_env() -> LiveControlPlaneEnv:
    """The shared live env (stateless; one instance is plenty)."""
    global _DEFAULT_ENV
    with _DEFAULT_ENV_LOCK:
        if _DEFAULT_ENV is None:
            _DEFAULT_ENV = LiveControlPlaneEnv()
        return _DEFAULT_ENV


def resolve(env: Optional[ControlPlaneEnv]) -> ControlPlaneEnv:
    return env if env is not None else default_env()
