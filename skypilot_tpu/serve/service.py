"""Per-service process: runs controller + load balancer for one service.

Role of reference ``sky/serve/service.py`` (``_start`` ``:133`` forks
``run_controller`` + ``run_load_balancer``): submitted as an ordinary
agent job named ``service-<name>`` on the serve-controller cluster, so it
gets logs/liveness from the agent runtime for free (SURVEY key idea #2).
"""
from __future__ import annotations

import argparse
import sys
import time
import traceback

from skypilot_tpu import tpu_logging
from skypilot_tpu.serve import controller as controller_lib
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.service_spec import SkyServiceSpec

logger = tpu_logging.init_logger(__name__)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--service-name', required=True)
    args = parser.parse_args()

    record = serve_state.get_service(args.service_name)
    if record is None:
        print(f'service {args.service_name} not in serve state db',
              file=sys.stderr)
        sys.exit(1)
    task_config = record['task_config']
    spec = SkyServiceSpec.from_yaml_config(task_config['service'])

    try:
        # LB binds first: replica ports are allocated by the controller
        # loop, which must see the controller+LB ports as taken.
        lb = lb_lib.SkyServeLoadBalancer(
            controller_url=f'http://127.0.0.1:{record["controller_port"]}',
            port=record['lb_port'],
            policy_name=spec.load_balancing_policy,
            tls_certfile=spec.tls_certfile,
            tls_keyfile=spec.tls_keyfile)
        lb.start()
        # recover=True always: a FIRST boot reconciles an empty
        # journal to a no-op; a RESTART (controller crashed and the
        # agent relaunched the service job) adopts the orphaned fleet,
        # resumes interrupted drains at their remaining deadlines and
        # replays unacked teardowns instead of scaling to zero.
        controller = controller_lib.ServeController(
            args.service_name, spec, task_config,
            port=record['controller_port'],
            reserved_ports={record['controller_port'], record['lb_port']},
            recover=True)
        controller.start()
        serve_state.set_service_status(
            args.service_name, serve_state.ServiceStatus.NO_REPLICA)
    except Exception:  # pylint: disable=broad-except
        serve_state.set_service_status(
            args.service_name, serve_state.ServiceStatus.CONTROLLER_FAILED,
            failure_reason=traceback.format_exc())
        raise

    try:
        controller.wait()
    finally:
        lb.stop()
    # terminate() removed the service row; exiting 0 lets the agent mark
    # the service job SUCCEEDED.
    logger.info(f'Service {args.service_name} terminated.')


if __name__ == '__main__':
    main()
