"""Per-service controller: autoscaler loop + replica manager + a small
HTTP API the load balancer syncs against.

Role of reference ``sky/serve/controller.py`` (``SkyServeController``
``:36``, ``_run_autoscaler`` ``:64``): periodically evaluate the
autoscaler against current replica states and apply the scaling
decisions; expose ``/controller/load_balancer_sync`` so the LB can push
request timestamps and pull ready replica URLs (reference uses FastAPI;
stdlib http.server here — no extra deps on the controller cluster).
"""
from __future__ import annotations

import http.server
import json
import os
import threading
from typing import Any, Dict, List, Optional
import urllib.parse

from skypilot_tpu import tpu_logging
from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve import control_env
from skypilot_tpu.serve import replica_managers
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.service_spec import SkyServiceSpec
from skypilot_tpu.telemetry import fleet as fleet_lib

logger = tpu_logging.init_logger(__name__)


def _tick() -> float:
    return float(os.environ.get('SKYTPU_SERVE_TICK', '10'))


class ServeController:

    def __init__(self, service_name: str, spec: SkyServiceSpec,
                 task_config: Dict[str, Any], port: int,
                 reserved_ports: Optional[set] = None,
                 env: Optional[control_env.ControlPlaneEnv] = None,
                 recover: bool = False):
        self.service_name = service_name
        self.spec = spec
        self.port = port
        # The simulator-or-live seam (control_env.py): the manager's
        # state machines and the autoscaler/forecaster clocks all draw
        # from one environment, so a simulated controller tick is the
        # SAME code on a virtual time axis.
        self._env = control_env.resolve(env)
        self.replica_manager = replica_managers.ReplicaManager(
            service_name, spec, task_config,
            reserved_ports=(reserved_ports or set()) | {port},
            env=self._env)
        self.autoscaler = autoscalers.Autoscaler.from_spec(
            spec, clock=self._env.time)
        # Fleet telemetry plane: merged per-replica metrics, assembled
        # cross-process traces, and SLO burn-rate accounting — fed on
        # the probe path (replica scrapes) and the LB sync body, and
        # clocked through the env seam so the simulator drives the
        # identical aggregation code on its virtual clock.
        self.fleet = fleet_lib.FleetAggregator(
            clock=self._env.time,
            slos=fleet_lib.slos_from_config(
                getattr(spec, 'slos', None)))
        self.replica_manager.set_telemetry_sink(self.fleet.ingest)
        self._stop = threading.Event()      # stops the autoscaler loop
        self._done = threading.Event()      # teardown fully finished
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        self._threads: List[threading.Thread] = []
        # Crash-safety telemetry + the controller's own fault hook
        # (site 'controller_tick', kind controller_crash — the loop
        # dies WITHOUT teardown, exactly like a real process crash).
        from skypilot_tpu import telemetry
        reg = telemetry.get_registry()
        self._m_restarts = reg.counter(
            'skytpu_controller_restarts_total',
            'Controller boots that found persisted lifecycle state to '
            'reconcile (restarts; a first boot over an empty journal '
            'does not count)')
        self._h_reconcile = reg.histogram(
            'skytpu_reconcile_seconds',
            'Restart reconciliation wall time: journal replay + '
            'adoption probes to manager rebuilt',
            buckets=telemetry.registry.DEFAULT_SECONDS_BUCKETS)
        self._faults = self._env.fault_injector()
        # What the last recovery boot did per persisted replica
        # (outcome -> count); empty on a fresh boot.
        self.last_reconcile: Dict[str, int] = {}
        # Horizontal LB tier membership: every LB registers its
        # (lb_id, url) on each sync; the pruned live set ships back as
        # ``lb_peers`` so all LBs agree on the consistent-hash ring.
        # Deliberately EPHEMERAL (never journaled): membership is
        # liveness — a restarted controller relearns it within one
        # sync period, exactly like the replica probe state.
        self._lb_lock = threading.Lock()
        self._lb_registry: Dict[str, Any] = {}
        if recover:
            self._recover()

    # ----------------------------------------------------- LB tier feed
    def note_lb_sync(self, lb_id: Optional[str],
                     lb_url: Optional[str]) -> Dict[str, str]:
        """Register the syncing LB (if it identified itself) and
        return the live peer map (lb_id -> url). Peers that missed
        ``SKYTPU_LB_PEER_TTL`` (default 15 s) of syncs age out — a
        crashed LB leaves the ring within one TTL and session-key
        ownership converges on the survivors."""
        now = self._env.monotonic()
        ttl = float(os.environ.get('SKYTPU_LB_PEER_TTL', '15'))
        with self._lb_lock:
            registry = dict(self._lb_registry)
            if lb_id:
                registry[str(lb_id)] = (str(lb_url or ''), now)
            self._lb_registry = {
                k: v for k, v in registry.items()
                if now - v[1] < ttl}
            return {k: v[0] for k, v in self._lb_registry.items()}

    # ----------------------------------------------------------- recovery
    def _recover(self) -> None:
        """Recovery boot: restore the autoscaler/forecaster snapshot,
        then rebuild the replica manager from the journal + live
        probes (``ReplicaManager.reconcile``). Idempotent over an
        empty DB — ``serve/service.py`` always boots with
        ``recover=True`` and a first boot reconciles to a no-op."""
        t0 = self._env.monotonic()
        restored = self._restore_autoscaler_state()
        stats = self.replica_manager.reconcile()
        self.last_reconcile = stats
        if restored or any(stats.values()):
            self._m_restarts.inc()
            self._h_reconcile.observe(
                max(0.0, self._env.monotonic() - t0))
            logger.info(
                f'Controller for {self.service_name} restarted: '
                f'reconciled in {self._env.monotonic() - t0:.3f}s '
                f'({stats}).')

    def _persist_autoscaler_state(self) -> None:
        """Journaled persist helper (graftcheck GC120): snapshot the
        autoscaler target + forecaster rings + learned provision lead
        each tick, so a restart never scales the fleet toward
        min_replicas while live traffic needs it."""
        try:
            self._env.put_note(self.service_name, 'autoscaler_state',
                               self.autoscaler.export_state())
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'autoscaler snapshot persist failed: '
                         f'{type(e).__name__}: {e}')

    def _restore_autoscaler_state(self) -> bool:
        try:
            notes = self._env.get_notes(self.service_name)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'autoscaler snapshot restore failed: '
                           f'{type(e).__name__}: {e}')
            return False
        state = notes.get('autoscaler_state')
        if not isinstance(state, dict):
            return False
        self.autoscaler.restore_state(state)
        return True

    # ---------------------------------------------------------- scaling
    def _replica_views(self) -> List[autoscalers.ReplicaView]:
        views = []
        for info in self.replica_manager.replicas():
            if info.gang_rank > 0:
                # A gang is ONE unit of serving capacity: rank 0
                # represents it to the autoscaler (counting followers
                # would make a 4-host gang look like 4 replicas and
                # freeze scale-up at 1/4 the intended fleet).
                continue
            views.append(autoscalers.ReplicaView(
                replica_id=info.replica_id,
                is_ready=(info.status == serve_state.ReplicaStatus.READY),
                is_spot=info.is_spot,
                is_terminal=info.status.is_terminal(),
                is_draining=(info.status ==
                             serve_state.ReplicaStatus.DRAINING),
                version=info.version))
        return views

    def _autoscaler_step(self) -> None:
        # Observed provision latencies (scale-up issued -> READY) feed
        # the forecast autoscaler's pre-scaling lead time; the base
        # autoscalers ignore them.
        for obs in self.replica_manager.pop_provision_observations():
            self.autoscaler.note_provision_seconds(obs)
        decisions = self.autoscaler.evaluate_scaling(self._replica_views())
        for d in decisions:
            if d.operator == autoscalers.DecisionOperator.SCALE_UP:
                if self.replica_manager.in_launch_backoff():
                    continue      # recent launch failure; retry later
                self.replica_manager.scale_up(
                    use_spot=bool(d.target.get('use_spot')))
            else:
                # Scale-down routes through graceful drain: the replica
                # leaves LB rotation, finishes its in-flight requests
                # under the drain deadline, THEN tears down — no work
                # is killed mid-decode. drain() is idempotent across
                # controller ticks and falls back to a direct teardown
                # for replicas that never served.
                self.replica_manager.drain(d.target['replica_id'])
        self._drain_old_versions()

    def _drain_old_versions(self) -> None:
        """Blue-green completion (reference ``replica_managers.py:1172``):
        once enough latest-version replicas are READY, old-version
        replicas are terminated."""
        latest = self.replica_manager.version
        infos = self.replica_manager.replicas()
        ready_new = sum(
            1 for i in infos if i.version == latest
            and i.status == serve_state.ReplicaStatus.READY)
        if ready_new < self.autoscaler.target_num_replicas:
            return
        for info in infos:
            if info.gang_rank > 0:
                continue      # gangs drain through their rank 0
            if info.version < latest and not info.status.is_terminal() \
                    and info.status not in (
                        serve_state.ReplicaStatus.SHUTTING_DOWN,
                        serve_state.ReplicaStatus.DRAINING):
                logger.info(f'Draining replica {info.replica_id} '
                            f'(v{info.version} < v{latest}).')
                self.replica_manager.drain(info.replica_id)

    def apply_update(self) -> None:
        """Reload spec/task from serve state after an `update` RPC bumped
        the version; new replicas launch with the new task."""
        record = serve_state.get_service(self.service_name)
        if record is None:
            return
        version = record['version']
        if version == self.replica_manager.version:
            return
        spec = SkyServiceSpec.from_yaml_config(
            record['task_config']['service'])
        self.spec = spec
        self.replica_manager.update_version(spec, record['task_config'],
                                            version)
        self.autoscaler.update_spec(spec, version)
        self.fleet.set_slos(fleet_lib.slos_from_config(
            getattr(spec, 'slos', None)))
        logger.info(f'Service {self.service_name} updated to v{version}.')

    def _update_service_status(self) -> None:
        record = serve_state.get_service(self.service_name)
        if record is None or record['status'] in (
                serve_state.ServiceStatus.SHUTTING_DOWN,):
            return
        infos = self.replica_manager.replicas()
        n_ready = sum(1 for i in infos
                      if i.status == serve_state.ReplicaStatus.READY)
        if n_ready > 0:
            status = serve_state.ServiceStatus.READY
        elif infos:
            status = serve_state.ServiceStatus.REPLICA_INIT
        else:
            status = serve_state.ServiceStatus.NO_REPLICA
        if status != record['status']:
            serve_state.set_service_status(self.service_name, status)

    def tick(self, *, sync_state: bool = True) -> None:
        """One controller evaluation: reconcile version, probe every
        replica, evaluate + apply scaling, refresh the service row.
        The live loop calls this on a wall-clock cadence; the fleet
        simulator calls it on the virtual clock (``sync_state=False``
        skips the sqlite-backed version/status reconciliation — a
        simulated service has no DB row and must never touch the
        operator's serve state)."""
        if sync_state:
            # Version reconciliation every tick: the update RPC's
            # POST is only a nudge — if it was missed, the DB version
            # must not stay permanently ahead of the running service.
            self.apply_update()
        self.replica_manager.probe_all()
        self._autoscaler_step()
        # Snapshot the scaling brain through the env seam (a no-op DB
        # in sim is still the same code path): restarts restore it.
        self._persist_autoscaler_state()
        if sync_state:
            self._update_service_status()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                if self._faults is not None:
                    rule = self._faults.fire('controller_tick')
                    if rule is not None and \
                            rule.kind == 'controller_crash':
                        logger.error(
                            'injected controller_crash: the control '
                            'plane dies NOW without teardown '
                            '(replicas keep serving; the journal '
                            'stays for the next boot to reconcile)')
                        self.crash()
                        return
                self.tick()
            except Exception:  # pylint: disable=broad-except
                logger.exception('controller loop error')
            self._stop.wait(_tick())

    # ------------------------------------------------------------- HTTP
    def _make_handler(controller):  # noqa: N805
        class Handler(http.server.BaseHTTPRequestHandler):
            # Socket-op timeout (graftcheck GC107): a stalled LB/CLI
            # peer must not pin a controller thread forever.
            timeout = 60

            def log_message(self, *args):  # quiet
                del args

            def _json(self, code: int, payload: Dict[str, Any]) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                parsed = urllib.parse.urlparse(self.path)
                query = urllib.parse.parse_qs(parsed.query)
                if parsed.path == '/controller/ready':
                    self._json(200, {'ready': True})
                elif parsed.path == '/controller/status':
                    self._json(200, controller.status_payload())
                elif parsed.path == '/fleet/metrics':
                    if query.get('format', [''])[0] == 'json':
                        self._json(200, controller.fleet.render_json())
                        return
                    body = (controller.fleet.render_prometheus()
                            .encode())
                    self.send_response(200)
                    self.send_header(
                        'Content-Type',
                        'text/plain; version=0.0.4; charset=utf-8')
                    self.send_header('Content-Length', str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif parsed.path == '/fleet/traces':
                    self._json(200,
                               {'traces': controller.fleet.trace_ids()})
                elif parsed.path.startswith('/fleet/trace/'):
                    tid = parsed.path[len('/fleet/trace/'):]
                    if query.get('format', [''])[0] == 'chrome':
                        events = controller.fleet.chrome_events(tid)
                        if events is None:
                            self._json(404, {'error':
                                             f'trace {tid!r} unknown'})
                            return
                        self._json(200, {'traceEvents': events,
                                         'displayTimeUnit': 'ms'})
                        return
                    assembled = controller.fleet.assemble_trace(tid)
                    if assembled is None:
                        self._json(404,
                                   {'error': f'trace {tid!r} unknown'})
                        return
                    self._json(200, assembled)
                else:
                    self._json(404, {'error': f'no route {self.path}'})

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get('Content-Length', 0))
                try:
                    payload = json.loads(self.rfile.read(length) or b'{}')
                except json.JSONDecodeError:
                    self._json(400, {'error': 'bad json'})
                    return
                if self.path == '/controller/load_balancer_sync':
                    ts = payload.get('request_timestamps', [])
                    # Optional parallel SLO-tier tags (the LB reads
                    # X-SLO-Tier): the forecaster keeps per-tier
                    # arrival series next to the 'all' series.
                    controller.autoscaler.collect_request_information(
                        ts, payload.get('request_tiers'))
                    # The LB piggybacks its completed trace legs (and
                    # its clock, for skew accounting) on the sync it
                    # already makes.
                    tel = payload.get('telemetry')
                    if isinstance(tel, dict):
                        controller.fleet.ingest(
                            str(payload.get('lb_id') or 'lb'), tel)
                    self._json(200, {
                        # Per-tier SLO burn/attainment: LBs surface it
                        # next to their own health gauges.
                        'slo': controller.fleet.slo_status(),
                        'ready_replica_urls':
                            controller.replica_manager.ready_urls(),
                        # Retry-After hint for the LB's own 503 while
                        # no replica is READY, from live probe/launch
                        # backoff state.
                        'retry_after_s':
                            controller.replica_manager.retry_after_hint(),
                        # The (tp, dp) plan replicas of the current
                        # spec version run with — the LB's replica
                        # view carries it alongside the live
                        # per-replica mesh probes.
                        'replica_parallelism':
                            controller.parallelism_payload(),
                        # Disaggregation roles (url -> prefill/decode/
                        # colocated): the phase-aware LB policy's
                        # cold-probe fallback.
                        'replica_roles':
                            controller.replica_manager.replica_roles(),
                        # Gang health blocks (rank0 url -> gang view):
                        # the LB keeps follower addresses out of probe
                        # sweeps while accounting every rank's health.
                        'replica_gangs':
                            controller.replica_manager.replica_gangs(),
                        # Live LB-tier peers (lb_id -> url): every LB
                        # builds the same consistent-hash ring from
                        # this, so session-key ownership is agreed
                        # without LB-to-LB coordination.
                        'lb_peers': controller.note_lb_sync(
                            payload.get('lb_id'),
                            payload.get('lb_url')),
                    })
                elif self.path == '/controller/update':
                    try:
                        controller.apply_update()
                        self._json(200, {
                            'version': controller.replica_manager.version})
                    except Exception as e:  # pylint: disable=broad-except
                        self._json(400, {'error': f'{type(e).__name__}: '
                                                  f'{e}'})
                elif self.path == '/controller/terminate':
                    threading.Thread(target=controller.terminate,
                                     daemon=True).start()
                    self._json(200, {'terminating': True})
                else:
                    self._json(404, {'error': f'no route {self.path}'})

        return Handler

    def parallelism_payload(self) -> Dict[str, Any]:
        """The adaptive-TP plan as a wire dict (stable keys)."""
        plan = self.replica_manager.parallelism_plan()
        return {'tp': plan.tp, 'dp': plan.dp, 'chips': plan.chips,
                'reason': plan.reason,
                'policy': self.spec.parallelism_policy}

    def status_payload(self) -> Dict[str, Any]:
        par = self.parallelism_payload()
        return {
            'service_name': self.service_name,
            'target_num_replicas': self.autoscaler.target_num_replicas,
            'autoscaler': type(self.autoscaler).__name__,
            'replica_parallelism': par,
            'slo': self.fleet.slo_status(),
            'replicas': [{
                'replica_id': i.replica_id,
                'cluster_name': i.cluster_name,
                'status': i.status.value,
                'url': i.url,
                'version': i.version,
                'is_spot': i.is_spot,
                'role': i.role,
                'mesh': {'tp': par['tp'], 'dp': par['dp']},
                'gang_id': i.gang_id,
                'gang_rank': i.gang_rank,
                'gang_world': i.gang_world,
            } for i in self.replica_manager.replicas()],
        }

    # ---------------------------------------------------------- lifecycle
    def start(self) -> None:
        handler = self._make_handler()
        self._httpd = http.server.ThreadingHTTPServer(
            ('127.0.0.1', self.port), handler)
        t_http = threading.Thread(target=self._httpd.serve_forever,
                                  daemon=True)
        t_loop = threading.Thread(target=self._loop, daemon=True)
        t_http.start()
        t_loop.start()
        self._threads = [t_http, t_loop]
        logger.info(f'Serve controller for {self.service_name} on port '
                    f'{self.port}.')

    def crash(self) -> None:
        """Die like a crashed process (chaos tests / the bench's
        ``ctrl_recovery`` block): stop the loop and the HTTP API but
        tear NOTHING down and touch NO rows — replicas keep serving,
        the journal and notes stay exactly as written, and the next
        ``ServeController(..., recover=True)`` must reconcile it all
        back. The LB sees sync failures and enters its
        stale-while-revalidate mode."""
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
        self._done.set()

    def terminate(self) -> None:
        serve_state.set_service_status(
            self.service_name, serve_state.ServiceStatus.SHUTTING_DOWN)
        # Order matters: stop the autoscaler loop and refuse new launches
        # BEFORE tearing replicas down, or the loop relaunches replicas
        # that terminate_all never snapshotted (leaked clusters).
        self._stop.set()
        self.replica_manager.shutdown()
        self.replica_manager.terminate_all()
        if self._httpd is not None:
            self._httpd.shutdown()
        serve_state.remove_service(self.service_name)
        # Last: releases wait() — the service process must stay alive
        # until the teardown above completed (terminate() usually runs on
        # a daemon thread that dies with the process).
        self._done.set()

    def wait(self) -> None:
        # Event wait, not a sleep-poll loop (graftcheck GC112): blocks
        # until terminate() finishes the teardown.
        self._done.wait()
