"""Service spec: the ``service:`` section of a task YAML.

Role of reference ``SkyServiceSpec`` (``sky/serve/service_spec.py:18``):
readiness probe + replica policy (fixed count or QPS autoscaling with
optional spot/on-demand mix). TPU-first notes: replicas are whole TPU
slices, so scaling granularity is a slice; the replica port is where the
in-tree model server (``skypilot_tpu.serve.server``) listens.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.utils import schemas


@dataclasses.dataclass
class SkyServiceSpec:
    """Validated service section."""
    readiness_path: str
    initial_delay_seconds: float = 60.0
    readiness_timeout_seconds: float = 15.0
    post_data: Optional[Any] = None
    min_replicas: int = 1
    max_replicas: Optional[int] = None      # None => fixed at min_replicas
    target_qps_per_replica: Optional[float] = None
    upscale_delay_seconds: float = 300.0
    downscale_delay_seconds: float = 1200.0
    base_ondemand_fallback_replicas: int = 0
    dynamic_ondemand_fallback: bool = False
    replica_port: int = 8081
    load_balancing_policy: str = 'round_robin'
    # TLS for the public LB endpoint (reference carries tls on
    # SkyServiceSpec, ``sky/serve/service_spec.py:18``). Paths are
    # resolved on the controller cluster.
    tls_certfile: Optional[str] = None
    tls_keyfile: Optional[str] = None
    # Multi-chip replica parallelism (``parallelism:`` block).
    # 'adaptive' picks (tp, dp) per model size and SLO tier
    # (serve/placement.py — Nitsum-style: latency tier maxes tp for
    # TPOT, throughput tier takes the smallest fitting tp and spends
    # the rest on dp); 'fixed' pins the explicit tp/dp below. The plan
    # reaches replicas as SKYTPU_TP/SKYTPU_DP launch env.
    parallelism_policy: str = 'adaptive'
    chips_per_replica: int = 1
    slo_tier: str = 'latency'
    parallelism_model: Optional[str] = None
    parallelism_quantize: Optional[str] = None
    hbm_per_chip_gb: float = 16.0
    tp: Optional[int] = None
    dp: Optional[int] = None
    # Multi-host gang serving (``parallelism: hosts:``): each replica
    # is a *gang* of this many processes that launch, drain,
    # checkpoint, and die together (serve/gang.py). Rank 0 owns the
    # replica's one routable endpoint; the manager keys every
    # lifecycle action by gang ID. Reaches replicas as the
    # SKYTPU_COORDINATOR/SKYTPU_RANK/SKYTPU_WORLD/SKYTPU_GANG_ID
    # launch env.
    gang_hosts: int = 1
    # Disaggregated prefill/decode serving (``disaggregation:`` block):
    # dedicate this many replicas to each phase; the rest stay
    # colocated. Roles reach replicas as the SKYTPU_ROLE launch env
    # (serve/placement.py::role_for_new_replica assigns them in launch
    # order: prefill pool first, then decode, then colocated).
    disagg_prefill_replicas: int = 0
    disagg_decode_replicas: int = 0
    # Forecast-aware autoscaling (``forecast:`` under ``replica_policy``,
    # serve/forecaster.py): pre-scale ahead of traffic ramps by the
    # learned provisioning lead time instead of reacting after the ramp
    # lands. The knobs are the forecaster's bucket width, season length
    # (diurnal period — or minutes for tests/benches), and the default
    # look-ahead horizon.
    forecast_enabled: bool = False
    forecast_bucket_seconds: float = 10.0
    forecast_season_seconds: float = 600.0
    forecast_horizon_seconds: float = 120.0
    # Per-tier service-level objectives (``slos:`` block): tier name ->
    # {ttft_ms, tpot_ms, shed_rate, target}. The controller's
    # FleetAggregator evaluates 5m/1h burn rates against these
    # (telemetry/fleet.py) and surfaces them in controller status, the
    # LB sync response and ``GET /fleet/metrics``.
    slos: Optional[Dict[str, Dict[str, float]]] = None
    # Multi-tenant LoRA serving (``adapters:`` block): each replica
    # carries a device-resident adapter bank of ``adapter_slots`` rows
    # at rank ``adapter_rank``, lazily loaded by name from
    # ``adapter_dir`` (LRU evict under pressure). Reaches replicas as
    # --adapter-slots/--adapter-dir/--adapter-rank server flags.
    adapter_slots: int = 0
    adapter_dir: Optional[str] = None
    adapter_rank: int = 8

    @property
    def disagg_enabled(self) -> bool:
        return (self.disagg_prefill_replicas > 0
                or self.disagg_decode_replicas > 0)

    def __post_init__(self):
        if not self.readiness_path.startswith('/'):
            raise exceptions.InvalidServiceSpecError(
                f'readiness path must start with "/": {self.readiness_path}')
        if self.max_replicas is not None and \
                self.max_replicas < self.min_replicas:
            raise exceptions.InvalidServiceSpecError(
                f'max_replicas ({self.max_replicas}) < min_replicas '
                f'({self.min_replicas})')
        if self.max_replicas is not None and \
                self.max_replicas > self.min_replicas and \
                self.target_qps_per_replica is None:
            raise exceptions.InvalidServiceSpecError(
                'replica_policy with max_replicas > min_replicas requires '
                'target_qps_per_replica')
        if self.forecast_enabled and not self.autoscaling_enabled:
            raise exceptions.InvalidServiceSpecError(
                'forecast requires autoscaling (target_qps_per_replica '
                'with max_replicas > min_replicas, or no max_replicas '
                'at all = unbounded)')
        if self.forecast_enabled and \
                self.forecast_bucket_seconds <= 0:
            raise exceptions.InvalidServiceSpecError(
                'forecast bucket_seconds must be positive')
        if self.target_qps_per_replica is not None and \
                self.target_qps_per_replica <= 0:
            raise exceptions.InvalidServiceSpecError(
                'target_qps_per_replica must be positive')
        if self.disagg_prefill_replicas < 0 or \
                self.disagg_decode_replicas < 0:
            raise exceptions.InvalidServiceSpecError(
                'disaggregation replica counts must be >= 0')
        if self.disagg_enabled and (self.disagg_prefill_replicas == 0
                                    or self.disagg_decode_replicas == 0):
            raise exceptions.InvalidServiceSpecError(
                'disaggregation needs BOTH prefill_replicas and '
                'decode_replicas >= 1 (a lone pool has nobody to hand '
                'off to/from)')
        if self.adapter_slots < 0:
            raise exceptions.InvalidServiceSpecError(
                f'adapters.slots must be >= 0, got {self.adapter_slots}')
        if self.adapter_rank < 1:
            raise exceptions.InvalidServiceSpecError(
                f'adapters.rank must be >= 1, got {self.adapter_rank}')
        if self.gang_hosts < 1:
            raise exceptions.InvalidServiceSpecError(
                f'parallelism.hosts must be >= 1, got {self.gang_hosts}')
        if self.gang_hosts > 1 and self.disagg_enabled:
            raise exceptions.InvalidServiceSpecError(
                'multi-host gangs and disaggregated prefill/decode '
                'cannot combine (a KV handoff in/out of a gang would '
                'desync its follower ranks); drop one of '
                'parallelism.hosts / disaggregation')
        for tier, obj in (self.slos or {}).items():
            if not isinstance(obj, dict):
                raise exceptions.InvalidServiceSpecError(
                    f'slos.{tier} must be a mapping of objectives')
            target = obj.get('target', 0.99)
            if not 0.0 < float(target) < 1.0:
                raise exceptions.InvalidServiceSpecError(
                    f'slos.{tier}.target must be in (0, 1), got '
                    f'{target}')
            for key in ('ttft_ms', 'tpot_ms'):
                if obj.get(key) is not None and float(obj[key]) <= 0:
                    raise exceptions.InvalidServiceSpecError(
                        f'slos.{tier}.{key} must be positive')
            shed = obj.get('shed_rate')
            if shed is not None and not 0.0 < float(shed) <= 1.0:
                raise exceptions.InvalidServiceSpecError(
                    f'slos.{tier}.shed_rate must be in (0, 1], got '
                    f'{shed}')

    @property
    def autoscaling_enabled(self) -> bool:
        # max_replicas is None with a QPS target = UNBOUNDED
        # autoscaling (the autoscaler clamps only from below); a policy
        # without a QPS target stays fixed at min_replicas.
        return (self.target_qps_per_replica is not None
                and (self.max_replicas is None
                     or self.max_replicas > self.min_replicas))

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'SkyServiceSpec':
        schemas.validate(config, schemas.SERVICE_SCHEMA, 'service')
        probe = config['readiness_probe']
        if isinstance(probe, str):
            probe = {'path': probe}
        policy = config.get('replica_policy')
        fields: Dict[str, Any] = {
            'readiness_path': probe.get('path', '/'),
            'initial_delay_seconds': float(
                probe.get('initial_delay_seconds', 60.0)),
            'readiness_timeout_seconds': float(
                probe.get('timeout_seconds', 15.0)),
            'post_data': probe.get('post_data'),
            'replica_port': int(config.get('port', 8081)),
            'load_balancing_policy': config.get('load_balancing_policy',
                                                'round_robin'),
        }
        tls = config.get('tls')
        if tls:
            fields.update(tls_certfile=tls.get('certfile'),
                          tls_keyfile=tls.get('keyfile'))
        disagg = config.get('disaggregation')
        if disagg:
            fields.update(
                disagg_prefill_replicas=int(
                    disagg.get('prefill_replicas', 0)),
                disagg_decode_replicas=int(
                    disagg.get('decode_replicas', 0)))
        adapters = config.get('adapters')
        if adapters:
            fields.update(
                adapter_slots=int(adapters.get('slots', 0)),
                adapter_dir=adapters.get('dir'),
                adapter_rank=int(adapters.get('rank', 8)))
        slos = config.get('slos')
        if slos:
            fields['slos'] = {
                str(tier): dict(obj or {})
                for tier, obj in slos.items()}
        par = config.get('parallelism')
        if par:
            fields.update(
                parallelism_policy=par.get('policy', 'adaptive'),
                chips_per_replica=int(par.get('chips_per_replica', 1)),
                slo_tier=par.get('slo_tier', 'latency'),
                parallelism_model=par.get('model'),
                parallelism_quantize=par.get('quantize'),
                hbm_per_chip_gb=float(par.get('hbm_per_chip_gb', 16.0)),
                tp=par.get('tp'), dp=par.get('dp'),
                gang_hosts=int(par.get('hosts', 1)))
        if policy is not None and 'replicas' in config:
            raise exceptions.InvalidServiceSpecError(
                'Give either replicas (fixed) or replica_policy, not both.')
        if policy is not None:
            fields.update(
                min_replicas=int(policy.get('min_replicas', 1)),
                max_replicas=(int(policy['max_replicas'])
                              if 'max_replicas' in policy else None),
                target_qps_per_replica=policy.get('target_qps_per_replica'),
                upscale_delay_seconds=float(
                    policy.get('upscale_delay_seconds', 300.0)),
                downscale_delay_seconds=float(
                    policy.get('downscale_delay_seconds', 1200.0)),
                base_ondemand_fallback_replicas=int(
                    policy.get('base_ondemand_fallback_replicas', 0)),
                dynamic_ondemand_fallback=bool(
                    policy.get('dynamic_ondemand_fallback', False)),
            )
            forecast = policy.get('forecast')
            if forecast:
                if forecast is True:
                    forecast = {}
                fields.update(
                    forecast_enabled=True,
                    forecast_bucket_seconds=float(
                        forecast.get('bucket_seconds', 10.0)),
                    forecast_season_seconds=float(
                        forecast.get('season_seconds', 600.0)),
                    forecast_horizon_seconds=float(
                        forecast.get('horizon_seconds', 120.0)))
        else:
            fields['min_replicas'] = int(config.get('replicas', 1))
        return cls(**fields)

    def to_yaml_config(self) -> Dict[str, Any]:
        probe: Dict[str, Any] = {
            'path': self.readiness_path,
            'initial_delay_seconds': self.initial_delay_seconds,
            'timeout_seconds': self.readiness_timeout_seconds,
        }
        if self.post_data is not None:
            probe['post_data'] = self.post_data
        cfg: Dict[str, Any] = {
            'readiness_probe': probe,
            'port': self.replica_port,
            'load_balancing_policy': self.load_balancing_policy,
        }
        if self.tls_certfile and self.tls_keyfile:
            cfg['tls'] = {'certfile': self.tls_certfile,
                          'keyfile': self.tls_keyfile}
        if self.disagg_enabled:
            cfg['disaggregation'] = {
                'prefill_replicas': self.disagg_prefill_replicas,
                'decode_replicas': self.disagg_decode_replicas,
            }
        if self.gang_hosts > 1:
            cfg['parallelism'] = {'hosts': self.gang_hosts}
        if self.slos:
            cfg['slos'] = {tier: dict(obj)
                           for tier, obj in sorted(self.slos.items())}
        if self.adapter_slots > 0:
            adapters: Dict[str, Any] = {'slots': self.adapter_slots,
                                        'rank': self.adapter_rank}
            if self.adapter_dir:
                adapters['dir'] = self.adapter_dir
            cfg['adapters'] = adapters
        if self.autoscaling_enabled or self.target_qps_per_replica:
            policy: Dict[str, Any] = {
                'min_replicas': self.min_replicas,
                'target_qps_per_replica': self.target_qps_per_replica,
                'upscale_delay_seconds': self.upscale_delay_seconds,
                'downscale_delay_seconds': self.downscale_delay_seconds,
                'base_ondemand_fallback_replicas':
                    self.base_ondemand_fallback_replicas,
                'dynamic_ondemand_fallback': self.dynamic_ondemand_fallback,
            }
            # None = unbounded: the key is simply omitted (writing
            # min_replicas here used to silently freeze an unbounded
            # policy at its floor on round-trip).
            if self.max_replicas is not None:
                policy['max_replicas'] = self.max_replicas
            if self.forecast_enabled:
                policy['forecast'] = {
                    'bucket_seconds': self.forecast_bucket_seconds,
                    'season_seconds': self.forecast_season_seconds,
                    'horizon_seconds': self.forecast_horizon_seconds,
                }
            cfg['replica_policy'] = policy
        else:
            cfg['replicas'] = self.min_replicas
        return cfg
