"""Serve-side SQLite state: services + replicas (lives on the serve
controller cluster's head, like the managed-jobs DB).

Role of reference ``sky/serve/serve_state.py`` (557 LoC): one row per
service (spec, status, version, LB/controller ports) and one per replica
(cluster name, status, version). Written by the per-service controller
process, read by the serve RPC for client queries.

Crash-safety (round 15): the controller is itself a failure domain.
Beyond the bare service/replica rows, this module now persists the
**lifecycle journal** — a WAL-style ops table where every multi-step
replica operation (launch, drain with its absolute deadline, teardown)
is recorded *before* it starts and marked terminal when acked — plus a
small **controller notes** table (checkpoint-dedupe keys, learned
canary digests, autoscaler/forecaster state snapshots). A restarted
controller replays the journal against live probes to rebuild its
``ReplicaManager`` (adopt orphaned-but-healthy replicas, resume
interrupted drains at their *remaining* deadline, replay unacked
teardowns exactly once, kill zombie clusters leaked mid-launch) —
see ``ReplicaManager.reconcile`` and ``docs/robustness.md``.

Every connection opens in WAL journal mode with a busy timeout, so a
controller restart racing a straggler writer thread gets a bounded
retry instead of ``sqlite3.OperationalError: database is locked``.
"""
from __future__ import annotations

import enum
import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional

import filelock


class ServiceStatus(enum.Enum):
    """Reference ``sky/serve/serve_state.py`` ServiceStatus."""
    CONTROLLER_INIT = 'CONTROLLER_INIT'
    REPLICA_INIT = 'REPLICA_INIT'     # replicas launching, none ready yet
    READY = 'READY'                   # >=1 ready replica
    NO_REPLICA = 'NO_REPLICA'         # up, but zero replicas at the moment
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    FAILED = 'FAILED'
    CONTROLLER_FAILED = 'CONTROLLER_FAILED'

    def is_terminal(self) -> bool:
        return self in (ServiceStatus.FAILED,
                        ServiceStatus.CONTROLLER_FAILED)


class ReplicaStatus(enum.Enum):
    """Reference ``sky/serve/serve_state.py`` ReplicaStatus lifecycle."""
    PENDING = 'PENDING'               # scale-up decided, launch not started
    PROVISIONING = 'PROVISIONING'     # cluster launching
    STARTING = 'STARTING'             # cluster up, probe not yet passing
    READY = 'READY'
    NOT_READY = 'NOT_READY'           # probe failing; grace period
    # Graceful scale-down: out of LB rotation, in-flight requests run
    # to completion under a deadline, THEN the cluster tears down.
    DRAINING = 'DRAINING'
    # Byzantine containment: the replica answered the manager's
    # known-digest canary prompt WRONG (silent data corruption). Out
    # of ready_urls IMMEDIATELY (never routable again), then drained
    # and torn down. Terminal: the autoscaler replaces it like any
    # failed replica — a corrupt replica is never trusted again.
    QUARANTINED = 'QUARANTINED'
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    PREEMPTED = 'PREEMPTED'
    FAILED = 'FAILED'
    FAILED_PROBE = 'FAILED_PROBE'     # never became ready in time

    def is_terminal(self) -> bool:
        return self in (ReplicaStatus.PREEMPTED, ReplicaStatus.FAILED,
                        ReplicaStatus.FAILED_PROBE,
                        ReplicaStatus.QUARANTINED)


def serve_dir() -> str:
    d = os.environ.get('SKYTPU_SERVE_DIR',
                       os.path.expanduser('~/.skytpu_serve'))
    os.makedirs(d, exist_ok=True)
    return d


def _db_path() -> str:
    return os.path.join(serve_dir(), 'serve.db')


_LOCKS: Dict[str, filelock.FileLock] = {}


def db_lock() -> filelock.FileLock:
    """Per-path singleton: FileLock is only reentrant on the SAME
    instance, and callers nest (e.g. the up RPC wraps add_service)."""
    path = os.path.join(serve_dir(), '.serve.lock')
    if path not in _LOCKS:
        _LOCKS[path] = filelock.FileLock(path)
    return _LOCKS[path]


# Lifecycle-journal op kinds (``ReplicaManager`` writes these through
# its journaled persist helpers — graftcheck GC120 bans any other
# writer). 'launch' carries the full replica descriptor (cluster,
# role, gang, port) so a crash mid-launch leaves enough to kill the
# zombie; 'drain' carries the ABSOLUTE deadline so a restart resumes
# at the remaining budget; 'teardown' is replayed exactly once.
JOURNAL_OP_KINDS = ('launch', 'drain', 'teardown')
JOURNAL_PENDING = 'pending'
JOURNAL_DONE = 'done'

# SQLite busy timeout (ms) every connection opens with: a restarted
# controller racing a straggler writer retries for this long instead
# of failing with 'database is locked'.
BUSY_TIMEOUT_MS = 10_000


def _configure_conn(conn: sqlite3.Connection) -> None:
    """WAL + busy timeout on EVERY connection (readers included: WAL
    is a property of the database file, but the busy timeout is
    per-connection)."""
    conn.execute(f'PRAGMA busy_timeout={BUSY_TIMEOUT_MS}')
    try:
        conn.execute('PRAGMA journal_mode=WAL')
    except sqlite3.OperationalError:
        pass      # exotic filesystems without WAL: keep the default


def _conn() -> sqlite3.Connection:
    conn = sqlite3.connect(_db_path(), timeout=10)
    _configure_conn(conn)
    conn.execute("""\
        CREATE TABLE IF NOT EXISTS services (
            name TEXT PRIMARY KEY,
            status TEXT,
            version INTEGER DEFAULT 1,
            task_config TEXT,
            controller_port INTEGER,
            lb_port INTEGER,
            agent_job_id INTEGER,
            submitted_at REAL,
            failure_reason TEXT)""")
    conn.execute("""\
        CREATE TABLE IF NOT EXISTS replicas (
            service_name TEXT,
            replica_id INTEGER,
            cluster_name TEXT,
            status TEXT,
            url TEXT,
            version INTEGER,
            is_spot INTEGER DEFAULT 0,
            launched_at REAL,
            port INTEGER,
            PRIMARY KEY (service_name, replica_id))""")
    conn.execute("""\
        CREATE TABLE IF NOT EXISTS lifecycle_ops (
            op_id INTEGER PRIMARY KEY AUTOINCREMENT,
            service_name TEXT,
            kind TEXT,
            replica_id INTEGER,
            gang_id TEXT,
            payload TEXT,
            started_at REAL,
            deadline_at REAL,
            state TEXT DEFAULT 'pending',
            finished_at REAL)""")
    conn.execute("""\
        CREATE TABLE IF NOT EXISTS controller_notes (
            service_name TEXT,
            key TEXT,
            value TEXT,
            updated_at REAL,
            PRIMARY KEY (service_name, key))""")
    conn.commit()
    return conn


# ---------------------------------------------------------------- services
def allocated_ports() -> set:
    """Every controller/LB/replica port recorded for any service —
    allocated even if the owning process hasn't bound it yet (a bind
    test alone cannot see those)."""
    conn = _conn()
    rows = conn.execute(
        'SELECT controller_port, lb_port FROM services').fetchall()
    ports = {p for row in rows for p in row if p}
    rows = conn.execute('SELECT port FROM replicas').fetchall()
    ports |= {r[0] for r in rows if r[0]}
    return ports


def add_service(name: str, task_config: Dict[str, Any],
                controller_port: int, lb_port: int,
                agent_job_id: Optional[int] = None) -> bool:
    """False if a live service with this name already exists. A row in a
    terminal state (FAILED/CONTROLLER_FAILED — kept so status can show
    the failure reason) is replaced, so a fixed task can be re-upped
    under the same name without a manual `serve down` first."""
    with db_lock():
        conn = _conn()
        row = conn.execute('SELECT status FROM services WHERE name=?',
                           (name,)).fetchone()
        if row is not None:
            if not ServiceStatus(row[0]).is_terminal():
                return False
            conn.execute('DELETE FROM services WHERE name=?', (name,))
            conn.execute('DELETE FROM replicas WHERE service_name=?',
                         (name,))
            conn.execute(
                'DELETE FROM lifecycle_ops WHERE service_name=?',
                (name,))
            conn.execute(
                'DELETE FROM controller_notes WHERE service_name=?',
                (name,))
        conn.execute(
            'INSERT INTO services (name, status, version, task_config, '
            'controller_port, lb_port, agent_job_id, submitted_at) '
            'VALUES (?,?,?,?,?,?,?,?)',
            (name, ServiceStatus.CONTROLLER_INIT.value, 1,
             json.dumps(task_config), controller_port, lb_port,
             agent_job_id, time.time()))
        conn.commit()
        return True


def set_service_status(name: str, status: ServiceStatus,
                       failure_reason: Optional[str] = None) -> None:
    with db_lock():
        conn = _conn()
        if failure_reason is not None:
            conn.execute(
                'UPDATE services SET status=?, failure_reason=? '
                'WHERE name=?', (status.value, failure_reason, name))
        else:
            conn.execute('UPDATE services SET status=? WHERE name=?',
                         (status.value, name))
        conn.commit()


def set_service_version(name: str, version: int,
                        task_config: Dict[str, Any]) -> None:
    with db_lock():
        conn = _conn()
        conn.execute(
            'UPDATE services SET version=?, task_config=? WHERE name=?',
            (version, json.dumps(task_config), name))
        conn.commit()


def bump_service_version(name: str,
                         task_config: Dict[str, Any]) -> Optional[int]:
    """Atomically read-increment-write the service version (two
    concurrent updates must get distinct versions, not both N+1).
    Returns the new version, or None if the service doesn't exist."""
    with db_lock():
        conn = _conn()
        row = conn.execute('SELECT version FROM services WHERE name=?',
                           (name,)).fetchone()
        if row is None:
            return None
        version = row[0] + 1
        conn.execute(
            'UPDATE services SET version=?, task_config=? WHERE name=?',
            (version, json.dumps(task_config), name))
        conn.commit()
        return version


def set_service_agent_job(name: str, agent_job_id: int) -> None:
    with db_lock():
        conn = _conn()
        conn.execute('UPDATE services SET agent_job_id=? WHERE name=?',
                     (agent_job_id, name))
        conn.commit()


def remove_service(name: str) -> None:
    with db_lock():
        conn = _conn()
        conn.execute('DELETE FROM services WHERE name=?', (name,))
        conn.execute('DELETE FROM replicas WHERE service_name=?', (name,))
        conn.execute('DELETE FROM lifecycle_ops WHERE service_name=?',
                     (name,))
        conn.execute('DELETE FROM controller_notes WHERE service_name=?',
                     (name,))
        conn.commit()


def get_service(name: str) -> Optional[Dict[str, Any]]:
    conn = _conn()
    row = conn.execute(
        'SELECT name, status, version, task_config, controller_port, '
        'lb_port, agent_job_id, submitted_at, failure_reason '
        'FROM services WHERE name=?', (name,)).fetchone()
    return _service_row(row) if row else None


def get_services() -> List[Dict[str, Any]]:
    conn = _conn()
    rows = conn.execute(
        'SELECT name, status, version, task_config, controller_port, '
        'lb_port, agent_job_id, submitted_at, failure_reason '
        'FROM services ORDER BY name').fetchall()
    return [_service_row(r) for r in rows]


def _service_row(row) -> Dict[str, Any]:
    return {
        'name': row[0],
        'status': ServiceStatus(row[1]),
        'version': row[2],
        'task_config': json.loads(row[3]) if row[3] else None,
        'controller_port': row[4],
        'lb_port': row[5],
        'agent_job_id': row[6],
        'submitted_at': row[7],
        'failure_reason': row[8],
    }


# ---------------------------------------------------------------- replicas
def add_or_update_replica(service_name: str, replica_id: int,
                          cluster_name: str, status: ReplicaStatus,
                          url: Optional[str], version: int,
                          is_spot: bool = False,
                          port: Optional[int] = None) -> None:
    with db_lock():
        conn = _conn()
        conn.execute(
            'INSERT INTO replicas (service_name, replica_id, cluster_name, '
            'status, url, version, is_spot, launched_at, port) '
            'VALUES (?,?,?,?,?,?,?,?,?) '
            'ON CONFLICT (service_name, replica_id) DO UPDATE SET '
            'cluster_name=excluded.cluster_name, status=excluded.status, '
            'url=excluded.url, version=excluded.version, '
            'is_spot=excluded.is_spot, port=excluded.port',
            (service_name, replica_id, cluster_name, status.value, url,
             version, int(is_spot), time.time(), port))
        conn.commit()


def set_replica_status(service_name: str, replica_id: int,
                       status: ReplicaStatus) -> None:
    with db_lock():
        conn = _conn()
        conn.execute(
            'UPDATE replicas SET status=? WHERE service_name=? AND '
            'replica_id=?', (status.value, service_name, replica_id))
        conn.commit()


def remove_replica(service_name: str, replica_id: int) -> None:
    with db_lock():
        conn = _conn()
        conn.execute(
            'DELETE FROM replicas WHERE service_name=? AND replica_id=?',
            (service_name, replica_id))
        conn.commit()


def get_replicas(service_name: str) -> List[Dict[str, Any]]:
    conn = _conn()
    rows = conn.execute(
        'SELECT replica_id, cluster_name, status, url, version, is_spot, '
        'launched_at, port FROM replicas WHERE service_name=? '
        'ORDER BY replica_id', (service_name,)).fetchall()
    return [{
        'replica_id': r[0],
        'cluster_name': r[1],
        'status': ReplicaStatus(r[2]),
        'url': r[3],
        'version': r[4],
        'is_spot': bool(r[5]),
        'launched_at': r[6],
        'port': r[7],
    } for r in rows]


def max_replica_id(service_name: str) -> int:
    """The highest replica id this service ever persisted — rows AND
    journal ops both count, so a restarted manager's id counter can
    never collide with an adopted (or mid-teardown) replica. 0 when
    the service has no history."""
    conn = _conn()
    row = conn.execute(
        'SELECT MAX(replica_id) FROM replicas WHERE service_name=?',
        (service_name,)).fetchone()
    top = row[0] or 0
    row = conn.execute(
        'SELECT MAX(replica_id) FROM lifecycle_ops WHERE '
        'service_name=?', (service_name,)).fetchone()
    return int(max(top, row[0] or 0))


def replica_ports(service_name: str) -> set:
    """Ports recorded on this service's replica rows (a restarted
    manager reserves them so an adopted fleet never double-allocates
    a port a live replica is still bound to)."""
    conn = _conn()
    rows = conn.execute(
        'SELECT port FROM replicas WHERE service_name=?',
        (service_name,)).fetchall()
    return {r[0] for r in rows if r[0]}


# ----------------------------------------------------- lifecycle journal
def journal_op_start(service_name: str, kind: str, replica_id: int,
                     gang_id: Optional[str],
                     payload: Optional[Dict[str, Any]] = None,
                     deadline_at: Optional[float] = None,
                     now: Optional[float] = None) -> int:
    """Record a multi-step lifecycle op BEFORE it starts; returns the
    op id the caller marks done with :func:`journal_op_finish` once
    the op is acked. A crash between the two leaves a pending row the
    restarted controller replays (see ``ReplicaManager.reconcile``)."""
    if kind not in JOURNAL_OP_KINDS:
        raise ValueError(f'unknown journal op kind {kind!r}; '
                         f'supported: {JOURNAL_OP_KINDS}')
    with db_lock():
        conn = _conn()
        cur = conn.execute(
            'INSERT INTO lifecycle_ops (service_name, kind, replica_id,'
            ' gang_id, payload, started_at, deadline_at, state) '
            'VALUES (?,?,?,?,?,?,?,?)',
            (service_name, kind, replica_id, gang_id,
             json.dumps(payload or {}),
             time.time() if now is None else now, deadline_at,
             JOURNAL_PENDING))
        conn.commit()
        return int(cur.lastrowid)


def journal_op_finish(service_name: str, op_id: int,
                      now: Optional[float] = None) -> None:
    with db_lock():
        conn = _conn()
        conn.execute(
            'UPDATE lifecycle_ops SET state=?, finished_at=? '
            'WHERE service_name=? AND op_id=?',
            (JOURNAL_DONE, time.time() if now is None else now,
             service_name, op_id))
        # Finished ops are history, not recovery state: prune them so
        # a long-lived service holds only its pending (in-flight) ops
        # plus a bounded tail of recent completions for debugging.
        conn.execute(
            'DELETE FROM lifecycle_ops WHERE service_name=? AND '
            'state=? AND op_id NOT IN (SELECT op_id FROM lifecycle_ops'
            ' WHERE service_name=? AND state=? ORDER BY op_id DESC '
            'LIMIT 64)',
            (service_name, JOURNAL_DONE, service_name, JOURNAL_DONE))
        conn.commit()


def pending_ops(service_name: str) -> List[Dict[str, Any]]:
    """Every journaled op not yet marked done, oldest first — what a
    restarted controller must replay or resume."""
    conn = _conn()
    rows = conn.execute(
        'SELECT op_id, kind, replica_id, gang_id, payload, started_at,'
        ' deadline_at FROM lifecycle_ops WHERE service_name=? AND '
        'state=? ORDER BY op_id', (service_name,
                                   JOURNAL_PENDING)).fetchall()
    return [{
        'op_id': r[0],
        'kind': r[1],
        'replica_id': r[2],
        'gang_id': r[3],
        'payload': json.loads(r[4]) if r[4] else {},
        'started_at': r[5],
        'deadline_at': r[6],
    } for r in rows]


# ------------------------------------------------------ controller notes
def put_note(service_name: str, key: str, value: Any,
             now: Optional[float] = None) -> None:
    """Upsert one durable controller fact (JSON value): checkpoint
    dedupe keys, learned canary digests, autoscaler state snapshots."""
    with db_lock():
        conn = _conn()
        conn.execute(
            'INSERT INTO controller_notes (service_name, key, value, '
            'updated_at) VALUES (?,?,?,?) ON CONFLICT '
            '(service_name, key) DO UPDATE SET value=excluded.value, '
            'updated_at=excluded.updated_at',
            (service_name, key, json.dumps(value),
             time.time() if now is None else now))
        conn.commit()


def del_note(service_name: str, key: str) -> None:
    with db_lock():
        conn = _conn()
        conn.execute(
            'DELETE FROM controller_notes WHERE service_name=? AND '
            'key=?', (service_name, key))
        conn.commit()


def get_notes(service_name: str) -> Dict[str, Any]:
    conn = _conn()
    rows = conn.execute(
        'SELECT key, value FROM controller_notes WHERE service_name=?',
        (service_name,)).fetchall()
    return {r[0]: json.loads(r[1]) for r in rows}


def service_to_json(record: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(record)
    out['status'] = record['status'].value
    return out


def replica_to_json(record: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(record)
    out['status'] = record['status'].value
    return out
