"""Adaptive tensor-parallel placement for serving replicas.

The policy layer of multi-chip serving ("Nitsum: Serving Tiered LLM
Requests with Adaptive Tensor Parallelism", PAPERS.md): given a model
size, a replica's chip budget, and the SLO tier the replica is meant
for, pick the (tp, dp) mesh shape the replica's engine should serve
with.

The tradeoff it encodes:

- **Tensor parallelism (tp)** splits every weight matrix and the KV
  pool's head dim across chips. Decode is HBM-bandwidth-bound, so the
  per-token weight+KV stream — and therefore TPOT — improves ~linearly
  with tp (minus the per-layer all-reduce). tp is also the only way to
  SERVE a model at all once its weights outgrow one chip's HBM.
- **Data parallelism (dp)** replicates the engine over chip groups.
  Aggregate tok/s scales linearly with dp at unchanged TPOT, and there
  are no cross-chip collectives on the critical path at all.

So: **latency-tier replicas take the highest tp the chip budget
allows** (interactive TPOT is the contract), **throughput-tier
replicas take the smallest tp that fits the model and spend the rest
on dp** (batch tok/s is the contract; dp's collective-free scaling
wins). The controller exports the plan to the replica as
``SKYTPU_TP``/``SKYTPU_DP`` (the env contract
``mesh_lib.serving_spec_from_env`` reads); ``serve/server.py --tp/--dp``
override per process.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

# Fraction of a chip's HBM the weights may take before the model is
# considered not-to-fit at that tp: the rest is KV pool, decode
# transients (horizon ring, prefill stacks) and XLA workspace — the
# same reserve structure PagedInferenceEngine._auto_n_pages budgets.
_WEIGHT_HBM_FRAC = 0.6


@dataclasses.dataclass(frozen=True)
class ParallelismPlan:
    """A replica's mesh shape plus why it was chosen. ``hosts`` is the
    gang size (processes per replica, ``parallelism: hosts:``): the
    replica manager launches that many ranks per replica, all sharing
    one gang ID, and the (tp, dp) mesh spans their combined chips on a
    pod (serve/gang.py owns the lifecycle contract)."""
    tp: int
    dp: int
    reason: str
    hosts: int = 1

    @property
    def chips(self) -> int:
        return self.tp * self.dp

    def as_env(self) -> Dict[str, str]:
        """The replica launch env contract
        (``serving_spec_from_env`` on the model-server side). Gang
        identity env (SKYTPU_RANK/WORLD/COORDINATOR/GANG_ID) is
        per-rank and owned by the replica manager, not the plan."""
        return {'SKYTPU_TP': str(self.tp), 'SKYTPU_DP': str(self.dp)}

    def with_hosts(self, hosts: int) -> 'ParallelismPlan':
        return dataclasses.replace(self, hosts=max(1, int(hosts)))


def model_weight_bytes(cfg_name: str,
                       quantize: Optional[str] = None) -> int:
    """Stored weight bytes of a preset config: 1 B/param for int8
    (per-channel fp32 scales are noise at this granularity), else the
    config dtype's width."""
    import jax.numpy as jnp

    from skypilot_tpu.models import configs
    cfg = configs.get_config(cfg_name)
    itemsize = 1 if quantize == 'int8' else jnp.dtype(cfg.dtype).itemsize
    return cfg.num_params * itemsize


def min_tp_to_fit(model_bytes: int, *, hbm_per_chip_gb: float = 16.0,
                  max_tp: int = 256) -> int:
    """Smallest power-of-two tp at which the weight shard leaves room
    for a useful KV pool on each chip (weights under
    ``_WEIGHT_HBM_FRAC`` of HBM)."""
    budget = _WEIGHT_HBM_FRAC * hbm_per_chip_gb * 1e9
    tp = 1
    while model_bytes / tp > budget and tp < max_tp:
        tp *= 2
    return tp


def choose_parallelism(model_bytes: int, chips: int, *,
                       slo_tier: str = 'latency',
                       hbm_per_chip_gb: float = 16.0
                       ) -> ParallelismPlan:
    """Pick (tp, dp) for one replica of ``chips`` chips.

    Raises ``ValueError`` when the model cannot fit even at
    ``tp == chips`` — the caller (controller/autoscaler) must grow the
    replica's chip budget, not silently serve a model that OOMs on
    load.
    """
    if chips < 1:
        raise ValueError(f'chips must be >= 1, got {chips}')
    # Largest power of two <= chips: mesh axes need exact factoring and
    # power-of-two splits keep every sharded dim divisible.
    tp_max = 1
    while tp_max * 2 <= chips:
        tp_max *= 2
    tp_min = min_tp_to_fit(model_bytes, hbm_per_chip_gb=hbm_per_chip_gb)
    if tp_min > tp_max:
        raise ValueError(
            f'model ({model_bytes / 1e9:.1f} GB) does not fit '
            f'{chips} chip(s) at {hbm_per_chip_gb:.0f} GB HBM each '
            f'(needs tp>={tp_min}); raise the replica chip budget')
    if slo_tier == 'latency':
        # Interactive TPOT contract: all chips into the tensor split.
        tp = tp_max
        reason = (f'latency tier: max tp={tp} of {chips} chip(s) for '
                  f'TPOT (model needs tp>={tp_min})')
    else:
        # Batch tok/s contract: smallest tp that fits, rest as
        # collective-free dp replicas.
        tp = tp_min
        reason = (f'throughput tier: min fitting tp={tp}, remaining '
                  'chips as dp for aggregate tok/s')
    dp = max(1, chips // tp)
    return ParallelismPlan(tp=tp, dp=dp, reason=reason)


def plan_for_model(cfg_name: str, chips: int, *,
                   slo_tier: str = 'latency',
                   quantize: Optional[str] = None,
                   hbm_per_chip_gb: float = 16.0) -> ParallelismPlan:
    """``choose_parallelism`` from a preset config name (the spelling
    the controller uses — service specs carry model names, not byte
    counts)."""
    return choose_parallelism(
        model_weight_bytes(cfg_name, quantize=quantize), chips,
        slo_tier=slo_tier, hbm_per_chip_gb=hbm_per_chip_gb)


def role_for_new_replica(spec, live_roles) -> str:
    """Disaggregation role for the NEXT replica to launch, given the
    roles of the replicas already alive (ThunderServe-style phase
    pools, ``service: disaggregation:`` block): fill the prefill pool
    first — a decode worker with nobody feeding it is idle capacity,
    while a prefill worker alone still serves (colocated fallback:
    with no decode worker taking handoffs it decodes locally) — then
    the decode pool, then colocated. Replacement after a failure
    naturally re-fills whichever pool lost a member first."""
    want_prefill = int(getattr(spec, 'disagg_prefill_replicas', 0))
    want_decode = int(getattr(spec, 'disagg_decode_replicas', 0))
    if want_prefill <= 0 and want_decode <= 0:
        return 'colocated'
    counts: Dict[str, int] = {}
    for r in live_roles:
        counts[r or 'colocated'] = counts.get(r or 'colocated', 0) + 1
    if counts.get('prefill', 0) < want_prefill:
        return 'prefill'
    if counts.get('decode', 0) < want_decode:
        return 'decode'
    return 'colocated'


def plan_for_spec(spec) -> ParallelismPlan:
    """The controller's entry point: the replica (tp, dp) plan for a
    service spec's ``parallelism`` block. 'fixed' pins the explicit
    shape; 'adaptive' with a model name runs the Nitsum-style policy;
    no block (or a 1-chip replica with no model) stays single-chip."""
    hosts = int(getattr(spec, 'gang_hosts', 1) or 1)
    if spec.parallelism_policy == 'fixed' or (
            spec.tp is not None or spec.dp is not None):
        return ParallelismPlan(tp=int(spec.tp or 1), dp=int(spec.dp or 1),
                               reason='fixed by service spec',
                               hosts=hosts)
    if spec.parallelism_model is None:
        if spec.chips_per_replica > 1:
            # Chips with no model-size signal: a pure-dp split is the
            # safe default (no fit constraint to satisfy, no
            # collectives added).
            return ParallelismPlan(tp=1, dp=spec.chips_per_replica,
                                   reason='no model size declared: '
                                          'chips as dp replicas',
                                   hosts=hosts)
        return ParallelismPlan(tp=1, dp=1, reason='single-chip replica',
                               hosts=hosts)
    return plan_for_model(spec.parallelism_model,
                          spec.chips_per_replica,
                          slo_tier=spec.slo_tier,
                          quantize=spec.parallelism_quantize,
                          hbm_per_chip_gb=spec.hbm_per_chip_gb
                          ).with_hosts(hosts)
