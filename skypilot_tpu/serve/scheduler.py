"""SLO-aware async serving core: admission, scheduling, shedding,
streaming.

BENCH_r05 measured the engine sustaining 1218.9 out-tok/s/chip while
the HTTP path delivered 538 with 14.1 s median TTFT at saturation —
head-of-line blocking and admission starvation in the serve loop, not
engine slowness. This module is the piece that closes that gap: it
sits between the HTTP front end and either inference engine and owns
every decision the old server made implicitly (FIFO into the engine
queue, unbounded growth, block-until-done handlers):

- **SLO tiers.** Every request carries a tier — ``latency``
  (interactive: TTFT is the contract) or ``throughput`` (batch:
  tokens/s is the contract) — declared per request (``slo_tier`` in
  the JSON payload or the ``X-SLO-Tier`` header) with a server
  default. Tiers map to engine admission priorities, drive the
  admission budget split, and get their own TTFT/TPOT/queue-wait
  quantiles in ``/metrics``.

- **Priority + shortest-remaining-work scheduling.** Queued requests
  wait in per-tier queues; each engine step the scheduler tops the
  engine up from them (``fill_engine``), splitting the chunked-prefill
  admission token budget across tiers by a deficit counter
  (``latency_admit_frac`` of admitted work goes to the latency tier
  while both tiers are backlogged — neither tier can starve the
  other). Within a tier the next request is the one with the least
  estimated remaining work (prompt + budgeted decode tokens — the
  SJF/SRW policy of "Scalable Joint Resource Allocation for
  SLO-Constrained LLM Inference"), FIFO on ties, so one long prompt
  stops stalling a line of short interactive requests.

- **Admission control + load shedding.** Per-tier queues are bounded
  in TOKENS (``max_queue_tokens``; auto-derived from the engine's KV
  pool capacity). A request that would overflow its tier's bound is
  shed IMMEDIATELY with HTTP 429 and a ``Retry-After`` computed from
  live telemetry (work ahead of the request / the measured token
  throughput) instead of silently joining a queue it will time out
  in. Shed counts ride ``skytpu_sched_shed_total{tier,reason}``.

- **Incremental streaming off the engine loop.** Every request owns an
  :class:`Outbox` the engine loop feeds fire-and-forget (``put`` never
  blocks the step); HTTP handler threads (or an asyncio consumer via
  :meth:`Outbox.aget` — graftcheck GC111 bans blocking engine calls
  inside ``serve/`` coroutines) drain it at their own pace. A slow or
  disconnected client never back-pressures the engine step; disconnect
  cancels the request engine-side through :meth:`RequestScheduler.
  cancel`, releasing the slot.

Locking: the scheduler has its own queue lock (``_q_lock``) and is
handed the serve layer's engine lock. Order is ALWAYS engine lock
outer, queue lock inner; nothing blocking runs under either.
``fill_engine``/``on_events`` are called by the engine-loop thread,
``submit``/``cancel`` by handler threads.
"""
from __future__ import annotations

import collections
import math
import queue as queue_mod
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from skypilot_tpu import telemetry
from skypilot_tpu import tpu_logging
from skypilot_tpu.telemetry import clock
from skypilot_tpu.telemetry import tracing

logger = tpu_logging.init_logger(__name__)

# SLO tiers, best first. The tuple order IS the engine admission
# priority order (index = engine priority hint: lower wins a free
# slot).
TIERS: Tuple[str, ...] = ('latency', 'throughput')

# Shed reasons (the stable label set of skytpu_sched_shed_total —
# every (tier, reason) series is registered at scheduler construction
# so the /metrics schema never grows mid-flight). 'draining' is the
# graceful-drain refusal: the replica is leaving rotation, so the
# caller gets a retryable 503 instead of the overload 429.
SHED_REASONS: Tuple[str, ...] = ('queue_full', 'engine_error',
                                 'draining')

_RETRY_AFTER_MIN_S = 1
_RETRY_AFTER_MAX_S = 120


class ShedError(RuntimeError):
    """Admission refused: the caller should answer ``http_status``
    (429 for overload, 503 while draining — both retryable) with the
    ``retry_after_s`` hint (derived from live queue telemetry — the
    work ahead of this request over the measured token throughput)."""

    def __init__(self, tier: str, reason: str, retry_after_s: int,
                 detail: str):
        super().__init__(detail)
        self.tier = tier
        self.reason = reason
        self.retry_after_s = retry_after_s

    @property
    def http_status(self) -> int:
        # Draining is not overload: the replica is healthy but
        # leaving — 503 + Retry-After tells the client (and the LB's
        # transparent retry) to go elsewhere, now.
        return 503 if self.reason == 'draining' else 429


class Outbox:
    """Per-request stream of ``(token, finished)`` tuples, fed by the
    engine loop and drained by exactly one consumer. ``put`` never
    blocks (unbounded queue — bounded upstream by the request's own
    ``max_new_tokens``), so a stalled consumer can never back-pressure
    the engine step. ``(None, True)`` is the failure sentinel (engine
    death / shed after admission); ``error`` then carries the reason."""

    def __init__(self) -> None:
        self._q: 'queue_mod.Queue[Tuple[Optional[int], bool]]' = \
            queue_mod.Queue()
        self.error: Optional[str] = None

    def put(self, token: Optional[int], finished: bool) -> None:
        self._q.put((token, finished))

    def fail(self, error: str) -> None:
        """Terminal failure: record the reason and wake the consumer
        with the sentinel. Idempotent — the first reason wins."""
        if self.error is None:
            self.error = error
        self._q.put((None, True))

    def get(self, timeout: Optional[float] = None
            ) -> Tuple[Optional[int], bool]:
        return self._q.get(timeout=timeout)

    async def aget(self) -> Tuple[Optional[int], bool]:
        """Asyncio adapter: awaits the next token WITHOUT blocking the
        event loop (the blocking ``get`` runs on the default executor —
        the pattern graftcheck GC111 routes ``serve/`` coroutines to)."""
        import asyncio
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, self.get)


class ScheduledRequest:
    """One request's scheduler-side state, submit to finish. The
    ``outbox`` is live from submission; ``request_id`` exists only once
    the request is admitted into an engine; ``result`` is the engine's
    finished ``Request`` object once complete."""

    __slots__ = ('tier', 'prompt', 'max_new_tokens', 'sampling', 'seq',
                 'submit_time', 'admit_time', 'outbox', 'request_id',
                 'result', 'first_token_time', 'cancelled', 'handoff',
                 'trace_ctx')

    def __init__(self, tier: str, prompt: List[int],
                 max_new_tokens: int, sampling: Dict[str, Any],
                 seq: int,
                 trace_ctx: Optional[Dict[str, Any]] = None):
        self.tier = tier
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.sampling = sampling
        self.seq = seq
        self.submit_time = clock.now()
        self.admit_time: Optional[float] = None
        self.outbox = Outbox()
        self.request_id: Optional[int] = None
        self.result: Optional[Any] = None
        self.first_token_time: Optional[float] = None
        self.cancelled = False
        # Wire-supplied trace context ({'trace_id', 'parent_span'}) —
        # the X-Skytpu-Trace hop header this request arrived with. On
        # admission the engine's RequestTrace adopts it and the
        # EFFECTIVE trace id (wire-supplied or locally minted) is
        # written back here, so downstream hops (KV handoff, gang
        # op-log) propagate the same fleet-wide id.
        self.trace_ctx = dict(trace_ctx) if trace_ctx else None
        # Adopted KV-handoff continuation (disaggregated serving): the
        # request was admitted and prefilled on ANOTHER replica, so
        # this replica's TTFT/queue-wait quantiles skip it — a near-
        # zero "TTFT" here would poison the latency telemetry the
        # autoscaler and routing policies read.
        self.handoff = False

    @property
    def work_tokens(self) -> int:
        """Remaining-work estimate while queued: the whole prompt must
        prefill and up to ``max_new_tokens`` must decode. The SRW
        ordering key (and the unit the admission budget is spent in)."""
        return len(self.prompt) + self.max_new_tokens


class _TokenRateMeter:
    """Sliding-window output-token throughput (tok/s) — the live
    denominator of the Retry-After computation. Bounded window of
    (monotonic time, n_tokens) buckets; O(1) amortized."""

    def __init__(self, window_s: float = 30.0):
        self.window_s = window_s
        self._events: 'collections.deque[Tuple[float, int]]' = \
            collections.deque()
        self._total = 0

    def add(self, n_tokens: int, now: Optional[float] = None) -> None:
        now = clock.monotonic() if now is None else now
        self._events.append((now, n_tokens))
        self._total += n_tokens
        self._trim(now)

    def _trim(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._events and self._events[0][0] < cutoff:
            _, n = self._events.popleft()
            self._total -= n

    def rate(self, now: Optional[float] = None) -> float:
        """tok/s over the window; 0.0 when no tokens were seen (the
        caller falls back to a capacity estimate)."""
        now = clock.monotonic() if now is None else now
        self._trim(now)
        if not self._events:
            return 0.0
        span = max(now - self._events[0][0], 1e-3)
        return self._total / span


class RequestScheduler:
    """The admission/scheduling core. One instance per model server;
    the engine binds late (``bind_engine``) because capacity-derived
    defaults need the loaded engine's pool size.

    Parameters
    ----------
    engine_lock:
        The serve layer's engine mutation lock. ``fill_engine`` is
        called WITH it held (from the engine loop); ``cancel`` and the
        finished-request bookkeeping take it internally.
    default_tier:
        Tier used when a request declares none.
    max_queue_tokens:
        Per-tier queue bound in work tokens (prompt + budgeted decode).
        ``None``/0 auto-derives at ``bind_engine``: 2x the engine's KV
        pool token capacity — roughly two full pools of work may wait,
        beyond that waiting is worse than retrying.
    latency_admit_frac:
        Share of admitted work tokens reserved for the latency tier
        while BOTH tiers are backlogged (deficit-weighted; an idle
        tier's share flows to the busy one). Default 0.7 — latency
        requests are short by contract, so a 70% token share admits
        far more than 70% of them.
    """

    def __init__(self, engine_lock: threading.Lock, *,
                 default_tier: str = 'latency',
                 max_queue_tokens: Optional[int] = None,
                 latency_admit_frac: float = 0.7,
                 wake: Optional[Callable[[], None]] = None):
        if default_tier not in TIERS:
            raise ValueError(f'unknown SLO tier {default_tier!r}; '
                             f'supported: {TIERS}')
        if not 0.0 < latency_admit_frac < 1.0:
            raise ValueError('latency_admit_frac must be in (0, 1), '
                             f'got {latency_admit_frac}')
        self._engine_lock = engine_lock
        self.default_tier = default_tier
        self.latency_admit_frac = latency_admit_frac
        self._max_queue_tokens = max_queue_tokens or None
        self._wake = wake or (lambda: None)
        # Gang op-log hooks (serve/gang.py): the gang leader records
        # every engine mutation so followers replay the identical call
        # stream. ``on_admit(rid, sr)`` fires right after a successful
        # ``engine.add_request`` (engine lock held — keep it cheap);
        # ``on_cancel(rid)`` fires after a successful engine-side
        # cancel. None (the default) costs one attribute check.
        self.on_admit: Optional[Callable[[int, 'ScheduledRequest'],
                                         None]] = None
        self.on_cancel: Optional[Callable[[int], None]] = None
        self._engine: Optional[Any] = None
        # Mesh throughput factor (tp x dp of the bound engine's mesh):
        # scales the WORK-TOKEN RATE estimates — the cold-meter
        # Retry-After fallback and nothing else. Work tokens themselves
        # stay mesh-independent (a token is a token); only how fast the
        # engine chews through them changes with mesh shape. Once the
        # live _TokenRateMeter warms up it dominates, so the factor
        # only matters for the first seconds after boot — exactly when
        # a tp=4 replica must not tell clients to back off 4x too long.
        self._mesh_speedup = 1
        self._q_lock = threading.Lock()
        self._queues: Dict[str, List[ScheduledRequest]] = {
            t: [] for t in TIERS}
        self._queued_tokens: Dict[str, int] = {t: 0 for t in TIERS}
        self._by_rid: Dict[int, ScheduledRequest] = {}
        self._seq = 0
        # Cumulative admitted work tokens per tier — the deficit
        # counter the per-step budget split rides.
        self._admitted_tokens: Dict[str, int] = {t: 0 for t in TIERS}
        self._rate = _TokenRateMeter()
        self._failed: Optional[str] = None
        self._draining = False
        self._init_metrics()

    # ------------------------------------------------------------ metrics
    def _init_metrics(self) -> None:
        """Register EVERY series up front (zeros rendered from the
        first scrape — the stable-schema contract scrapers rely on)."""
        reg = telemetry.get_registry()
        self._g_queue_tokens = {
            t: reg.gauge('skytpu_sched_queue_tokens',
                         'Work tokens waiting in the scheduler queue',
                         tier=t) for t in TIERS}
        self._g_queue_depth = {
            t: reg.gauge('skytpu_sched_queue_depth',
                         'Requests waiting in the scheduler queue',
                         tier=t) for t in TIERS}
        self._g_budget_share = {
            t: reg.gauge('skytpu_sched_admit_share',
                         'Share of admitted work tokens (cumulative)',
                         tier=t) for t in TIERS}
        self._c_shed = {
            (t, r): reg.counter('skytpu_sched_shed_total',
                                'Requests shed at admission (HTTP 429)',
                                tier=t, reason=r)
            for t in TIERS for r in SHED_REASONS}
        self._c_admitted = {
            t: reg.counter('skytpu_sched_admitted_total',
                           'Requests admitted into the engine', tier=t)
            for t in TIERS}
        self._h_ttft = {
            t: reg.histogram('skytpu_request_ttft_ms',
                             'Time to first token (ms)', tier=t)
            for t in TIERS}
        self._h_tpot = {
            t: reg.histogram('skytpu_request_tpot_ms',
                             'Mean time per output token after the '
                             'first (ms)', tier=t) for t in TIERS}
        self._h_queue_wait = {
            t: reg.histogram('skytpu_request_queue_wait_ms',
                             'Submit to engine admission (ms)', tier=t)
            for t in TIERS}

    # ------------------------------------------------------------- engine
    def bind_engine(self, engine: Any) -> None:
        """Attach the loaded engine; derives the auto queue bound from
        its KV pool capacity."""
        with self._q_lock:
            self._engine = engine
            if hasattr(engine, 'mesh_axes'):
                axes = engine.mesh_axes()
                self._mesh_speedup = max(
                    1, int(axes.get('tp', 1)) * int(axes.get('dp', 1)))
            if self._max_queue_tokens is None:
                cap = 0
                if hasattr(engine, 'kv_pool_stats'):
                    cap = int(engine.kv_pool_stats()
                              ['pool_token_capacity'])
                self._max_queue_tokens = max(
                    2 * cap, 4 * engine.max_batch * 256)
        logger.info(
            f'scheduler bound: max_queue_tokens={self._max_queue_tokens} '
            f'default_tier={self.default_tier} '
            f'latency_admit_frac={self.latency_admit_frac} '
            f'mesh_speedup={self._mesh_speedup}')

    @property
    def mesh_speedup(self) -> int:
        """tp x dp of the bound engine's mesh (1 until bound)."""
        return self._mesh_speedup

    @property
    def max_queue_tokens(self) -> int:
        return self._max_queue_tokens or 0

    # ------------------------------------------------------------- submit
    def resolve_tier(self, tier: Optional[str]) -> str:
        if tier in (None, ''):
            return self.default_tier
        if tier not in TIERS:
            raise ValueError(f'unknown SLO tier {tier!r}; supported: '
                             f'{", ".join(TIERS)}')
        return tier

    def submit(self, prompt: List[int], *, max_new_tokens: int,
               tier: Optional[str] = None,
               trace_ctx: Optional[Dict[str, Any]] = None,
               **sampling: Any) -> ScheduledRequest:
        """Admission-controlled submit from a handler thread. Returns
        the live :class:`ScheduledRequest` (its outbox streams tokens)
        or raises :class:`ShedError` (HTTP 429) when the tier's queue
        bound would be exceeded, with ``retry_after_s`` from live queue
        telemetry. Raises ``RuntimeError`` after an engine failure."""
        tier = self.resolve_tier(tier)
        if self._failed is not None:
            raise RuntimeError(f'engine failed: {self._failed}')
        work = len(prompt) + max_new_tokens
        with self._q_lock:
            if self._draining:
                # Graceful drain: already-accepted work runs to
                # completion, but nothing new is admitted — the client
                # retries (through the LB: on another replica).
                retry = max(_RETRY_AFTER_MIN_S,
                            min(5, _RETRY_AFTER_MAX_S))
                self._c_shed[(tier, 'draining')].inc()
                raise ShedError(
                    tier, 'draining', retry,
                    'replica is draining (graceful scale-down); '
                    f'retry on another replica in ~{retry}s')
            bound = self._max_queue_tokens
            if bound and self._queued_tokens[tier] + work > bound:
                retry = self._retry_after_locked(tier, work)
                self._c_shed[(tier, 'queue_full')].inc()
                raise ShedError(
                    tier, 'queue_full', retry,
                    f'{tier}-tier queue full '
                    f'({self._queued_tokens[tier]} + {work} > {bound} '
                    f'queued work tokens); retry in ~{retry}s')
            self._seq += 1
            sr = ScheduledRequest(tier, list(prompt), max_new_tokens,
                                  sampling, self._seq,
                                  trace_ctx=trace_ctx)
            self._queues[tier].append(sr)
            self._queued_tokens[tier] += work
        self._wake()
        return sr

    # -------------------------------------------------------- handoff
    def adopt(self, request_id: int, *, tier: Optional[str],
              prompt: List[int], output: List[int],
              max_new_tokens: int,
              trace_ctx: Optional[Dict[str, Any]] = None
              ) -> ScheduledRequest:
        """Register a KV-handoff continuation that was seated directly
        in the engine (``ingest_kv_snapshot``) — admission already
        happened on the prefill worker, so the request bypasses the
        tier queues; this wires up the outbox/event routing and the
        bookkeeping the engine loop relies on. The caller holds the
        engine lock across ingest+adopt so ``fail_all`` cannot miss
        the window between them."""
        tier = self.resolve_tier(tier)
        if self._failed is not None:
            raise RuntimeError(f'engine failed: {self._failed}')
        with self._q_lock:
            self._seq += 1
            sr = ScheduledRequest(tier, list(prompt) + list(output),
                                  max_new_tokens, {}, self._seq,
                                  trace_ctx=trace_ctx)
            sr.request_id = request_id
            sr.admit_time = sr.submit_time
            sr.first_token_time = sr.submit_time
            sr.handoff = True
            self._by_rid[request_id] = sr
        self._c_admitted[tier].inc()
        return sr

    # ------------------------------------------------------- retry-after
    def _retry_after_locked(self, tier: str, work: int) -> int:
        """Retry-After (whole seconds) for a request of ``work`` tokens
        arriving now: the work AHEAD of it (engine in-flight remainder
        + every queued token of tiers at or above this one) over the
        measured token throughput. Falls back to a capacity guess
        before the meter warms up. Clamped to [1, 120] — precision
        past that is noise to a client backoff loop."""
        ahead = sum(self._queued_tokens[t] for t in TIERS
                    if TIERS.index(t) <= TIERS.index(tier))
        eng = self._engine
        if eng is not None and hasattr(eng, 'remaining_work_tokens'):
            ahead += eng.remaining_work_tokens()
        rate = self._rate.rate()
        if rate <= 0.0:
            # Cold meter: assume the engine streams ~8 tok/s/slot (a
            # deliberately conservative interactive-decode floor),
            # scaled by the mesh's tp x dp — a sharded replica chews
            # the same work tokens proportionally faster, and quoting
            # a single-chip Retry-After off a tp=4 mesh overstates the
            # backoff 4x right when the replica is freshest.
            eng_batch = eng.max_batch if eng is not None else 8
            rate = 8.0 * max(1, eng_batch) * self._mesh_speedup
        return int(min(_RETRY_AFTER_MAX_S,
                       max(_RETRY_AFTER_MIN_S,
                           math.ceil((ahead + work) / rate))))

    def retry_after_s(self, tier: str, work: int = 0) -> int:
        with self._q_lock:
            return self._retry_after_locked(tier, work)

    # ---------------------------------------------------------- admission
    def _pick_tier_locked(self) -> Optional[str]:
        """Deficit-weighted tier choice: the latency tier owns
        ``latency_admit_frac`` of cumulative admitted work while both
        tiers wait; an idle tier's share flows to the other."""
        waiting = [t for t in TIERS if self._queues[t]]
        if not waiting:
            return None
        if len(waiting) == 1:
            return waiting[0]
        total = sum(self._admitted_tokens.values())
        if total == 0:
            return TIERS[0]
        lat_share = self._admitted_tokens[TIERS[0]] / total
        return (TIERS[0] if lat_share < self.latency_admit_frac
                else TIERS[1])

    def _pop_srw_locked(self, tier: str) -> ScheduledRequest:
        """Shortest-remaining-work pop, FIFO on ties (``seq`` is the
        arrival stamp). Callers hold ``_q_lock`` (the ``_locked``
        suffix contract); the checker cannot see the cross-method
        lock context."""
        q = self._queues[tier]
        best = min(range(len(q)),
                   key=lambda i: (q[i].work_tokens, q[i].seq))
        sr = q.pop(best)
        self._queued_tokens[tier] -= sr.work_tokens   # graftcheck: disable=GC101
        return sr

    def fill_engine(self, engine: Any) -> bool:
        """Top the engine up from the tier queues — called by the
        engine loop each step WITH the engine lock held, BEFORE
        ``engine.step()``. Admits at most as many requests as the
        engine has free slots (the engine's own queue stays empty, so
        ordering stays HERE), picking the tier by budget deficit and
        the request by shortest remaining work. Each admission carries
        the tier's engine priority hint, so engine-internal requeues
        (paged preemption) keep tier ordering too."""
        admitted = False
        # Requests whose adapter could not get a bank slot THIS cycle:
        # held out of the queues until the loop exits (SRW would
        # deterministically re-pick them), then requeued — so a
        # bank-full adapter never head-of-line-blocks base-model or
        # other-adapter admissions.
        bank_deferred: List[Tuple[str, ScheduledRequest]] = []
        while True:
            free = (engine.max_batch - engine.num_active
                    - engine.queue_depth)
            if free <= 0:
                break
            with self._q_lock:
                tier = self._pick_tier_locked()
                if tier is None:
                    break
                sr = self._pop_srw_locked(tier)
                self._admitted_tokens[tier] += sr.work_tokens
            try:
                rid = engine.add_request(
                    sr.prompt, max_new_tokens=sr.max_new_tokens,
                    priority=TIERS.index(tier), **sr.sampling)
            except ValueError as e:
                # Invalid for THIS engine (e.g. prompt outgrew max_seq
                # between front-end validation and admission, or an
                # unknown adapter name): fail the one request, keep
                # admitting.
                sr.outbox.fail(f'rejected: {e}')
                continue
            except RuntimeError as e:
                from skypilot_tpu.inference.adapters import \
                    AdapterBankFullError
                if not isinstance(e, AdapterBankFullError):
                    raise
                # Every adapter-bank slot is pinned by a live request:
                # a RETRYABLE capacity condition, not a client error.
                # Defer just THIS request and keep admitting others;
                # pins release as requests finish, so it self-recovers
                # next cycle.
                with self._q_lock:
                    self._admitted_tokens[tier] -= sr.work_tokens
                bank_deferred.append((tier, sr))
                continue
            sr.request_id = rid
            sr.admit_time = clock.now()
            if hasattr(engine, 'adopt_trace_context'):
                # The engine trace joins the wire-supplied fleet trace
                # (or keeps its minted 128-bit id); the EFFECTIVE id is
                # written back so every downstream hop — KV handoff,
                # gang op-log, migration legs — carries the same id.
                ctx = sr.trace_ctx or {}
                tid = engine.adopt_trace_context(
                    rid, trace_id=ctx.get('trace_id'),
                    parent_span=ctx.get('parent_span'))
                if tid is not None:
                    sr.trace_ctx = dict(ctx, trace_id=tid)
            if self.on_admit is not None:
                self.on_admit(rid, sr)
            with self._q_lock:
                self._by_rid[rid] = sr
            self._c_admitted[tier].inc()
            self._h_queue_wait[tier].observe(
                (sr.admit_time - sr.submit_time) * 1e3)
            admitted = True
        if bank_deferred:
            with self._q_lock:
                for d_tier, d_sr in bank_deferred:
                    self._queues[d_tier].append(d_sr)
                    self._queued_tokens[d_tier] += d_sr.work_tokens
        return admitted

    @property
    def backlog(self) -> int:
        with self._q_lock:
            return sum(len(q) for q in self._queues.values())

    # -------------------------------------------------------------- events
    def on_events(self, engine: Any,
                  events: List[Tuple[int, int, bool]]) -> None:
        """Route one step's ``(request_id, token, finished)`` events to
        the owning outboxes — called by the engine loop WITHOUT the
        engine lock (outbox puts are lock-free; only the finished-
        request pop re-takes it briefly). A finished request's
        ``result`` is popped BEFORE its final token is put: the moment
        a consumer sees ``finished`` the ``Request`` object is already
        there (the streaming handlers read ``sr.result`` for the
        finish_reason on that very event)."""
        n_tokens = 0
        for rid, token, finished in events:
            with self._q_lock:
                sr = self._by_rid.get(rid)
            if sr is None:
                continue
            if token is not None and token < 0:
                # NaN blast-radius isolation: the engine evicted this
                # request on the device-side non-finite sentinel. Fail
                # exactly this outbox with a RETRYABLE error (the
                # stream handler emits `retryable: true`, so the LB's
                # in-flight recovery resubmits prompt + tokens-so-far
                # to a surviving replica); co-batched requests in the
                # same event batch continue untouched.
                with self._q_lock:
                    self._by_rid.pop(rid, None)
                telemetry.get_registry().counter(
                    'skytpu_gray_failures_total',
                    'Gray failures detected by the data-plane '
                    'defense layer', kind='nan_logits').inc()
                sr.outbox.fail(
                    'request evicted: non-finite logits (NaN/Inf) '
                    'detected on device; retry on another replica')
                continue
            n_tokens += 1
            if sr.first_token_time is None:
                sr.first_token_time = clock.now()
            if finished:
                with self._engine_lock:
                    sr.result = engine.pop_finished(rid)
                with self._q_lock:
                    self._by_rid.pop(rid, None)
            sr.outbox.put(token, finished)
            if finished:
                self._record_finished(sr)
        if n_tokens:
            self._rate.add(n_tokens)
        self._refresh_gauges()

    def _record_finished(self, sr: ScheduledRequest) -> None:
        req = sr.result
        if req is None or sr.handoff:
            # Handoff continuations: TTFT belongs to the prefill
            # worker that served the first token, not this replica.
            return
        if req.ttft_ms is not None:
            self._h_ttft[sr.tier].observe(req.ttft_ms)
        if (req.first_token_time is not None
                and req.finish_time is not None
                and len(req.output) > 1):
            self._h_tpot[sr.tier].observe(
                (req.finish_time - req.first_token_time) * 1e3
                / (len(req.output) - 1))

    def _refresh_gauges(self) -> None:
        with self._q_lock:
            tokens = dict(self._queued_tokens)
            depth = {t: len(self._queues[t]) for t in TIERS}
            admitted = dict(self._admitted_tokens)
        total = sum(admitted.values())
        for t in TIERS:
            self._g_queue_tokens[t].set(tokens[t])
            self._g_queue_depth[t].set(depth[t])
            self._g_budget_share[t].set(
                admitted[t] / total if total else 0.0)

    # -------------------------------------------------------------- cancel
    def cancel(self, sr: ScheduledRequest) -> bool:
        """Abort a live request (client disconnect): drop it from the
        tier queue if still waiting, or cancel it engine-side so the
        slot stops generating tokens nobody reads. Returns True when
        the request was still live (not finished)."""
        sr.cancelled = True
        with self._q_lock:
            q = self._queues[sr.tier]
            if sr in q:
                q.remove(sr)
                self._queued_tokens[sr.tier] -= sr.work_tokens
                sr.outbox.fail('cancelled')
                return True
        if sr.request_id is None or sr.result is not None:
            return False
        with self._engine_lock:
            engine = self._engine
            if engine is None:
                return False
            req = engine.pop_finished(sr.request_id)
            cancelled = req is None and engine.cancel(sr.request_id)
            if cancelled and self.on_cancel is not None:
                # Under the engine lock on purpose: the gang op log's
                # order must match engine execution order exactly (a
                # cancel logged after a step the leader ran post-cancel
                # would desync follower KV state).
                self.on_cancel(sr.request_id)
        with self._q_lock:
            self._by_rid.pop(sr.request_id, None)
        if req is not None:
            sr.result = req
            self._record_finished(sr)
            return False
        return cancelled

    # ------------------------------------------------------------- failure
    def fail_all(self, error: str) -> None:
        """Engine death: every queued and in-flight request is failed
        (queued ones count as shed reason ``engine_error`` — their
        admission never happened), and future submits raise."""
        with self._q_lock:
            self._failed = error
            stranded = [sr for q in self._queues.values() for sr in q]
            for t in TIERS:
                self._queues[t].clear()
                self._queued_tokens[t] = 0
            inflight = list(self._by_rid.values())
            self._by_rid.clear()
        for sr in stranded:
            self._c_shed[(sr.tier, 'engine_error')].inc()
            sr.outbox.fail(error)
        for sr in inflight:
            sr.outbox.fail(error)
        self._refresh_gauges()

    # -------------------------------------------------------------- drain
    def begin_drain(self) -> None:
        """Enter graceful drain: new submits shed with a retryable 503
        (reason ``draining``); queued and in-flight requests keep
        running to completion (``fill_engine`` still admits the
        backlog). Idempotent."""
        with self._q_lock:
            self._draining = True

    @property
    def draining(self) -> bool:
        with self._q_lock:
            return self._draining

    @property
    def inflight(self) -> int:
        """Requests the scheduler still owns: queued + engine-side."""
        with self._q_lock:
            return (sum(len(q) for q in self._queues.values())
                    + len(self._by_rid))

    @property
    def drained(self) -> bool:
        """True once every accepted request has finished (queues empty
        AND nothing in flight engine-side)."""
        return self.inflight == 0

    # ------------------------------------------------------------- surface
    def json_stats(self) -> Dict[str, Any]:
        """The stable-schema per-tier block of ``/metrics?format=json``:
        every key ALWAYS present and numeric (zeros when idle), so
        scrapers see one schema from the first request."""
        self._refresh_gauges()
        with self._q_lock:
            tokens = dict(self._queued_tokens)
            depth = {t: len(self._queues[t]) for t in TIERS}
            admitted = dict(self._admitted_tokens)
        total = sum(admitted.values())
        tiers: Dict[str, Any] = {}
        for t in TIERS:
            shed = sum(int(self._c_shed[(t, r)].value)
                       for r in SHED_REASONS)
            tiers[t] = {
                'queue_depth': depth[t],
                'queue_tokens': tokens[t],
                'admitted': int(self._c_admitted[t].value),
                'admitted_tokens': admitted[t],
                'admit_share': round(admitted[t] / total, 4) if total
                else 0.0,
                'shed_total': shed,
                'ttft_ms_median': round(
                    self._h_ttft[t].quantile(0.5), 1),
                'ttft_ms_p90': round(self._h_ttft[t].quantile(0.9), 1),
                'tpot_ms_median': round(
                    self._h_tpot[t].quantile(0.5), 2),
                'queue_wait_ms_median': round(
                    self._h_queue_wait[t].quantile(0.5), 1),
                'queue_wait_ms_p90': round(
                    self._h_queue_wait[t].quantile(0.9), 1),
            }
        return {
            'default_tier': self.default_tier,
            'max_queue_tokens': self.max_queue_tokens,
            'latency_admit_frac': self.latency_admit_frac,
            'mesh_speedup': self._mesh_speedup,
            'tiers': tiers,
        }
