"""SkyServe client API: ``serve.up/down/status/tail_logs``.

Role of reference ``sky/serve/core.py`` (``up`` ``:136``, ``update``
``:362``, ``down`` ``:525``): ensure the serve-controller cluster (an
ordinary cluster — the whole stack recursively, SURVEY key idea #2), then
drive the serve RPC on its head. The service's replicas are themselves
ordinary clusters launched by the controller process.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions
from skypilot_tpu import execution
from skypilot_tpu import global_state
from skypilot_tpu import tpu_logging
from skypilot_tpu.dag import Dag
from skypilot_tpu.resources import Resources
from skypilot_tpu.serve.service_spec import SkyServiceSpec
from skypilot_tpu.task import Task
from skypilot_tpu.utils import common_utils

logger = tpu_logging.init_logger(__name__)

CONTROLLER_CLUSTER_NAME = 'skytpu-serve-controller'


def _to_task(task_or_dag: Union[Task, Dag]) -> Task:
    if isinstance(task_or_dag, Dag):
        tasks = task_or_dag.topological_order()
        if len(tasks) != 1:
            raise exceptions.InvalidDagError(
                'A service is a single task, not a pipeline.')
        return tasks[0]
    return task_or_dag


def _controller_resources(task: Task) -> Resources:
    cfg = config_lib.get_nested(('serve', 'controller', 'resources'), None)
    if cfg:
        return Resources.from_yaml_config(dict(cfg))
    cloud = None
    for res in task.resources:
        if res.cloud:
            cloud = res.cloud
            break
    return Resources(cloud=cloud or 'gcp', cpus='4+')


def _ensure_controller(task: Task) -> Any:
    record = global_state.get_cluster_from_name(CONTROLLER_CLUSTER_NAME)
    if record is not None and record['handle'] is not None:
        from skypilot_tpu.backend import backend_utils
        rec, handle = backend_utils.refresh_cluster_status(
            CONTROLLER_CLUSTER_NAME)
        if (rec is not None and handle is not None
                and rec['status'] == global_state.ClusterStatus.UP):
            return handle
    controller_task = Task(name='serve-controller')
    controller_task.set_resources(_controller_resources(task))
    _, handle = execution.launch(controller_task,
                                 cluster_name=CONTROLLER_CLUSTER_NAME,
                                 detach_run=True, stream_logs=False)
    return handle


def _controller_request(handle, request: Dict[str, Any]) -> Dict[str, Any]:
    from skypilot_tpu.provision import provisioner
    return provisioner.agent_request(handle.head_runner(), request,
                                     module='skypilot_tpu.serve.rpc',
                                     error_cls=exceptions.ApiError)


def _get_controller_handle() -> Any:
    record = global_state.get_cluster_from_name(CONTROLLER_CLUSTER_NAME)
    if record is None or record['handle'] is None:
        raise exceptions.ClusterNotUpError(
            'No serve controller is running (no services up).')
    return record['handle']


# ------------------------------------------------------------------- API
def up(task_or_dag: Union[Task, Dag],
       service_name: Optional[str] = None) -> Dict[str, Any]:
    """Spin up a service; returns {'name', 'endpoint'}.

    Reference ``sky.serve.up`` (``sky/serve/core.py:136``)."""
    task = _to_task(task_or_dag)
    if task.service is None:
        raise exceptions.InvalidServiceSpecError(
            'Task has no `service:` section; cannot `serve up`.')
    spec = SkyServiceSpec.from_yaml_config(task.service)   # validate early
    del spec
    if service_name is None:
        service_name = task.name or common_utils.generate_cluster_name(
            prefix='service')
    common_utils.check_cluster_name_is_valid(service_name)

    handle = _ensure_controller(task)
    resp = _controller_request(handle, {
        'op': 'up',
        'service_name': service_name,
        'task_config': task.to_yaml_config(),
        'username': common_utils.get_cleaned_username(),
        'run_timestamp': common_utils.make_run_timestamp(),
    })
    if not resp.get('ok'):
        raise exceptions.ApiError(resp.get('error', 'serve up failed'))
    head_ip = handle.cluster_info.hosts[0].internal_ip
    endpoint = f'http://{head_ip}:{resp["lb_port"]}'
    logger.info(f'Service {service_name!r} submitted; endpoint: {endpoint}')
    return {'name': service_name, 'endpoint': endpoint}


def update(task_or_dag: Union[Task, Dag], service_name: str
           ) -> Dict[str, Any]:
    """Blue-green-lite service update (reference ``sky.serve.update``
    ``sky/serve/core.py:362``): new replicas launch with the new task;
    old-version replicas drain once enough new ones are READY."""
    task = _to_task(task_or_dag)
    if task.service is None:
        raise exceptions.InvalidServiceSpecError(
            'Task has no `service:` section; cannot `serve update`.')
    SkyServiceSpec.from_yaml_config(task.service)      # validate early
    handle = _get_controller_handle()
    resp = _controller_request(handle, {
        'op': 'update',
        'service_name': service_name,
        'task_config': task.to_yaml_config(),
    })
    if not resp.get('ok'):
        raise exceptions.ApiError(resp.get('error', 'serve update failed'))
    logger.info(f'Service {service_name!r} updating to '
                f'v{resp["version"]}.')
    return {'name': service_name, 'version': resp['version']}


def status(service_names: Optional[List[str]] = None
           ) -> List[Dict[str, Any]]:
    """Service table incl. per-replica rows (reference ``sky serve
    status``)."""
    handle = _get_controller_handle()
    resp = _controller_request(handle, {
        'op': 'status', 'service_names': service_names})
    if not resp.get('ok'):
        raise exceptions.ApiError(resp.get('error', 'serve status failed'))
    head_ip = handle.cluster_info.hosts[0].internal_ip
    services = resp['services']
    for svc in services:
        svc['endpoint'] = f'http://{head_ip}:{svc["lb_port"]}'
    return services


def down(service_name: str, purge: bool = False) -> None:
    """Tear down a service: replicas, then controller/LB processes
    (reference ``sky.serve.down`` ``sky/serve/core.py:525``). With
    ``purge``, transport failures (controller cluster down/unreachable —
    the main reason purge exists) fall back to best-effort local cleanup
    of replica clusters instead of raising."""
    try:
        handle = _get_controller_handle()
        resp = _controller_request(handle, {
            'op': 'down', 'service_name': service_name})
    except Exception as e:  # pylint: disable=broad-except
        if not purge:
            raise
        logger.warning(f'Controller unreachable ({type(e).__name__}: {e}); '
                       f'purging {service_name!r} locally.')
        _purge_replica_clusters(service_name)
        return
    if not resp.get('ok'):
        if not purge:
            raise exceptions.ApiError(resp.get('error', 'serve down failed'))
        logger.warning(f'serve down reported failure '
                       f'({resp.get("error")}); purging '
                       f'{service_name!r} locally.')
        _purge_replica_clusters(service_name)


def _purge_replica_clusters(service_name: str) -> None:
    """Best-effort teardown of clusters named like this service's
    replicas, using the client-side cluster table.

    Scope caveat: replicas are launched BY the controller process, so on
    a remote controller host their records live in ITS state DB, not the
    client's — this purge can only clean what the client can see (on the
    local provider that is everything, since the state dir is shared).
    Clusters it cannot see must be cleaned from the controller host or
    the cloud console; we log the limitation rather than claim success."""
    logger.warning('Purge uses the client-side cluster table; replica '
                   'clusters recorded only on the (unreachable) '
                   'controller host are not covered.')
    from skypilot_tpu import core as sky_core
    prefix = f'{service_name}-replica-'
    for record in global_state.get_clusters():
        if record['name'].startswith(prefix):
            try:
                sky_core.down(record['name'])
            except Exception as e:  # pylint: disable=broad-except
                logger.warning(f'Purge: teardown of {record["name"]} '
                               f'failed (it may leak): '
                               f'{type(e).__name__}: {e}')


def tail_logs(service_name: str, follow: bool = True) -> None:
    """Stream the service process log (controller+LB trace)."""
    from skypilot_tpu.backend import tpu_backend
    handle = _get_controller_handle()
    backend = tpu_backend.TpuVmBackend()
    for j in backend.get_job_queue(handle):
        if j['name'] == f'service-{service_name}':
            backend.tail_logs(handle, j['job_id'], follow=follow)
            return
    raise exceptions.ServiceNotFoundError(
        f'No service process found for {service_name!r}.')
