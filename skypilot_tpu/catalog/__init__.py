"""Service catalog: instance types × regions × zones × prices.

Functional parity with reference ``sky/clouds/service_catalog/__init__.py``
(``list_accelerators`` ``:60``, ``get_hourly_cost`` ``:195``, ``get_tpus``
``:340``) with a much smaller surface: one provider (GCP), one checked-in CSV,
stdlib csv instead of pandas (the catalog is small; a DataFrame buys nothing).
"""
from skypilot_tpu.catalog.catalog import (
    CatalogEntry,
    get_catalog,
    get_hourly_cost,
    get_instance_type_for_cpus,
    get_tpus,
    instance_type_exists,
    list_accelerators,
    validate_region_zone,
    zones_for_accelerator,
)

__all__ = [
    'CatalogEntry',
    'get_catalog',
    'get_hourly_cost',
    'get_instance_type_for_cpus',
    'get_tpus',
    'instance_type_exists',
    'list_accelerators',
    'validate_region_zone',
    'zones_for_accelerator',
]
