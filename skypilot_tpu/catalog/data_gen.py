"""Generate the static GCP catalog CSV.

Plays the role of the reference's catalog data fetchers
(``sky/clouds/service_catalog/data_fetchers/fetch_gcp.py`` — which hard-codes
TPU availability tables at ``:73-92``). We have zero egress, so the tables are
checked in; prices are approximations of GCP list prices (the optimizer only
needs correct *relative* ordering and the failover loop needs real
region/zone shapes).

Run:  python -m skypilot_tpu.catalog.data_gen
"""
from __future__ import annotations

import csv
import os

from skypilot_tpu.accelerators import TPU_GENERATIONS

# generation -> (price per chip-hour on-demand, zones)
_TPU_AVAILABILITY = {
    'v2': (1.125, ['us-central1-b', 'us-central1-c', 'us-central1-f',
                   'europe-west4-a', 'asia-east1-c']),
    'v3': (2.00, ['us-central1-a', 'us-central1-b', 'europe-west4-a']),
    'v4': (3.22, ['us-central2-b']),
    'v5e': (1.20, ['us-central1-a', 'us-west4-a', 'us-east1-c', 'us-east5-a',
                   'europe-west4-b', 'asia-southeast1-b']),
    'v5p': (4.20, ['us-east5-a', 'us-central1-a', 'europe-west4-b']),
    'v6e': (2.70, ['us-east5-b', 'us-east1-d', 'europe-west4-a',
                   'asia-northeast1-b']),
}
_SPOT_DISCOUNT = 0.43  # spot price ~= 43% of on-demand

# Slice sizes offered per generation (in the generation's naming unit).
_TPU_SLICE_SIZES = {
    'v2': [8, 32, 128, 256, 512],
    'v3': [8, 32, 128, 256, 512, 1024],
    'v4': [8, 16, 32, 64, 128, 256, 512, 1024, 2048],
    'v5e': [1, 4, 8, 16, 32, 64, 128, 256],
    'v5p': [8, 16, 32, 64, 128, 256, 512, 1024],
    'v6e': [1, 4, 8, 16, 32, 64, 128, 256],
}

# TPU-VM host shapes (vCPU / GiB per host), approximating GCP machine specs.
_TPU_HOST_SHAPE = {
    'v2': (96, 335), 'v3': (96, 335), 'v4': (240, 407),
    'v5e': (112, 192), 'v5p': (208, 448), 'v6e': (180, 720),
}

# GPU + CPU VMs: (instance_type, accel_name, accel_count, vcpus, mem, price,
#                 regions)
_GPU_VMS = [
    ('a2-highgpu-1g', 'A100', 1, 12, 85, 3.67),
    ('a2-highgpu-4g', 'A100', 4, 48, 340, 14.69),
    ('a2-highgpu-8g', 'A100', 8, 96, 680, 29.39),
    ('a2-ultragpu-8g', 'A100-80GB', 8, 96, 1360, 40.22),
    ('a3-highgpu-8g', 'H100', 8, 208, 1872, 88.25),
    ('g2-standard-4', 'L4', 1, 4, 16, 0.71),
    ('g2-standard-48', 'L4', 4, 48, 192, 3.99),
    ('n1-standard-8+T4', 'T4', 1, 8, 30, 0.73),
    ('n1-standard-8+V100', 'V100', 1, 8, 30, 2.86),
]
_GPU_REGIONS = ['us-central1-a', 'us-central1-b', 'us-east1-c',
                'europe-west4-a', 'asia-east1-a']

_CPU_VMS = [
    ('n2-standard-2', 2, 8, 0.097),
    ('n2-standard-4', 4, 16, 0.194),
    ('n2-standard-8', 8, 32, 0.388),
    ('n2-standard-16', 16, 64, 0.777),
    ('n2-standard-32', 32, 128, 1.554),
    ('n2-highmem-8', 8, 64, 0.524),
    ('e2-standard-4', 4, 16, 0.134),
    ('e2-standard-8', 8, 32, 0.268),
]
_CPU_REGIONS = ['us-central1-a', 'us-central1-b', 'us-central2-b',
                'us-east1-c', 'us-east5-a', 'us-east5-b', 'us-west4-a',
                'europe-west4-a', 'europe-west4-b', 'asia-east1-a',
                'asia-southeast1-b', 'asia-northeast1-b']

FIELDS = ['InstanceType', 'AcceleratorName', 'AcceleratorCount', 'vCPUs',
          'MemoryGiB', 'Price', 'SpotPrice', 'Region', 'AvailabilityZone']


def generate_rows():
    rows = []
    # TPUs: InstanceType is the synthetic 'TPU-VM' (reference prices TPU-VM
    # hosts at zero and bills the accelerator:
    # sky/clouds/service_catalog/gcp_catalog.py:222-244). We instead fold the
    # whole slice cost into the accelerator price and expose host shape.
    for gen_name, (chip_price, zones) in _TPU_AVAILABILITY.items():
        gen = TPU_GENERATIONS[gen_name]
        vcpus, mem = _TPU_HOST_SHAPE[gen_name]
        for size in _TPU_SLICE_SIZES[gen_name]:
            chips = size // gen.cores_per_chip if gen.names_by_cores else size
            if chips < 1:
                continue
            name = f'tpu-{gen_name}-{size}'
            price = chip_price * chips
            spot = round(price * _SPOT_DISCOUNT, 4)
            hosts = max(1, chips // gen.chips_per_host)
            for zone in zones:
                region = zone.rsplit('-', 1)[0]
                rows.append(['TPU-VM', name, 1, vcpus * hosts, mem * hosts,
                             round(price, 4), spot, region, zone])
    for (itype, acc, cnt, vcpus, mem, price) in _GPU_VMS:
        for zone in _GPU_REGIONS:
            region = zone.rsplit('-', 1)[0]
            rows.append([itype, acc, cnt, vcpus, mem, price,
                         round(price * _SPOT_DISCOUNT, 4), region, zone])
    for (itype, vcpus, mem, price) in _CPU_VMS:
        for zone in _CPU_REGIONS:
            region = zone.rsplit('-', 1)[0]
            rows.append([itype, '', '', vcpus, mem, price,
                         round(price * _SPOT_DISCOUNT, 4), region, zone])
    return rows


def main():
    out = os.path.join(os.path.dirname(__file__), 'data', 'gcp.csv')
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, 'w', newline='', encoding='utf-8') as f:
        w = csv.writer(f)
        w.writerow(FIELDS)
        w.writerows(generate_rows())
    print(f'wrote {out}')


if __name__ == '__main__':
    main()
