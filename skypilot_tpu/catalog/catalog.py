"""In-memory catalog over the checked-in CSV data."""
from __future__ import annotations

import csv
import dataclasses
import functools
import os
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import exceptions

_DATA_DIR = os.path.join(os.path.dirname(__file__), 'data')


@dataclasses.dataclass(frozen=True)
class CatalogEntry:
    instance_type: str
    accelerator_name: Optional[str]
    accelerator_count: int
    vcpus: float
    memory_gib: float
    price: float          # on-demand $/hr (whole slice for TPUs)
    spot_price: float
    region: str
    zone: str

    @property
    def is_tpu(self) -> bool:
        return (self.accelerator_name or '').startswith('tpu-')

    def hourly_cost(self, use_spot: bool) -> float:
        return self.spot_price if use_spot else self.price


@functools.lru_cache(maxsize=None)
def get_catalog(cloud: str = 'gcp') -> Tuple[CatalogEntry, ...]:
    path = os.path.join(_DATA_DIR, f'{cloud.lower()}.csv')
    if not os.path.exists(path):
        raise exceptions.NoCloudAccessError(
            f'No catalog data for cloud {cloud!r} at {path}.')
    entries: List[CatalogEntry] = []
    with open(path, newline='', encoding='utf-8') as f:
        for row in csv.DictReader(f):
            entries.append(CatalogEntry(
                instance_type=row['InstanceType'],
                accelerator_name=row['AcceleratorName'] or None,
                accelerator_count=int(float(row['AcceleratorCount'])
                                      ) if row['AcceleratorCount'] else 0,
                vcpus=float(row['vCPUs']),
                memory_gib=float(row['MemoryGiB']),
                price=float(row['Price']),
                spot_price=float(row['SpotPrice']),
                region=row['Region'],
                zone=row['AvailabilityZone'],
            ))
    return tuple(entries)


def list_accelerators(cloud: str = 'gcp',
                      name_filter: Optional[str] = None,
                      require_price: bool = False
                      ) -> Dict[str, List[CatalogEntry]]:
    """accelerator name -> entries (dedup by (name, count, region))."""
    del require_price  # all entries are priced
    out: Dict[str, List[CatalogEntry]] = {}
    for e in get_catalog(cloud):
        if e.accelerator_name is None:
            continue
        if name_filter and name_filter.lower() not in e.accelerator_name.lower():
            continue
        out.setdefault(e.accelerator_name, []).append(e)
    return out


def get_tpus(cloud: str = 'gcp') -> Dict[str, List[CatalogEntry]]:
    """Reference ``service_catalog.get_tpus`` (``__init__.py:340``)."""
    return {name: entries
            for name, entries in list_accelerators(cloud).items()
            if name.startswith('tpu-')}


def zones_for_accelerator(accelerator_name: str,
                          count: int = 1,
                          region: Optional[str] = None,
                          cloud: str = 'gcp') -> List[CatalogEntry]:
    """All zone-level entries offering the accelerator, cheapest first."""
    entries = [e for e in get_catalog(cloud)
               if e.accelerator_name == accelerator_name
               and e.accelerator_count >= count
               and (region is None or e.region == region)]
    return sorted(entries, key=lambda e: (e.price, e.zone))


def get_instance_type_for_cpus(cpus: Optional[float] = None,
                               memory_gib: Optional[float] = None,
                               at_least: bool = True,
                               region: Optional[str] = None,
                               cloud: str = 'gcp'
                               ) -> Optional[CatalogEntry]:
    """Cheapest CPU-only instance meeting the cpu/memory requirement."""
    best: Optional[CatalogEntry] = None
    for e in get_catalog(cloud):
        if e.accelerator_name is not None:
            continue
        if region is not None and e.region != region:
            continue
        if cpus is not None:
            if at_least and e.vcpus < cpus:
                continue
            if not at_least and e.vcpus != cpus:
                continue
        if memory_gib is not None and e.memory_gib < memory_gib:
            continue
        if best is None or e.price < best.price:
            best = e
    return best


def instance_type_exists(instance_type: str, cloud: str = 'gcp') -> bool:
    return any(e.instance_type == instance_type for e in get_catalog(cloud))


def get_hourly_cost(instance_type: str,
                    use_spot: bool = False,
                    region: Optional[str] = None,
                    zone: Optional[str] = None,
                    accelerator_name: Optional[str] = None,
                    cloud: str = 'gcp') -> float:
    """$/hr for an instance type (TPUs: pass accelerator_name, whole slice)."""
    for e in get_catalog(cloud):
        if e.instance_type != instance_type:
            continue
        if accelerator_name and e.accelerator_name != accelerator_name:
            continue
        if region and e.region != region:
            continue
        if zone and e.zone != zone:
            continue
        return e.hourly_cost(use_spot)
    raise exceptions.InvalidResourcesError(
        f'No catalog entry for {instance_type} '
        f'(accel={accelerator_name}, region={region}, zone={zone}).')


def validate_region_zone(region: Optional[str], zone: Optional[str],
                         cloud: str = 'gcp') -> None:
    if region is None and zone is None:
        return
    for e in get_catalog(cloud):
        if region is not None and e.region != region:
            continue
        if zone is not None and e.zone != zone:
            continue
        return
    raise exceptions.InvalidResourcesError(
        f'Region/zone not found in {cloud} catalog: '
        f'region={region!r} zone={zone!r}')
