"""Client-side global state: SQLite DB of clusters / storage / enabled clouds.

Role of reference ``sky/global_user_state.py`` (``create_table``
``sky/global_user_state.py:34``, ``add_or_update_cluster`` ``:148``). The DB
lives under the state dir (``SKYTPU_STATE_DIR``, default ``~/.skytpu``), so
tests isolate state by pointing the env var at a tmp dir.

Cluster handles are stored as pickles with a ``_VERSION`` guard (reference
versioned-pickle idea for client/controller skew).
"""
from __future__ import annotations

import enum
import json
import os
import pickle
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import common_utils


class ClusterStatus(enum.Enum):
    """Cluster lifecycle (reference ``sky/status_lib.py`` semantics:
    INIT = partially provisioned / unknown; UP = runtime healthy;
    STOPPED = instances stopped, disk kept)."""
    INIT = 'INIT'
    UP = 'UP'
    STOPPED = 'STOPPED'

    def colored(self) -> str:
        color = {'INIT': '\x1b[33m', 'UP': '\x1b[32m',
                 'STOPPED': '\x1b[90m'}[self.value]
        return f'{color}{self.value}\x1b[0m'


class StorageStatus(enum.Enum):
    INIT = 'INIT'
    UPLOAD_FAILED = 'UPLOAD_FAILED'
    READY = 'READY'


_lock = threading.Lock()
_conn_cache: Dict[str, sqlite3.Connection] = {}


def _db_path() -> str:
    return os.path.join(common_utils.state_dir(), 'state.db')


def _get_conn() -> sqlite3.Connection:
    """One connection per (path, thread-shared with check_same_thread off,
    guarded by _lock for writes)."""
    path = _db_path()
    with _lock:
        conn = _conn_cache.get(path)
        if conn is None:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            conn = sqlite3.connect(path, check_same_thread=False)
            conn.execute('PRAGMA journal_mode=WAL')
            _create_tables(conn)
            _conn_cache[path] = conn
        return conn


def _create_tables(conn: sqlite3.Connection) -> None:
    cur = conn.cursor()
    cur.execute("""\
        CREATE TABLE IF NOT EXISTS clusters (
            name TEXT PRIMARY KEY,
            launched_at INTEGER,
            handle BLOB,
            last_use TEXT,
            status TEXT,
            autostop INTEGER DEFAULT -1,
            to_down INTEGER DEFAULT 0,
            owner TEXT DEFAULT null,
            cluster_hash TEXT DEFAULT null,
            launched_resources TEXT DEFAULT null,
            usage_intervals BLOB DEFAULT null)""")
    cur.execute("""\
        CREATE TABLE IF NOT EXISTS cluster_history (
            cluster_hash TEXT PRIMARY KEY,
            name TEXT,
            num_nodes INTEGER,
            requested_resources TEXT,
            launched_resources TEXT,
            usage_intervals BLOB)""")
    cur.execute("""\
        CREATE TABLE IF NOT EXISTS storage (
            name TEXT PRIMARY KEY,
            launched_at INTEGER,
            handle BLOB,
            last_use TEXT,
            status TEXT)""")
    cur.execute("""\
        CREATE TABLE IF NOT EXISTS enabled_clouds (
            cloud TEXT PRIMARY KEY)""")
    cur.execute("""\
        CREATE TABLE IF NOT EXISTS config (
            key TEXT PRIMARY KEY,
            value TEXT)""")
    conn.commit()


# ---------------------------------------------------------------- clusters
def add_or_update_cluster(cluster_name: str,
                          cluster_handle: Any,
                          requested_resources: Optional[Any] = None,
                          ready: bool = False,
                          is_launch: bool = True) -> None:
    """Upsert a cluster row (reference ``add_or_update_cluster``)."""
    status = ClusterStatus.UP if ready else ClusterStatus.INIT
    handle_blob = pickle.dumps(cluster_handle)
    now = int(time.time())
    conn = _get_conn()
    with _lock:
        row = conn.execute(
            'SELECT usage_intervals, launched_at FROM clusters WHERE name=?',
            (cluster_name,)).fetchone()
        usage_intervals: List = []
        launched_at = now
        if row is not None:
            usage_intervals = pickle.loads(row[0]) if row[0] else []
            launched_at = row[1] or now
        if is_launch:
            if not usage_intervals or usage_intervals[-1][1] is not None:
                usage_intervals.append([now, None])
        launched_resources = None
        handle_res = getattr(cluster_handle, 'launched_resources', None)
        if handle_res is not None:
            launched_resources = json.dumps(handle_res.to_yaml_config())
        conn.execute(
            """INSERT INTO clusters
               (name, launched_at, handle, last_use, status, autostop,
                owner, cluster_hash, launched_resources, usage_intervals)
               VALUES (?,?,?,?,?,
                       COALESCE((SELECT autostop FROM clusters WHERE name=?),
                                -1),
                       ?,?,?,?)
               ON CONFLICT(name) DO UPDATE SET
                 handle=excluded.handle, last_use=excluded.last_use,
                 status=excluded.status,
                 launched_resources=excluded.launched_resources,
                 usage_intervals=excluded.usage_intervals""",
            (cluster_name, launched_at, handle_blob, _last_use(), status.value,
             cluster_name, common_utils.get_user_hash(),
             getattr(cluster_handle, 'cluster_hash', None),
             launched_resources, pickle.dumps(usage_intervals)))
        conn.commit()


def _last_use() -> str:
    import sys
    if not sys.argv:
        return 'api'
    parts = [os.path.basename(sys.argv[0])] + sys.argv[1:]
    return ' '.join(parts)[:200]


def update_cluster_status(cluster_name: str,
                          status: ClusterStatus) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute('UPDATE clusters SET status=? WHERE name=?',
                     (status.value, cluster_name))
        conn.commit()


def update_last_use(cluster_name: str) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute('UPDATE clusters SET last_use=? WHERE name=?',
                     (_last_use(), cluster_name))
        conn.commit()


def remove_cluster(cluster_name: str, terminate: bool) -> None:
    """On stop: close the usage interval, keep row as STOPPED. On
    terminate: archive usage to cluster_history and drop the row."""
    conn = _get_conn()
    now = int(time.time())
    with _lock:
        row = conn.execute(
            'SELECT usage_intervals, cluster_hash, launched_resources, handle '
            'FROM clusters WHERE name=?', (cluster_name,)).fetchone()
        if row is None:
            return
        usage_intervals = pickle.loads(row[0]) if row[0] else []
        if usage_intervals and usage_intervals[-1][1] is None:
            usage_intervals[-1][1] = now
        if terminate:
            cluster_hash = row[1] or cluster_name
            handle = pickle.loads(row[3]) if row[3] else None
            num_nodes = getattr(handle, 'num_nodes', None)
            conn.execute(
                """INSERT OR REPLACE INTO cluster_history
                   (cluster_hash, name, num_nodes, requested_resources,
                    launched_resources, usage_intervals)
                   VALUES (?,?,?,?,?,?)""",
                (cluster_hash, cluster_name, num_nodes, None, row[2],
                 pickle.dumps(usage_intervals)))
            conn.execute('DELETE FROM clusters WHERE name=?', (cluster_name,))
        else:
            conn.execute(
                'UPDATE clusters SET status=?, usage_intervals=? '
                'WHERE name=?',
                (ClusterStatus.STOPPED.value, pickle.dumps(usage_intervals),
                 cluster_name))
        conn.commit()


def get_cluster_from_name(
        cluster_name: str) -> Optional[Dict[str, Any]]:
    conn = _get_conn()
    row = conn.execute(
        'SELECT name, launched_at, handle, last_use, status, autostop, '
        'to_down, owner, launched_resources, usage_intervals '
        'FROM clusters WHERE name=?', (cluster_name,)).fetchone()
    if row is None:
        return None
    return _cluster_row_to_record(row)


def _cluster_row_to_record(row) -> Dict[str, Any]:
    return {
        'name': row[0],
        'launched_at': row[1],
        'handle': pickle.loads(row[2]) if row[2] else None,
        'last_use': row[3],
        'status': ClusterStatus(row[4]),
        'autostop': row[5],
        'to_down': bool(row[6]),
        'owner': row[7],
        'launched_resources': json.loads(row[8]) if row[8] else None,
        'usage_intervals': pickle.loads(row[9]) if row[9] else [],
    }


def get_clusters() -> List[Dict[str, Any]]:
    conn = _get_conn()
    rows = conn.execute(
        'SELECT name, launched_at, handle, last_use, status, autostop, '
        'to_down, owner, launched_resources, usage_intervals '
        'FROM clusters ORDER BY launched_at DESC').fetchall()
    return [_cluster_row_to_record(r) for r in rows]


def get_handle_from_cluster_name(cluster_name: str) -> Optional[Any]:
    record = get_cluster_from_name(cluster_name)
    return record['handle'] if record else None


def set_cluster_autostop(cluster_name: str, idle_minutes: int,
                         to_down: bool = False) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute(
            'UPDATE clusters SET autostop=?, to_down=? WHERE name=?',
            (idle_minutes, int(to_down), cluster_name))
        conn.commit()


def get_cluster_usage_hours(cluster_name_or_hash: str) -> float:
    """Total up-hours from usage intervals (live + history)."""
    conn = _get_conn()
    now = int(time.time())
    total = 0.0
    for table, col in (('clusters', 'name'),
                       ('cluster_history', 'cluster_hash'),
                       ('cluster_history', 'name')):
        rows = conn.execute(
            f'SELECT usage_intervals FROM {table} WHERE {col}=?',
            (cluster_name_or_hash,)).fetchall()
        for (blob,) in rows:
            if not blob:
                continue
            for start, end in pickle.loads(blob):
                total += ((end or now) - start) / 3600.0
        if total:
            break
    return total


def get_cluster_history() -> List[Dict[str, Any]]:
    conn = _get_conn()
    rows = conn.execute(
        'SELECT cluster_hash, name, num_nodes, launched_resources, '
        'usage_intervals FROM cluster_history').fetchall()
    out = []
    for row in rows:
        out.append({
            'cluster_hash': row[0],
            'name': row[1],
            'num_nodes': row[2],
            'launched_resources': json.loads(row[3]) if row[3] else None,
            'usage_intervals': pickle.loads(row[4]) if row[4] else [],
        })
    return out


# ---------------------------------------------------------------- storage
def add_or_update_storage(storage_name: str, storage_handle: Any,
                          storage_status: StorageStatus) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute(
            'INSERT OR REPLACE INTO storage VALUES (?,?,?,?,?)',
            (storage_name, int(time.time()), pickle.dumps(storage_handle),
             _last_use(), storage_status.value))
        conn.commit()


def remove_storage(storage_name: str) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute('DELETE FROM storage WHERE name=?', (storage_name,))
        conn.commit()


def get_storage() -> List[Dict[str, Any]]:
    conn = _get_conn()
    rows = conn.execute(
        'SELECT name, launched_at, handle, last_use, status '
        'FROM storage').fetchall()
    return [{
        'name': r[0], 'launched_at': r[1],
        'handle': pickle.loads(r[2]) if r[2] else None,
        'last_use': r[3], 'status': StorageStatus(r[4]),
    } for r in rows]


def get_storage_from_name(storage_name: str) -> Optional[Dict[str, Any]]:
    for record in get_storage():
        if record['name'] == storage_name:
            return record
    return None


# ---------------------------------------------------------------- clouds
def set_enabled_clouds(clouds: List[str]) -> None:
    conn = _get_conn()
    with _lock:
        conn.execute('DELETE FROM enabled_clouds')
        conn.executemany('INSERT INTO enabled_clouds VALUES (?)',
                         [(c,) for c in clouds])
        conn.commit()


def get_enabled_clouds() -> List[str]:
    conn = _get_conn()
    return [r[0] for r in
            conn.execute('SELECT cloud FROM enabled_clouds').fetchall()]
