"""Usage telemetry: local-first event recording with an optional push
endpoint.

Role of reference ``sky/usage/usage_lib.py`` (messages assembled per
command and POSTed to a Loki collector, opt-out via env): here events
spool to ``{state_dir}/usage/usage.jsonl`` always-local-first; if
``usage.endpoint`` is configured they are also POSTed (best-effort,
never blocking a command on telemetry). Opt out entirely with
``SKYTPU_DISABLE_USAGE_COLLECTION=1``.
"""
from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Dict, List, Optional

from skypilot_tpu import config as config_lib
from skypilot_tpu import tpu_logging
from skypilot_tpu.utils import common_utils

logger = tpu_logging.init_logger(__name__)

_run_id: Optional[str] = None


def disabled() -> bool:
    return os.environ.get('SKYTPU_DISABLE_USAGE_COLLECTION', '0') == '1'


def run_id() -> str:
    global _run_id
    if _run_id is None:
        _run_id = str(uuid.uuid4())[:8]
    return _run_id


def _spool_path() -> str:
    d = os.path.join(common_utils.state_dir(), 'usage')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, 'usage.jsonl')


def record(event: str, **fields: Any) -> None:
    """Append one usage event; never raises into the caller."""
    if disabled():
        return
    entry = {
        'time': time.time(),
        'run_id': run_id(),
        'event': event,
        'user': common_utils.get_cleaned_username(),
        **{k: v for k, v in fields.items() if v is not None},
    }
    try:
        with open(_spool_path(), 'a', encoding='utf-8') as f:
            f.write(json.dumps(entry) + '\n')
    except OSError:
        return
    _maybe_push(entry)


def _maybe_push(entry: Dict[str, Any]) -> None:
    endpoint = config_lib.get_nested(('usage', 'endpoint'), None)
    if not endpoint:
        return

    def _post():
        try:
            import urllib.request
            req = urllib.request.Request(
                endpoint, data=json.dumps(entry).encode(),
                headers={'Content-Type': 'application/json'})
            urllib.request.urlopen(req, timeout=2)
        except Exception as e:  # pylint: disable=broad-except
            # Telemetry must never break a command — but the failure
            # should still be observable under SKYTPU_DEBUG.
            logger.debug(f'usage push failed: {type(e).__name__}: {e}')

    # Fire-and-forget: a slow/unreachable collector must not stall the
    # command path.
    import threading
    threading.Thread(target=_post, daemon=True).start()


def entries(limit: int = 0) -> List[Dict[str, Any]]:
    try:
        with open(_spool_path(), encoding='utf-8') as f:
            lines = f.readlines()
    except FileNotFoundError:
        return []
    if limit:
        lines = lines[-limit:]
    out = []
    for line in lines:
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return out
