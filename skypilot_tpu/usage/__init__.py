"""Usage telemetry (reference ``sky/usage/``): local-first event spool
with an optional push endpoint; opt out with
SKYTPU_DISABLE_USAGE_COLLECTION=1."""
from skypilot_tpu.usage.usage_lib import disabled, entries, record

__all__ = ['disabled', 'entries', 'record']
