"""Execution pipeline: the OPTIMIZE→PROVISION→SYNC→SETUP→EXEC stage
machine behind ``launch`` / ``exec``.

Role of reference ``sky/execution.py`` (``Stage`` ``:31``, ``_execute``
``:95``, ``launch`` ``:368``, ``exec`` ``:553``).
"""
from __future__ import annotations

import enum
from typing import Any, List, Optional, Tuple, Union

from skypilot_tpu import admin_policy as admin_policy_lib
from skypilot_tpu import exceptions
from skypilot_tpu import optimizer as optimizer_lib
from skypilot_tpu import tpu_logging
from skypilot_tpu.backend import tpu_backend
from skypilot_tpu.dag import Dag
from skypilot_tpu.task import Task
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import timeline

logger = tpu_logging.init_logger(__name__)


class Stage(enum.Enum):
    OPTIMIZE = enum.auto()
    PROVISION = enum.auto()
    SYNC_WORKDIR = enum.auto()
    SYNC_FILE_MOUNTS = enum.auto()
    SETUP = enum.auto()
    EXEC = enum.auto()
    DOWN = enum.auto()


def _to_dag(task_or_dag: Union[Task, Dag]) -> Dag:
    if isinstance(task_or_dag, Dag):
        return task_or_dag
    dag = Dag(name=task_or_dag.name)
    dag.add(task_or_dag)
    return dag


def _execute(
    dag: Dag,
    *,
    cluster_name: Optional[str],
    stages: Optional[List[Stage]],
    dryrun: bool,
    detach_run: bool,
    idle_minutes_to_autostop: Optional[int],
    down: bool,
    retry_until_up: bool,
    no_setup: bool,
) -> Tuple[Optional[int], Optional[Any]]:
    if len(dag) != 1:
        raise exceptions.NotSupportedError(
            'launch/exec support single-task dags; use jobs.launch for '
            'pipelines.')
    dag = admin_policy_lib.apply(dag)
    task = dag.topological_order()[0]
    if cluster_name is None:
        cluster_name = common_utils.generate_cluster_name()
    common_utils.check_cluster_name_is_valid(cluster_name)
    stages = stages or list(Stage)

    backend = tpu_backend.TpuVmBackend()
    handle = None
    job_id = None

    if Stage.OPTIMIZE in stages:
        with timeline.Event('optimize'):
            optimizer_lib.optimize(dag, quiet=tpu_logging.is_silent())
    if Stage.PROVISION in stages:
        with timeline.Event('provision', cluster=cluster_name):
            handle = backend.provision(task, task.best_resources,
                                       cluster_name=cluster_name,
                                       dryrun=dryrun,
                                       retry_until_up=retry_until_up)
        if dryrun:
            logger.info('Dryrun finished (optimize + plan only).')
            return None, None
    else:
        from skypilot_tpu.backend import backend_utils
        handle = backend_utils.check_cluster_available(cluster_name)

    assert handle is not None
    if Stage.SYNC_WORKDIR in stages and task.workdir:
        with timeline.Event('sync_workdir'):
            backend.sync_workdir(handle, task.workdir)
    if Stage.SYNC_FILE_MOUNTS in stages and (task.file_mounts
                                             or task.storage_mounts):
        with timeline.Event('sync_file_mounts'):
            backend.sync_file_mounts(handle, task.file_mounts,
                                     task.storage_mounts)
    if Stage.SETUP in stages and not no_setup:
        with timeline.Event('setup', cluster=cluster_name):
            backend.setup(handle, task)
    if down and idle_minutes_to_autostop is None:
        # `down` means "tear down after the job queue drains", not "tear
        # down now" — with a detached job an immediate teardown would
        # kill the job it just submitted. Autostop-at-idle implements
        # the intended semantics.
        idle_minutes_to_autostop = 0
    try:
        if Stage.EXEC in stages:
            try:
                with timeline.Event('exec', cluster=cluster_name):
                    job_id = backend.execute(handle, task,
                                             detach_run=detach_run)
            finally:
                backend.post_execute(handle, down)
    finally:
        # Armed AFTER the job is queued: with idle=0 an earlier arm could
        # tear the cluster down before queue_job lands on the agent. The
        # finally makes sure a failed submission still leaves the
        # user-requested autostop armed rather than a forever-idle
        # cluster.
        if idle_minutes_to_autostop is not None:
            backend.set_autostop(handle, idle_minutes_to_autostop,
                                 down=down)
    if Stage.DOWN in stages and down and Stage.EXEC not in stages:
        # Explicit DOWN stage with nothing submitted: tear down now.
        backend.teardown(handle, terminate=True)
    return job_id, handle


def launch(
    task: Union[Task, Dag],
    cluster_name: Optional[str] = None,
    *,
    dryrun: bool = False,
    detach_run: bool = True,
    stream_logs: bool = False,
    idle_minutes_to_autostop: Optional[int] = None,
    down: bool = False,
    retry_until_up: bool = False,
    no_setup: bool = False,
) -> Tuple[Optional[int], Optional[Any]]:
    """Provision (or reuse) a cluster and run the task on it.

    Returns (job_id, handle). Reference ``sky.launch``
    (``sky/execution.py:368``)."""
    job_id, handle = _execute(
        _to_dag(task),
        cluster_name=cluster_name,
        stages=None,
        dryrun=dryrun,
        detach_run=detach_run and not stream_logs,
        idle_minutes_to_autostop=idle_minutes_to_autostop,
        down=down,
        retry_until_up=retry_until_up,
        no_setup=no_setup,
    )
    return job_id, handle


def exec_cmd(  # pylint: disable=redefined-builtin
    task: Union[Task, Dag],
    cluster_name: str,
    *,
    detach_run: bool = True,
    dryrun: bool = False,
) -> Tuple[Optional[int], Optional[Any]]:
    """Run a task on an existing UP cluster: skips provision/setup
    (reference ``sky.exec`` ``sky/execution.py:553``)."""
    return _execute(
        _to_dag(task),
        cluster_name=cluster_name,
        stages=[Stage.SYNC_WORKDIR, Stage.SYNC_FILE_MOUNTS, Stage.EXEC],
        dryrun=dryrun,
        detach_run=detach_run,
        idle_minutes_to_autostop=None,
        down=False,
        retry_until_up=False,
        no_setup=True,
    )
