"""Exception hierarchy for skypilot_tpu.

Mirrors the role of the reference's ``sky/exceptions.py`` (error taxonomy that
the failover loop keys on), re-designed around TPU provisioning semantics:
queued-resource timeouts and slice preemption are first-class failover signals
(see reference failure taxonomy at
``sky/backends/cloud_vm_ray_backend.py:1031-1086``).
"""
from __future__ import annotations

from typing import List, Optional


class SkyTpuError(Exception):
    """Base class for all framework errors."""


# --- Spec / validation -----------------------------------------------------
class InvalidTaskError(SkyTpuError):
    """Task YAML / Task object failed validation."""


class InvalidResourcesError(SkyTpuError):
    """Resources spec is malformed (unknown accelerator, bad topology...)."""


class InvalidDagError(SkyTpuError):
    """DAG is malformed (cycles, unsupported shape)."""


# --- Optimizer -------------------------------------------------------------
class ResourcesUnavailableError(SkyTpuError):
    """No feasible (cloud, region, zone, type) satisfies the request.

    Carries ``no_failover`` when retrying with different resources is
    pointless (e.g. user pinned a zone that is out of capacity and asked for
    no failover).
    """

    def __init__(self, message: str, no_failover: bool = False):
        super().__init__(message)
        self.no_failover = no_failover


class ResourcesMismatchError(SkyTpuError):
    """Requested resources do not match an existing cluster's resources."""


class NoCloudAccessError(SkyTpuError):
    """No cloud is enabled/credentialed."""


# --- Provisioning ----------------------------------------------------------
class ProvisionError(SkyTpuError):
    """Provisioning failed; carries a blocklist hint for the failover loop."""

    #: Scope the failover should blocklist: 'zone' | 'region' | 'cloud'.
    blocklist_scope: str = 'zone'


class QuotaExceededError(ProvisionError):
    """Cloud quota prevents provisioning in a region; blocklist the region.

    A ProvisionError subclass so the failover loop catches and
    blocklists it rather than crashing the launch."""
    blocklist_scope = 'region'


class InsufficientCapacityError(ProvisionError):
    """Stockout: the zone has no capacity for the slice type."""
    blocklist_scope = 'zone'


class QueuedResourceTimeoutError(ProvisionError):
    """Queued-resource request sat in WAITING/PROVISIONING beyond deadline.

    TPU-specific: the queued-resources API is async accept->provision; a
    too-long queue is treated like a stockout so the optimizer can move on.
    """
    blocklist_scope = 'zone'


class PreemptedDuringProvisionError(ProvisionError):
    """Spot/preemptible slice was reclaimed before setup finished."""
    blocklist_scope = 'zone'


class ClusterOwnerIdentityMismatchError(SkyTpuError):
    """Cluster was created by a different cloud identity."""


class CommandError(SkyTpuError):
    """A remote command failed."""

    def __init__(self, returncode: int, command: str, error_msg: str = '',
                 detailed_reason: Optional[str] = None):
        self.returncode = returncode
        self.command = command
        self.error_msg = error_msg
        self.detailed_reason = detailed_reason
        msg = (f'Command failed with return code {returncode}: {command}'
               + (f'\n{error_msg}' if error_msg else ''))
        super().__init__(msg)


# --- Cluster state ---------------------------------------------------------
class ClusterNotUpError(SkyTpuError):
    """Operation requires an UP cluster."""


class ClusterDoesNotExist(SkyTpuError):
    """Named cluster not found in global state."""


class NotSupportedError(SkyTpuError):
    """Operation not supported for this cloud/cluster (e.g. stop TPU pod)."""


# --- Jobs ------------------------------------------------------------------
class JobNotFoundError(SkyTpuError):
    """Job id not present in the job table."""


class ManagedJobReachedMaxRetriesError(SkyTpuError):
    """Managed job exhausted recovery attempts."""


class ManagedJobStatusError(SkyTpuError):
    """Managed job is in a state that does not allow the operation."""


# --- Serve -----------------------------------------------------------------
class ServeUserTerminatedError(SkyTpuError):
    """Service was torn down by the user while an operation was in flight."""


class ServiceNotFoundError(SkyTpuError):
    """Named service not found."""


class InvalidServiceSpecError(SkyTpuError):
    """Malformed ``service:`` section in a task YAML."""


# --- Storage ---------------------------------------------------------------
class StorageError(SkyTpuError):
    """Base class for storage errors."""


class StorageBucketCreateError(StorageError):
    pass


class StorageBucketGetError(StorageError):
    pass


class StorageBucketDeleteError(StorageError):
    pass


class StorageUploadError(StorageError):
    pass


class StorageModeError(StorageError):
    pass


class StorageSpecError(StorageError):
    pass


# --- Misc ------------------------------------------------------------------
class ApiError(SkyTpuError):
    """Cloud REST API returned an error; wraps status code + body."""

    def __init__(self, message: str, status: Optional[int] = None,
                 reason: Optional[str] = None):
        super().__init__(message)
        self.status = status
        self.reason = reason


class UserRequestRejectedByPolicy(SkyTpuError):
    """Admin policy rejected the request."""


def format_blocklist(resources_list: List) -> str:
    return '\n'.join(f'  - {r}' for r in resources_list)
