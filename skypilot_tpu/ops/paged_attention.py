"""Pallas paged-attention decode kernel.

The gather-based paged decode (``inference/paged.py``) materializes a
contiguous copy of each slot's pages per layer — that copy is a full
extra read+write of the KV stream, and measured 0.37x the slot cache's
decode throughput on a v5e. This kernel is the vLLM/JetStream answer
built the TPU way (SURVEY §7 step 8 "paged KV in Pallas"): the page
table rides the grid as a SCALAR-PREFETCH operand, each grid step DMAs
one page of K/V straight from the pool in HBM into VMEM (no
intermediate copy), and a flash-style online softmax accumulates per
slot. Reads are LENGTH-EXACT per slot: a slot visits only
ceil(len/page) pages (the XLA gather path had to read the bucketed max
over all slots).

The pool stores pages HEAD-MAJOR: ``[L, n_pages, hkv, page, d]`` (and
scales ``[L, n_pages, hkv, page]``). Both attention contractions then
run straight off the DMA'd block — logits contract d (the minor dim of
q AND k, the MXU's native A.B^T form) and the p.v dot contracts page —
so the kernel performs NO in-kernel relayout. The previous token-major
``[page, hkv, d]`` layout needed k.transpose(1, 2, 0) / v.transpose(1,
0, 2) per page visit: a VPU lane-shuffle of every streamed byte that
capped the kernel at ~175 GB/s effective vs the slot cache's ~430
(perf.md "slot vs paged"). Head-major costs the WRITE side a strided
row append ([hkv, 1, d] slices, 32 runs x 128 B) — decode writes one
row per slot per step vs reading hundreds, so the read side wins.

The kernel computes the CACHE part of decode attention and returns the
partial-softmax triple (acc, m, l); the caller merges the current
token + fused-horizon ring rows (tiny tensors) in XLA — one softmax
across all three blocks, exactly like ``ops.attention.
ring_decode_attention``.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams across 0.4.x/0.5.x.
# Cost-model annotation (analysis/costmodel.py): these KERNEL BODIES
# (the names pallas_call eqns carry) take the FULL layer-stacked pool
# ([L, n_pages, ...]) with the layer as a scalar-prefetch block index
# and DMA one layer's pages per call — so the static analyzer prices
# their kv_pool/kv_scale operands at aval_bytes / L, not the whole
# stacked aval. ``_kernel_all`` (the all-layers sweep) is deliberately
# absent: it really does read every layer. A kernel that starts
# reading more than its layer must drop itself from this map (and eat
# the byte budget it then owes).
COST_KERNEL_KV_TRAFFIC = {
    '_kernel': 'one_layer_per_call',          # paged_decode_attention
    '_kernel_manual': 'one_layer_per_call',
    '_kernel_fused': 'one_layer_per_call',    # ..._fused (cross-layer)
}

_CompilerParams = getattr(pltpu, 'CompilerParams',
                          getattr(pltpu, 'TPUCompilerParams', None))

_NEG_INF = -1e30


def _dequantize_unpack_int4(x):
    """In-kernel int4 unpack: uint8 nibble bytes -> sign-extended int8
    codes with the minor dim doubled (low nibble first — the exact
    inverse of ``quantization.pack_int4(axis=-1)``). VPU bit-ops the
    compiler folds into the operand read; the HBM/VMEM stream stays
    packed at head_dim/2 bytes per row."""
    lo = (x & 0xF).astype(jnp.int8)
    lo = jnp.where(lo >= 8, lo - 16, lo)
    hi = (x >> 4).astype(jnp.int8)
    hi = jnp.where(hi >= 8, hi - 16, hi)
    return jnp.stack([lo, hi], axis=-1).reshape(
        x.shape[:-1] + (x.shape[-1] * 2,))


def _flash_page_update(qg, k_raw, v_raw, ks, vs, pos0, length,
                       m_s, l_s, acc_s, *, page: int, quantized: bool,
                       packed: bool):
    """One page block's online-softmax update against the VMEM scratch
    triple (m_s, l_s, acc_s) — the body shared by the per-layer,
    all-layer and fused-merge grid kernels.

    qg: [hkv, g, d] f32 PRE-SCALED queries; k_raw/v_raw: the DMA'd
    head-major page block ([hkv, page, d]; packed int4 pools arrive as
    [hkv, page, d/2] uint8 nibbles and unpack HERE, so the HBM stream
    stays packed); ks/vs: [hkv, page] f32 scale rows or None; pos0:
    the block's first absolute cache position (for the length mask)."""
    if packed:
        k_raw = _dequantize_unpack_int4(k_raw)
        v_raw = _dequantize_unpack_int4(v_raw)
    k = k_raw.astype(jnp.float32)                     # [hkv, page, d]
    v = v_raw.astype(jnp.float32)
    hkv, g, d = qg.shape
    hq = hkv * g
    # logits[h, g, p] = sum_d q[h,g,d] * k[h,p,d]: batched (over
    # hkv) A.B^T dots, both operands contracting their MINOR dim —
    # the head-major page layout feeds the MXU with no relayout.
    # Quantized pools: the per-row scales ride HEAD-MAJOR [hkv, page]
    # blocks and fold into the LOGITS (and into p for the v side).
    logits = jax.lax.dot_general(
        qg, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)           # [hkv, g, page]
    if quantized:
        logits = logits * ks[:, None, :]
    logits = logits.reshape(hq, page)
    pos = pos0 + jax.lax.broadcasted_iota(
        jnp.int32, (hq, page), 1)
    logits = jnp.where(pos < length, logits, _NEG_INF)
    m_prev = m_s[:, :1]                               # [hq, 1]
    m_page = jnp.max(logits, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_page)
    p = jnp.exp(logits - m_new)                       # [hq, page]
    p = jnp.where(pos < length, p, 0.0)
    corr = jnp.exp(m_prev - m_new)                    # [hq, 1]
    l_s[:] = l_s[:] * corr + jnp.broadcast_to(
        jnp.sum(p, axis=1, keepdims=True), l_s.shape)
    m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
    # pv[h,g,d] = sum_p p[h,g,p] * v[h,p,d]: batched over hkv.
    pg = p.reshape(hkv, g, page)
    if quantized:
        pg = pg * vs[:, None, :]
    pv = jax.lax.dot_general(
        pg, v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)           # [hkv, g, d]
    acc_s[:] = acc_s[:] * corr + pv.reshape(hq, d)


def _kernel(li_ref, table_ref, lens_ref,         # scalar prefetch
            q_ref, k_ref, v_ref,                 # inputs (VMEM blocks)
            *refs,                               # [ks, vs,] outs, scratch
            page: int, pages_per_slot: int, scale: float,
            quantized: bool, packed: bool = False):
    # li_ref carries the layer index: the pool stays [L, ...] and the
    # block specs index straight into it, so the per-layer slice is a
    # DMA address, never a materialized copy (feeding
    # dynamic_index_in_dim output into pallas_call would copy the whole
    # layer's pool per step — measured 0.4x the slot cache on a 7B).
    # Quantized pools carry two extra scale operands; the bf16 variant
    # omits them entirely (a dummy scale pool would cost a real HBM DMA
    # per page on the decode hot path).
    del li_ref                                   # consumed by index maps
    if quantized:
        ks_ref, vs_ref = refs[0], refs[1]
        refs = refs[2:]
    else:
        ks_ref = vs_ref = None
    acc_ref, m_ref, l_ref, m_s, l_s, acc_s = refs
    i = pl.program_id(0)                         # slot
    j = pl.program_id(1)                         # page index within slot

    @pl.when(j == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    length = lens_ref[i]
    # Number of pages this slot actually needs. Pages past that are
    # compute-masked here AND their DMA collapses: the index maps clamp
    # j to the last needed page, and Pallas skips the copy when a grid
    # step's block index repeats the previous step's — so a
    # short-context slot in a long-bucket table pays no extra HBM
    # traffic.
    needed = (length + page - 1) // page

    @pl.when(j < needed)
    def _compute():
        # Layout note: all Refs/values stay >=2D with the LANE dim last
        # (Mosaic rejects trailing size-1 ref dims: "unsupported output
        # implicit dimension"); m/l ride [hq, LANES] broadcast columns,
        # the same trick the flash kernel's lse uses.
        q = q_ref[0].astype(jnp.float32) * scale          # [hq, d]
        hq, d = q.shape
        hkv = k_ref.shape[2]
        g = hq // hkv
        _flash_page_update(
            q.reshape(hkv, g, d), k_ref[0, 0], v_ref[0, 0],
            ks_ref[0, 0].astype(jnp.float32) if quantized else None,
            vs_ref[0, 0].astype(jnp.float32) if quantized else None,
            j * page, length, m_s, l_s, acc_s,
            page=page, quantized=quantized, packed=packed)

    @pl.when(j == pages_per_slot - 1)
    def _finish():
        acc_ref[0] = acc_s[:]
        m_ref[0] = m_s[:]
        l_ref[0] = l_s[:]


def _kernel_all(table_ref, lens_ref,             # scalar prefetch
                q_ref, k_ref, v_ref,             # inputs (VMEM blocks)
                *refs,                           # [ks, vs,] outs, scratch
                page: int, pages_per_slot: int, scale: float,
                quantized: bool, packed: bool = False):
    """All-layer variant of ``_kernel``: the layer axis rides the GRID
    (``(slots, L, pages)``) instead of scalar prefetch, so ONE
    pallas_call streams every layer's pages — the per-call dispatch
    and pipeline-warmup cost is paid once instead of L times per step.
    Queries for ALL layers must exist up front (stacked
    [L, slots, hq, d]); the decode layer chain cannot provide that
    (layer l's query depends on layer l-1's output), so the decode hot
    path keeps per-layer calls — this kernel serves the paths where
    the full query stack IS known: the kv_round2 bandwidth probe and
    any cross-layer scoring pass."""
    if quantized:
        ks_ref, vs_ref = refs[0], refs[1]
        refs = refs[2:]
    else:
        ks_ref = vs_ref = None
    acc_ref, m_ref, l_ref, m_s, l_s, acc_s = refs
    i = pl.program_id(0)                         # slot
    j = pl.program_id(2)                         # page index within slot

    @pl.when(j == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    length = lens_ref[i]
    needed = (length + page - 1) // page

    @pl.when(j < needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale       # [hq, d]
        hq, d = q.shape
        hkv = k_ref.shape[2]
        g = hq // hkv
        _flash_page_update(
            q.reshape(hkv, g, d), k_ref[0, 0], v_ref[0, 0],
            ks_ref[0, 0].astype(jnp.float32) if quantized else None,
            vs_ref[0, 0].astype(jnp.float32) if quantized else None,
            j * page, length, m_s, l_s, acc_s,
            page=page, quantized=quantized, packed=packed)

    @pl.when(j == pages_per_slot - 1)
    def _finish():
        acc_ref[0, 0] = acc_s[:]
        m_ref[0, 0] = m_s[:]
        l_ref[0, 0] = l_s[:]


def _kernel_fused(li_ref, rl_ref, table_ref, lens_ref,  # scalar prefetch
                  q_ref, ksf_ref, vsf_ref, rk_ref, rv_ref,
                  k_ref, v_ref,
                  *refs,                         # [ks, vs,] out, scratch
                  page: int, pages_per_slot: int, scale: float,
                  quantized: bool, packed: bool = False):
    """Fused-merge variant of ``_kernel``: after the cache pages, the
    final grid step folds the fused-horizon ring rows and the current
    token into the SAME online softmax and emits the normalized
    per-layer attention output directly — the separate XLA
    ``merge_partial_with_ring_self`` program (and its [b, hq, d] f32
    partial triple round-tripping through HBM every layer of every
    decode step) disappears. The merge replicates the XLA three-block
    softmax op-for-op, so greedy decode stays byte-identical."""
    del li_ref                                   # consumed by index maps
    if quantized:
        ks_ref, vs_ref = refs[0], refs[1]
        refs = refs[2:]
    else:
        ks_ref = vs_ref = None
    out_ref, m_s, l_s, acc_s = refs
    i = pl.program_id(0)                         # slot
    j = pl.program_id(1)                         # page index within slot

    @pl.when(j == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    length = lens_ref[i]
    needed = (length + page - 1) // page

    @pl.when(j < needed)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale          # [hq, d]
        hq, d = q.shape
        hkv = k_ref.shape[2]
        g = hq // hkv
        _flash_page_update(
            q.reshape(hkv, g, d), k_ref[0, 0], v_ref[0, 0],
            ks_ref[0, 0].astype(jnp.float32) if quantized else None,
            vs_ref[0, 0].astype(jnp.float32) if quantized else None,
            j * page, length, m_s, l_s, acc_s,
            page=page, quantized=quantized, packed=packed)

    @pl.when(j == pages_per_slot - 1)
    def _finish():
        # Ring + self merge: the exact op sequence of
        # ``merge_partial_with_ring_self`` on this slot's row, with the
        # kernel scratch standing in for the cache partial.
        q = q_ref[0].astype(jnp.float32) * scale          # [hq, d]
        hq, d = q.shape
        hkv = rk_ref.shape[2]
        g = hq // hkv
        qg = q.reshape(hkv, g, d)
        rk = rk_ref[0].astype(jnp.float32)                # [H, hkv, d]
        rv = rv_ref[0].astype(jnp.float32)
        H = rk.shape[0]
        ring_len = rl_ref[0]
        # lr[h, g, kk] = sum_d qg[h,g,d] * rk[kk,h,d]
        lr = jax.lax.dot_general(
            qg, rk, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)           # [hkv, g, H]
        ridx = jax.lax.broadcasted_iota(jnp.int32, (hkv, g, H), 2)
        lr = jnp.where(ridx < ring_len, lr, _NEG_INF)
        ksf = ksf_ref[0].astype(jnp.float32)              # [hkv, d]
        vsf = vsf_ref[0].astype(jnp.float32)
        lself = jnp.sum(qg * ksf[:, None, :], axis=-1,
                        keepdims=True)                    # [hkv, g, 1]
        m_rs = jnp.maximum(jnp.max(lr, -1, keepdims=True), lself)
        p_r = jnp.exp(lr - m_rs)
        p_s = jnp.exp(lself - m_rs)
        l_rs = jnp.sum(p_r, -1, keepdims=True) + p_s
        # acc_rs[h,g,d] = sum_kk p_r[h,g,kk] * rv[kk,h,d] + p_s * v_self
        acc_rs = jax.lax.dot_general(
            p_r, rv, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) \
            + p_s * vsf[:, None, :]                       # [hkv, g, d]
        m_cg = m_s[:, :1].reshape(hkv, g, 1)
        l_cg = l_s[:, :1].reshape(hkv, g, 1)
        acc_cg = acc_s[:].reshape(hkv, g, d)
        m = jnp.maximum(m_cg, m_rs)
        c_c = jnp.exp(m_cg - m)
        c_rs = jnp.exp(m_rs - m)
        l = l_cg * c_c + l_rs * c_rs
        acc = acc_cg * c_c + acc_rs * c_rs
        out = acc / jnp.maximum(l, 1e-30)                 # [hkv, g, d]
        out_ref[0] = out.reshape(hq, d).astype(out_ref.dtype)


def _kernel_manual(li_ref, table_ref, lens_ref,   # scalar prefetch
                   q_ref, k_hbm, v_hbm,           # q VMEM; pools in HBM
                   *refs,
                   page: int, pages_per_block: int, scale: float,
                   quantized: bool):
    """Manual-DMA variant: grid is (slots,) and the kernel loops over
    the slot's pages itself with double-buffered async copies — block
    j+1 streams from HBM while block j computes. This beats the
    grid-per-page formulation (which pays per-grid-step pipeline
    overhead on hundreds of tiny steps per layer: measured 0.71x the
    slot cache's decode on a 7B) and reads length-exact blocks.

    ``pages_per_block`` (K) pages are fetched per loop iteration into
    per-page VMEM buffers (async copies issued back-to-back, one wait
    each); the final block's tail pages SKIP their DMA entirely
    (conditional issue + wait on the same predicate), so reads are
    length-exact at page granularity for any K. With the head-major
    pool every DMA (data AND scales) lands contiguously in its [kk]
    buffer and the flash update runs per page (unrolled online-softmax
    updates — exp over [hq, page] is VPU noise next to the stream).
    Measured on the 7B int8 decode at batch 48, K=1 beats K=2/4/8 by
    4-10% (1790 vs 1724/1625/1620 tok/s/chip): with no in-loop
    relayout to hide, per-iteration overhead is small and the K>1
    double-buffer granularity only delays the first compute."""
    if quantized:
        ks_hbm, vs_hbm = refs[0], refs[1]
        refs = refs[2:]
    else:
        ks_hbm = vs_hbm = None
    acc_ref, m_ref, l_ref = refs[:3]
    scratch = refs[3:]
    if quantized:
        kb, vb, ksb, vsb, sem = scratch
    else:
        kb, vb, sem = scratch
        ksb = vsb = None
    i = pl.program_id(0)
    li = li_ref[0]
    length = lens_ref[i]
    K = pages_per_block
    blk = K * page
    P = table_ref.shape[1]
    needed = (length + blk - 1) // blk            # K-page blocks
    hq, d = q_ref.shape[1], q_ref.shape[2]
    hkv = kb.shape[2]
    g = hq // hkv

    # Pages the slot actually holds: the final K-block's tail pages
    # (j*K + kk >= needed_pages) are SKIPPED, not clamped — their DMA
    # never issues and the compute mask zeroes their positions, so
    # reads are length-exact at page granularity instead of rounding
    # up to K*page per slot (at K=4/page=128 the rounding cost ~25%
    # extra KV stream on ~380-token average contexts).
    needed_pages = (length + page - 1) // page

    def dma_ops(buf, j, kk):
        pid = table_ref[i, jnp.minimum(j * K + kk, P - 1)]
        s0, s1 = 2 * kk, 2 * kk + 1
        out = [pltpu.make_async_copy(
                   k_hbm.at[li, pid],
                   kb.at[buf, kk],
                   sem.at[buf, s0]),
               pltpu.make_async_copy(
                   v_hbm.at[li, pid],
                   vb.at[buf, kk],
                   sem.at[buf, s1])]
        if quantized:
            out += [pltpu.make_async_copy(
                        ks_hbm.at[li, pid],
                        ksb.at[buf, kk],
                        sem.at[buf, 2 * K + s0]),
                    pltpu.make_async_copy(
                        vs_hbm.at[li, pid],
                        vsb.at[buf, kk],
                        sem.at[buf, 2 * K + s1])]
        return out

    def start_dmas(buf, j):
        for kk in range(K):
            if K == 1:
                # j*K+kk < needed_pages is the fori_loop bound itself:
                # no predicate, no skip machinery on the hot path.
                for dma in dma_ops(buf, j, kk):
                    dma.start()
                continue

            @pl.when(j * K + kk < needed_pages)
            def _go(buf=buf, j=j, kk=kk):
                for dma in dma_ops(buf, j, kk):
                    dma.start()

    def wait_dmas(buf, j):
        for kk in range(K):
            if K == 1:
                for dma in dma_ops(buf, j, kk):
                    dma.wait()
                continue

            @pl.when(j * K + kk < needed_pages)
            def _wait(buf=buf, j=j, kk=kk):
                for dma in dma_ops(buf, j, kk):
                    dma.wait()

    if K > 1:
        @pl.when(i == 0)
        def _zero_scratch():
            # Skipped tail pages never DMA; their buffers are read
            # (then compute-masked) anyway. Stale FINITE data from
            # earlier slots is harmless (p is zeroed at masked
            # positions before the v dot), but UNINITIALIZED f32/bf16
            # scratch can be NaN and 0 * NaN = NaN would poison acc —
            # so zero everything once. (At K=1 every executed
            # iteration DMAs its page: nothing stale is ever read.)
            kb[...] = jnp.zeros_like(kb)
            vb[...] = jnp.zeros_like(vb)
            if quantized:
                ksb[...] = jnp.zeros_like(ksb)
                vsb[...] = jnp.zeros_like(vsb)

    @pl.when(needed > 0)
    def _prefetch_first():
        start_dmas(0, 0)

    q = q_ref[0].astype(jnp.float32) * scale              # [hq, d]
    qg = q.reshape(hkv, g, d)

    def page_step(j, carry):
        carry_in = carry
        buf = j % 2

        @pl.when(j + 1 < needed)
        def _prefetch_next():
            start_dmas((j + 1) % 2, j + 1)

        wait_dmas(buf, j)
        acc, m_prev, l_prev = carry_in
        for kk in range(K):                       # unrolled: static K
            k = kb[buf, kk].astype(jnp.float32)           # [hkv, page, d]
            v = vb[buf, kk].astype(jnp.float32)
            # Batched A.B^T: both operands contract their minor dim
            # straight off the DMA'd head-major block — no relayout.
            logits = jax.lax.dot_general(
                qg, k, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)       # [hkv, g, page]
            if quantized:
                # head-major [hkv, page] scale blocks fold into the
                # logits (k side) and p (v side).
                logits = logits * ksb[buf, kk].astype(
                    jnp.float32)[:, None, :]
            logits = logits.reshape(hq, page)
            pos = (j * K + kk) * page + jax.lax.broadcasted_iota(
                jnp.int32, (hq, page), 1)
            logits = jnp.where(pos < length, logits, _NEG_INF)
            m_page = jnp.max(logits, axis=1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_page)
            p = jnp.exp(logits - m_new)
            p = jnp.where(pos < length, p, 0.0)
            corr = jnp.exp(m_prev - m_new)
            l_prev = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
            m_prev = m_new
            pg = p.reshape(hkv, g, page)
            if quantized:
                pg = pg * vsb[buf, kk].astype(jnp.float32)[:, None, :]
            pv = jax.lax.dot_general(
                pg, v, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)       # [hkv, g, d]
            acc = acc * corr + pv.reshape(hq, d)
        return acc, m_prev, l_prev

    acc0 = jnp.zeros((hq, d), jnp.float32)
    m0 = jnp.full((hq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((hq, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, needed, page_step, (acc0, m0, l0))
    acc_ref[0] = acc
    m_ref[0] = jnp.broadcast_to(m, m_ref.shape[1:])
    l_ref[0] = jnp.broadcast_to(l, l_ref.shape[1:])


def paged_decode_attention(
    q: jax.Array,                      # [slots, hq, d] current-token queries
    pool_k: jax.Array,                 # [L, n_pages, hkv, page, d]
    pool_v: jax.Array,
    table_p: jax.Array,                # [slots, P] page ids
    lengths: jax.Array,                # [slots] valid cache rows
    k_scale: Optional[jax.Array] = None,  # [L, n_pages, hkv, page]
    v_scale: Optional[jax.Array] = None,  # (HEAD-MAJOR; see caller)
    *,
    layer: jax.Array | int = 0,        # which pool layer to attend over
    scale: Optional[float] = None,
    interpret: bool = False,
    pages_per_block: int = 1,          # K pages DMA'd/computed per loop
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Partial softmax of each slot's query against its OWN pages of
    pool layer ``layer``. The full stacked pool is taken (with the
    layer as a scalar-prefetch index into the block specs) so the
    caller's per-layer scan never materializes a pool slice — a sliced
    operand would cost a whole extra read+write of the KV stream per
    decode step.

    Returns (acc [slots, hq, d] f32 — UNnormalized, rebased at m;
    m [slots, hq] f32; l [slots, hq] f32). Rows past ``lengths`` are
    masked; slots with length 0 return (0, -inf, 0) — merging is a
    no-op for them.
    """
    slots, hq, d = q.shape
    _, n_pages, hkv, page, dc = pool_k.shape
    P = table_p.shape[1]
    g = hq // hkv
    if scale is None:
        scale = d ** -0.5
    quantized = k_scale is not None
    # Packed int4 pools: uint8 nibble rows, dc == d/2 — the grid
    # kernel unpacks in VMEM (the HBM stream stays packed). The manual
    # path is excluded: its per-page DMA buffers would need a 64-lane
    # minor dim, below Mosaic's 128-lane tile.
    packed = pool_k.dtype == jnp.uint8

    LANES = 128
    li = jnp.asarray(layer, jnp.int32).reshape(1)
    out_shape_m = [
        jax.ShapeDtypeStruct((slots, hq, d), jnp.float32),
        jax.ShapeDtypeStruct((slots, hq, LANES), jnp.float32),
        jax.ShapeDtypeStruct((slots, hq, LANES), jnp.float32),
    ]
    # Manual path constraint: the per-page scale DMA slices a
    # [hkv, page] block whose minor dim (page) must be 128-aligned for
    # Mosaic — int8 pools need page % 128 == 0 (the engine's default
    # page is 128 for exactly this reason); bf16 pools have no scale
    # operand and run at any page size.
    if not interpret and not packed \
            and (k_scale is None or page % 128 == 0):
        # Compiled path: manual double-buffered K-page block DMA, one
        # grid step per slot (the per-page grid pays pipeline overhead
        # on hundreds of tiny steps; interpret mode has no DMA
        # emulation guarantee, so CPU tests ride the grid variant
        # below).
        # Clamp K so the double-buffered K/V blocks stay within ~16MB
        # of VMEM regardless of page size (page=256 at K=4 would need
        # 67MB of buffers alone and fail Mosaic's scoped-vmem checks).
        page_buf_bytes = 4 * page * hkv * d * pool_k.dtype.itemsize
        K = max(1, min(pages_per_block, P,
                       (16 * 1024 * 1024) // page_buf_bytes))
        kernel = functools.partial(_kernel_manual, page=page,
                                   pages_per_block=K, scale=scale,
                                   quantized=quantized)
        any_spec = pl.BlockSpec(memory_space=pl.ANY)
        in_specs = [
            pl.BlockSpec((1, hq, d),
                         lambda i, li, tab, lens: (i, 0, 0)),
            any_spec, any_spec,
        ]
        args = [li, table_p, lengths, q, pool_k, pool_v]
        n_sems = 2 * K
        scratch = [
            pltpu.VMEM((2, K, hkv, page, d), pool_k.dtype),
            pltpu.VMEM((2, K, hkv, page, d), pool_v.dtype),
        ]
        if quantized:
            in_specs += [any_spec, any_spec]
            args += [k_scale, v_scale]
            scratch += [pltpu.VMEM((2, K, hkv, page), jnp.float32),
                        pltpu.VMEM((2, K, hkv, page), jnp.float32)]
            n_sems = 4 * K
        scratch.append(pltpu.SemaphoreType.DMA((2, n_sems)))
        acc, m, l = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=3,           # layer, table, lengths
                grid=(slots,),
                in_specs=in_specs,
                out_specs=[
                    pl.BlockSpec((1, hq, d),
                                 lambda i, li, tab, lens: (i, 0, 0)),
                    pl.BlockSpec((1, hq, LANES),
                                 lambda i, li, tab, lens: (i, 0, 0)),
                    pl.BlockSpec((1, hq, LANES),
                                 lambda i, li, tab, lens: (i, 0, 0)),
                ],
                scratch_shapes=scratch,
            ),
            out_shape=out_shape_m,
            # MHA shapes (hq=32, d=128, K-page blocks) put outputs +
            # double buffers a few MB past Mosaic's default 16M scoped
            # vmem; the v5e has 128M physical VMEM.
            compiler_params=_CompilerParams(
                vmem_limit_bytes=48 * 1024 * 1024),
        )(*args)
        return acc, m[..., 0], l[..., 0]

    grid = (slots, P)
    kernel = functools.partial(_kernel, page=page, pages_per_slot=P,
                               scale=scale, quantized=quantized,
                               packed=packed)
    out_shape = out_shape_m

    def page_idx(i, j, lens):
        # Clamp past-needed steps to the last needed page: a repeated
        # block index skips the DMA (see kernel note).
        needed = (lens[i] + page - 1) // page
        return jnp.minimum(j, jnp.maximum(needed - 1, 0))

    in_specs = [
        pl.BlockSpec((1, hq, d), lambda i, j, li, tab, lens: (i, 0, 0)),
        pl.BlockSpec((1, 1, hkv, page, dc), lambda i, j, li, tab, lens:
                     (li[0], tab[i, page_idx(i, j, lens)], 0, 0, 0)),
        pl.BlockSpec((1, 1, hkv, page, dc), lambda i, j, li, tab, lens:
                     (li[0], tab[i, page_idx(i, j, lens)], 0, 0, 0)),
    ]
    args = [li, table_p, lengths, q, pool_k, pool_v]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, hkv, page),
                         lambda i, j, li, tab, lens:
                         (li[0], tab[i, page_idx(i, j, lens)], 0, 0)),
            pl.BlockSpec((1, 1, hkv, page),
                         lambda i, j, li, tab, lens:
                         (li[0], tab[i, page_idx(i, j, lens)], 0, 0)),
        ]
        args += [k_scale, v_scale]
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,               # layer, table, lengths
            grid=grid,
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, hq, d), lambda i, j, li, tab, lens:
                             (i, 0, 0)),
                pl.BlockSpec((1, hq, LANES), lambda i, j, li, tab, lens:
                             (i, 0, 0)),
                pl.BlockSpec((1, hq, LANES), lambda i, j, li, tab, lens:
                             (i, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((hq, LANES), jnp.float32),
                pltpu.VMEM((hq, LANES), jnp.float32),
                pltpu.VMEM((hq, d), jnp.float32),
            ],
        ),
        out_shape=out_shape,
        interpret=interpret,
    )(*args)
    return acc, m[..., 0], l[..., 0]


def paged_decode_attention_all_layers(
    q: jax.Array,                      # [L, slots, hq, d] stacked queries
    pool_k: jax.Array,                 # [L, n_pages, hkv, page, d]
    pool_v: jax.Array,
    table_p: jax.Array,                # [slots, P] page ids
    lengths: jax.Array,                # [slots] valid cache rows
    k_scale: Optional[jax.Array] = None,  # [L, n_pages, hkv, page]
    v_scale: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """ALL layers' cache partials in ONE pallas_call: the layer axis
    rides the grid (``(slots, L, P)``) so per-call dispatch and
    pipeline warmup are paid once per step instead of once per layer
    — the cross-layer batching front (a) of the KV round. Requires
    the full query stack up front, so the decode layer chain (where
    layer l's query depends on layer l-1) cannot use it; callers with
    all queries in hand (the kv_round2 bandwidth probe, cross-layer
    scoring) get L-for-1 dispatch amortization. Byte-identical to L
    stacked :func:`paged_decode_attention` calls.

    Returns (acc [L, slots, hq, d] f32 unnormalized, m, l
    [L, slots, hq] f32)."""
    L, slots, hq, d = q.shape
    _, n_pages, hkv, page, dc = pool_k.shape
    P = table_p.shape[1]
    if scale is None:
        scale = d ** -0.5
    quantized = k_scale is not None
    packed = pool_k.dtype == jnp.uint8
    LANES = 128

    kernel = functools.partial(_kernel_all, page=page, pages_per_slot=P,
                               scale=scale, quantized=quantized,
                               packed=packed)

    def page_idx(i, j, lens):
        needed = (lens[i] + page - 1) // page
        return jnp.minimum(j, jnp.maximum(needed - 1, 0))

    in_specs = [
        pl.BlockSpec((1, 1, hq, d),
                     lambda i, l, j, tab, lens: (l, i, 0, 0)),
        pl.BlockSpec((1, 1, hkv, page, dc),
                     lambda i, l, j, tab, lens:
                     (l, tab[i, page_idx(i, j, lens)], 0, 0, 0)),
        pl.BlockSpec((1, 1, hkv, page, dc),
                     lambda i, l, j, tab, lens:
                     (l, tab[i, page_idx(i, j, lens)], 0, 0, 0)),
    ]
    args = [table_p, lengths, q, pool_k, pool_v]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, hkv, page),
                         lambda i, l, j, tab, lens:
                         (l, tab[i, page_idx(i, j, lens)], 0, 0)),
            pl.BlockSpec((1, 1, hkv, page),
                         lambda i, l, j, tab, lens:
                         (l, tab[i, page_idx(i, j, lens)], 0, 0)),
        ]
        args += [k_scale, v_scale]
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,               # table, lengths
            grid=(slots, L, P),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, 1, hq, d),
                             lambda i, l, j, tab, lens: (l, i, 0, 0)),
                pl.BlockSpec((1, 1, hq, LANES),
                             lambda i, l, j, tab, lens: (l, i, 0, 0)),
                pl.BlockSpec((1, 1, hq, LANES),
                             lambda i, l, j, tab, lens: (l, i, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((hq, LANES), jnp.float32),
                pltpu.VMEM((hq, LANES), jnp.float32),
                pltpu.VMEM((hq, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((L, slots, hq, d), jnp.float32),
            jax.ShapeDtypeStruct((L, slots, hq, LANES), jnp.float32),
            jax.ShapeDtypeStruct((L, slots, hq, LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=48 * 1024 * 1024),
        interpret=interpret,
    )(*args)
    return acc, m[..., 0], l[..., 0]


def paged_decode_attention_fused(
    q: jax.Array,                      # [slots, hq, d] current-token queries
    k_self: jax.Array,                 # [slots, hkv, d] current-token rows
    v_self: jax.Array,
    ring_k: jax.Array,                 # [slots, H, hkv, d] fused-horizon ring
    ring_v: jax.Array,
    ring_len,                          # scalar: valid ring rows
    pool_k: jax.Array,                 # [L, n_pages, hkv, page, d]
    pool_v: jax.Array,
    table_p: jax.Array,                # [slots, P] page ids
    lengths: jax.Array,                # [slots] valid cache rows
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    *,
    layer: jax.Array | int = 0,
    scale: Optional[float] = None,
    interpret: bool = False,
) -> jax.Array:
    """The complete decode attention for one layer in ONE kernel:
    cache pages (online softmax, length-exact) THEN the ring + current
    token folded into the same accumulator on the final grid step —
    the normalized [slots, hq, d] output comes back in q's dtype and
    the XLA merge program (``merge_partial_with_ring_self``) plus its
    HBM round-trip of the f32 partial triple disappears from the layer
    scan. This is ``decode_impl='cross_layer'``'s kernel."""
    slots, hq, d = q.shape
    _, n_pages, hkv, page, dc = pool_k.shape
    P = table_p.shape[1]
    H = ring_k.shape[1]
    if scale is None:
        scale = d ** -0.5
    quantized = k_scale is not None
    packed = pool_k.dtype == jnp.uint8
    LANES = 128
    li = jnp.asarray(layer, jnp.int32).reshape(1)
    rl = jnp.asarray(ring_len, jnp.int32).reshape(1)

    kernel = functools.partial(_kernel_fused, page=page,
                               pages_per_slot=P, scale=scale,
                               quantized=quantized, packed=packed)

    def page_idx(i, j, lens):
        needed = (lens[i] + page - 1) // page
        return jnp.minimum(j, jnp.maximum(needed - 1, 0))

    in_specs = [
        pl.BlockSpec((1, hq, d),
                     lambda i, j, li, rl, tab, lens: (i, 0, 0)),
        pl.BlockSpec((1, hkv, d),
                     lambda i, j, li, rl, tab, lens: (i, 0, 0)),
        pl.BlockSpec((1, hkv, d),
                     lambda i, j, li, rl, tab, lens: (i, 0, 0)),
        pl.BlockSpec((1, H, hkv, d),
                     lambda i, j, li, rl, tab, lens: (i, 0, 0, 0)),
        pl.BlockSpec((1, H, hkv, d),
                     lambda i, j, li, rl, tab, lens: (i, 0, 0, 0)),
        pl.BlockSpec((1, 1, hkv, page, dc),
                     lambda i, j, li, rl, tab, lens:
                     (li[0], tab[i, page_idx(i, j, lens)], 0, 0, 0)),
        pl.BlockSpec((1, 1, hkv, page, dc),
                     lambda i, j, li, rl, tab, lens:
                     (li[0], tab[i, page_idx(i, j, lens)], 0, 0, 0)),
    ]
    args = [li, rl, table_p, lengths, q, k_self, v_self,
            ring_k, ring_v, pool_k, pool_v]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, hkv, page),
                         lambda i, j, li, rl, tab, lens:
                         (li[0], tab[i, page_idx(i, j, lens)], 0, 0)),
            pl.BlockSpec((1, 1, hkv, page),
                         lambda i, j, li, rl, tab, lens:
                         (li[0], tab[i, page_idx(i, j, lens)], 0, 0)),
        ]
        args += [k_scale, v_scale]
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,          # layer, ring_len, table, lens
            grid=(slots, P),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, hq, d),
                             lambda i, j, li, rl, tab, lens: (i, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((hq, LANES), jnp.float32),
                pltpu.VMEM((hq, LANES), jnp.float32),
                pltpu.VMEM((hq, d), jnp.float32),
            ],
        ),
        out_shape=[jax.ShapeDtypeStruct((slots, hq, d), q.dtype)],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=48 * 1024 * 1024),
        interpret=interpret,
    )(*args)[0]
    return out


def merge_partial_with_ring_self(
    partial: Tuple[jax.Array, jax.Array, jax.Array],
    q: jax.Array,                      # [b, 1, hq, d]
    k_self: jax.Array,                 # [b, 1, hkv, d]
    v_self: jax.Array,
    ring_k: jax.Array,                 # [b, H, hkv, d]
    ring_v: jax.Array,
    ring_len,                          # scalar: valid ring rows
    *,
    scale: Optional[float] = None,
) -> jax.Array:
    """Complete the decode softmax: merge the kernel's cache partial
    with the fused-horizon ring rows and the current token (tiny
    tensors — plain XLA). Mirrors ``ring_decode_attention``'s
    three-block softmax; returns [b, 1, hq, d]."""
    acc_c, m_c, l_c = partial
    b, _, hq, d = q.shape
    hkv = k_self.shape[2]
    g = hq // hkv
    if scale is None:
        scale = d ** -0.5
    qg = (q.astype(jnp.float32) * scale).reshape(b, hkv, g, d)

    lr = jnp.einsum('bhgd,bkhd->bhgk', qg,
                    ring_k.astype(jnp.float32))            # [b,hkv,g,H]
    H = ring_k.shape[1]
    ridx = jnp.arange(H)[None, None, None, :]
    lr = jnp.where(ridx < ring_len, lr, _NEG_INF)
    lself = jnp.einsum('bhgd,bhd->bhg', qg,
                       k_self[:, 0].astype(jnp.float32))[..., None]

    m_rs = jnp.maximum(jnp.max(lr, -1, keepdims=True), lself)
    p_r = jnp.exp(lr - m_rs)
    p_s = jnp.exp(lself - m_rs)
    l_rs = jnp.sum(p_r, -1, keepdims=True) + p_s
    acc_rs = (jnp.einsum('bhgk,bkhd->bhgd', p_r,
                         ring_v.astype(jnp.float32))
              + p_s * v_self[:, 0].astype(jnp.float32)[:, :, None, :])

    m_cg = m_c.reshape(b, hkv, g)[..., None]
    l_cg = l_c.reshape(b, hkv, g)[..., None]
    acc_cg = acc_c.reshape(b, hkv, g, d)

    m = jnp.maximum(m_cg, m_rs)
    c_c = jnp.exp(m_cg - m)
    c_rs = jnp.exp(m_rs - m)
    l = l_cg * c_c + l_rs * c_rs
    acc = acc_cg * c_c + acc_rs * c_rs
    out = acc / jnp.maximum(l, 1e-30)          # [b, hkv, g, d]
    return out.reshape(b, 1, hq, d).astype(q.dtype)
