"""Pallas TPU flash attention (FlashAttention-2 style), fwd + bwd.

The training hot op. Blockwise online-softmax attention that never
materializes the [sq, skv] score matrix in HBM: each (batch, head, q-block)
streams kv-blocks through VMEM with running max/denominator in f32 scratch;
the MXU sees [block_q, head_dim] x [head_dim, block_k] matmuls.

Conventions:
- Public entry takes the model layout [batch, seq, heads, head_dim] and
  handles GQA natively in the forward (kv BlockSpec index-maps q-head ->
  kv-head, no materialized repeat).
- Backward follows FA-2: recompute p from q,k and the saved logsumexp, one
  kernel for dk/dv (loop over q blocks) and one for dq (loop over kv
  blocks). For GQA the backward expands kv to query heads and sums dk/dv
  over the group afterwards (read-only expansion would race on writes).
- All softmax math in f32; inputs/outputs keep their dtype (bf16 typical).

Grid iteration on TPU is sequential with the last dimension innermost, so
f32 scratch accumulators persist across the kv-block loop — the standard
Pallas flash pattern.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)
_LANES = 128

# jax renamed TPUCompilerParams -> CompilerParams across 0.4.x/0.5.x;
# resolve whichever the pinned jax ships.
_CompilerParams = getattr(pltpu, 'CompilerParams',
                          getattr(pltpu, 'TPUCompilerParams', None))


# --------------------------------------------------------------------------
# Forward kernel
# --------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scratch, l_scratch, acc_scratch,
                *, scale: float, causal: bool, block_q: int, block_k: int,
                num_k_blocks: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, -jnp.inf)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    # For causal attention, kv blocks strictly above the diagonal contribute
    # nothing; skip their compute (the grid still visits them).
    q_start = iq * block_q
    k_start = ik * block_k
    if causal:
        needed = k_start <= q_start + block_q - 1
        last_needed_ik = jnp.minimum((q_start + block_q - 1) // block_k,
                                     num_k_blocks - 1)
    else:
        needed = jnp.bool_(True)
        last_needed_ik = num_k_blocks - 1

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)            # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)            # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]

        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)

        m_prev = m_scratch[:, :1]                      # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)     # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                         # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)                # [bq, 1]
        l_new = alpha * l_scratch[:, :1] + jnp.sum(p, -1, keepdims=True)

        v = v_ref[0, 0].astype(jnp.float32)            # [bk, d]
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, d]
        acc_scratch[:] = acc_scratch[:] * alpha + pv
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)

    @pl.when(ik == last_needed_ik)
    def _finalize():
        l = l_scratch[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scratch[:] / l_safe).astype(o_ref.dtype)
        lse = m_scratch[:, :1] + jnp.log(l_safe)       # [bq, 1]
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _fwd(q, k, v, *, scale, causal, block_q, block_k, interpret):
    """q: [b, hq, sq, d]; k/v: [b, hkv, skv, d] -> (out, lse)."""
    b, hq, sq, d = q.shape
    hkv, skv = k.shape[1], k.shape[2]
    group = hq // hkv
    block_q = min(block_q, sq)
    block_k = min(block_k, skv)
    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(skv, block_k)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, num_k_blocks=nk)

    out, lse = pl.pallas_call(
        kernel,
        grid=(b, hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda ib, ih, iq, ik: (ib, ih // group, ik, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda ib, ih, iq, ik: (ib, ih // group, ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, block_q, _LANES),
                         lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'parallel',
                                 'arbitrary')),
        interpret=interpret,
    )(q, k, v)
    return out, lse[..., 0]


# --------------------------------------------------------------------------
# Chunked-prefill forward (nonzero cache offset)
# --------------------------------------------------------------------------
def _fwd_chunk_kernel(cl_ref, q_ref, k_ref, v_ref, o_ref,
                      m_scratch, l_scratch, acc_scratch,
                      *, scale: float, split: int, block_q: int,
                      block_k: int, num_k_blocks: int):
    """Forward-only flash for a prefill CHUNK against a cache prefix.

    kv rows [0, split) are the per-row cache prefix (valid iff their
    index < cl_ref[b], the row's live cache length); rows [split, skv)
    are the chunk itself, causal against the chunk-local q positions.
    The chunk's absolute positions are cl_b + [0..sq) so every valid
    cache row strictly precedes every q row — only the length mask
    applies to the cache region."""
    ib = pl.program_id(0)
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    cl = cl_ref[ib]              # this row's live cache length (SMEM)

    @pl.when(ik == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, -jnp.inf)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    q_start = iq * block_q
    k_start = ik * block_k
    # A block is needed when it holds live cache rows (any index < cl)
    # or overlaps the chunk's causal triangle; blocks straddling
    # ``split`` evaluate both.
    needed = ((jnp.logical_and(k_start < split, k_start < cl))
              | jnp.logical_and(k_start + block_k > split,
                                k_start - split <= q_start + block_q - 1))
    # The diagonal chunk block is always needed and always has the
    # largest needed ik (chunk rows come after cache rows), so the
    # finalize index depends only on the q block.
    last_needed_ik = jnp.minimum((split + q_start + block_q - 1)
                                 // block_k, num_k_blocks - 1)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)            # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)            # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]

        q_pos = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_idx = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = jnp.where(k_idx < split, k_idx < cl,
                          k_idx - split <= q_pos)
        s = jnp.where(valid, s, DEFAULT_MASK_VALUE)

        m_prev = m_scratch[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scratch[:, :1] + jnp.sum(p, -1, keepdims=True)

        v = v_ref[0, 0].astype(jnp.float32)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scratch[:] = acc_scratch[:] * alpha + pv
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)

    @pl.when(ik == last_needed_ik)
    def _finalize():
        l = l_scratch[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scratch[:] / l_safe).astype(o_ref.dtype)


def _fwd_chunk(q, k, v, cache_len, *, scale, split, block_q, block_k,
               interpret):
    """q: [b, hq, sq, d]; k/v: [b, hkv, skv, d] laid out as
    [cache(:split); chunk]; cache_len: [b] int32 live cache rows."""
    b, hq, sq, d = q.shape
    hkv, skv = k.shape[1], k.shape[2]
    group = hq // hkv
    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(skv, block_k)

    kernel = functools.partial(
        _fwd_chunk_kernel, scale=scale, split=split, block_q=block_q,
        block_k=block_k, num_k_blocks=nk)

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,               # cache lengths [b]
            grid=(b, hq, nq, nk),
            in_specs=[
                pl.BlockSpec((1, 1, block_q, d),
                             lambda ib, ih, iq, ik, cl: (ib, ih, iq, 0)),
                pl.BlockSpec((1, 1, block_k, d),
                             lambda ib, ih, iq, ik, cl:
                             (ib, ih // group, ik, 0)),
                pl.BlockSpec((1, 1, block_k, d),
                             lambda ib, ih, iq, ik, cl:
                             (ib, ih // group, ik, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, block_q, d),
                                   lambda ib, ih, iq, ik, cl:
                                   (ib, ih, iq, 0)),
            scratch_shapes=[
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'parallel',
                                 'arbitrary')),
        interpret=interpret,
    )(cache_len.astype(jnp.int32), q, k, v)
    return out


# --------------------------------------------------------------------------
# Backward kernels
# --------------------------------------------------------------------------
def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scratch, dv_scratch,
                    *, scale: float, causal: bool, block_q: int,
                    block_k: int, num_q_blocks: int):
    ik = pl.program_id(2)
    iq = pl.program_id(3)

    @pl.when(iq == 0)
    def _init():
        dk_scratch[:] = jnp.zeros_like(dk_scratch)
        dv_scratch[:] = jnp.zeros_like(dv_scratch)

    q_start = iq * block_q
    k_start = ik * block_k
    needed = (jnp.bool_(True) if not causal
              else q_start + block_q - 1 >= k_start)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)            # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)            # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)            # [bk, d]
        do = do_ref[0, 0].astype(jnp.float32)          # [bq, d]
        lse = lse_ref[0, 0][:, :1]                     # [bq, 1]
        delta = delta_ref[0, 0][:, :1]                 # [bq, 1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)                           # [bq, bk]

        # dv += p^T @ do
        dv_scratch[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]
        # ds = p * (do @ v^T - delta)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds = p * (dp - delta) * scale
        # dk += ds^T @ q
        dk_scratch[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]

    @pl.when(iq == num_q_blocks - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scratch[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scratch[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scratch,
                   *, scale: float, causal: bool, block_q: int,
                   block_k: int, num_k_blocks: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_scratch[:] = jnp.zeros_like(dq_scratch)

    q_start = iq * block_q
    k_start = ik * block_k
    needed = (not causal) or (k_start <= q_start + block_q - 1)
    last_needed_ik = (num_k_blocks - 1 if not causal else
                      jnp.minimum((q_start + block_q - 1) // block_k,
                                  num_k_blocks - 1))

    @pl.when(needed if isinstance(needed, bool) else needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scratch[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, d]

    @pl.when(ik == last_needed_ik)
    def _finalize():
        dq_ref[0, 0] = dq_scratch[:].astype(dq_ref.dtype)


def _bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    do = g
    b, hq, sq, d = q.shape
    hkv, skv = k.shape[1], k.shape[2]
    group = hq // hkv
    # Expand kv to query heads for the backward (write-race-free).
    ke = jnp.repeat(k, group, axis=1) if group > 1 else k
    ve = jnp.repeat(v, group, axis=1) if group > 1 else v

    bq = min(block_q, sq)
    bk = min(block_k, skv)
    nq = pl.cdiv(sq, bq)
    nk = pl.cdiv(skv, bk)

    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1)                           # [b, hq, sq]
    lse_b = jnp.broadcast_to(lse[..., None], (*lse.shape, _LANES))
    delta_b = jnp.broadcast_to(delta[..., None], (*delta.shape, _LANES))

    q_spec = pl.BlockSpec((1, 1, bq, d), lambda ib, ih, a, b_: (ib, ih, b_, 0))
    k_spec = pl.BlockSpec((1, 1, bk, d), lambda ib, ih, a, b_: (ib, ih, a, 0))
    r_spec = pl.BlockSpec((1, 1, bq, _LANES),
                          lambda ib, ih, a, b_: (ib, ih, b_, 0))

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, block_q=bq,
        block_k=bk, num_q_blocks=nq)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, hq, nk, nq),
        in_specs=[q_spec, k_spec, k_spec, q_spec, r_spec, r_spec],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda ib, ih, a, b_: (ib, ih, a, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda ib, ih, a, b_: (ib, ih, a, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, skv, d), k.dtype),
            jax.ShapeDtypeStruct((b, hq, skv, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'parallel',
                                 'arbitrary')),
        interpret=interpret,
    )(q, ke, ve, do, lse_b, delta_b)

    q_spec2 = pl.BlockSpec((1, 1, bq, d), lambda ib, ih, a, b_: (ib, ih, a, 0))
    k_spec2 = pl.BlockSpec((1, 1, bk, d), lambda ib, ih, a, b_: (ib, ih, b_, 0))
    r_spec2 = pl.BlockSpec((1, 1, bq, _LANES),
                           lambda ib, ih, a, b_: (ib, ih, a, 0))
    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, block_q=bq,
        block_k=bk, num_k_blocks=nk)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, hq, nq, nk),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, r_spec2, r_spec2],
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda ib, ih, a, b_: (ib, ih, a, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'parallel',
                                 'arbitrary')),
        interpret=interpret,
    )(q, ke, ve, do, lse_b, delta_b)

    if group > 1:
        dk = dk.reshape(b, hkv, group, skv, d).sum(axis=2)
        dv = dv.reshape(b, hkv, group, skv, d).sum(axis=2)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# --------------------------------------------------------------------------
# Public entry ([b, s, h, d] layout, custom VJP)
# --------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhsd(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, interpret=interpret)
    return out


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, scale=scale, causal=causal, block_q=block_q,
                    block_k=block_k, interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(scale, causal, block_q, block_k, interpret, res, g):
    return _bwd(scale, causal, block_q, block_k, interpret, res, g)


_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    cache_len: Optional[jax.Array] = None,
                    kv_split: Optional[int] = None,
                    interpret: bool = False) -> jax.Array:
    """Flash attention over model-layout tensors.

    q: [b, sq, hq, d]; k/v: [b, skv, hkv, d] (GQA: hkv divides hq).
    Returns [b, sq, hq, d].

    Chunked prefill against a cache (nonzero cache offset): pass
    ``kv_split`` and ``cache_len`` with k/v laid out as
    ``[cache(:kv_split); chunk]``. Row b's cache prefix is valid up to
    ``cache_len[b]`` rows; the chunk (rows ``kv_split:``) is causal
    against q, whose absolute positions are ``cache_len[b] + [0..sq)``.
    This path is FORWARD-ONLY (inference prefill — no VJP).
    """
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    if hq % hkv != 0:
        raise ValueError(f'GQA requires hkv ({hkv}) to divide hq ({hq})')
    if cache_len is not None or kv_split is not None:
        if cache_len is None or kv_split is None:
            raise ValueError('cache_len and kv_split must be passed '
                             'together')
        if not causal:
            raise ValueError('chunked-prefill flash is causal only')
        if skv != kv_split + sq:
            raise ValueError(
                f'kv must be [cache({kv_split}); chunk({sq})] rows, got '
                f'skv={skv}')
    elif causal and sq != skv:
        raise ValueError(
            f'causal flash kernel assumes sq == skv (got {sq} vs {skv}); '
            'use ops.attention with q_offset for cached prefill/decode')
    # Shrink blocks (512 -> 256 -> 128) until they divide the sequence:
    # 512 is the throughput sweet spot, but seq lengths like 640/768 are
    # only 128-divisible and must still route through the kernel.
    block_q = min(block_q, sq)
    while block_q > 128 and sq % block_q:
        block_q //= 2
    block_k = min(block_k, skv)
    while block_k > 128 and skv % block_k:
        block_k //= 2
    # Mosaic needs sublane-aligned tiles: a sequence like 300 or 129
    # would otherwise sail through with block==sq and die in the kernel
    # compile with an opaque error. Short power-of-two sequences
    # (block == sq, multiple of 8) remain valid, as before.
    if (sq % block_q != 0 or skv % block_k != 0
            or block_q % 8 != 0 or block_k % 8 != 0):
        raise ValueError(
            f'seq lengths must be divisible by 8-aligned block sizes: '
            f'sq={sq} (block_q={block_q}), skv={skv} (block_k={block_k})')
    if scale is None:
        scale = q.shape[-1] ** -0.5
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    if cache_len is not None:
        out = _fwd_chunk(qt, kt, vt, cache_len, scale=scale,
                         split=kv_split, block_q=block_q,
                         block_k=block_k, interpret=interpret)
    else:
        out = _flash_bhsd(qt, kt, vt, scale, causal, block_q, block_k,
                          interpret)
    return out.transpose(0, 2, 1, 3)
