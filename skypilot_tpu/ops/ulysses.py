"""Ulysses-style sequence parallelism: all-to-all head scatter.

SURVEY §5 names this the alternative to ring attention for short rings:
instead of rotating KV blocks around the `sp` axis (sp_size ppermute
hops, work growing with ring length), ONE all-to-all converts the
sequence sharding into a head sharding, every rank runs ordinary
full-sequence attention over its head slice, and a second all-to-all
converts back. Two collectives total — cheaper than a ring whenever the
head count divides nicely over sp and the full sequence fits per-rank
memory for the attention inner op (flash keeps that O(s)).

Layout contract (matches ring_attention): q/k/v arrive sharded
[b, S/sp, h, d] over the `sp` mesh axis; output leaves the same way.
Inside the manual region each rank holds [b, S, h/sp, d].

GQA: kv heads must also divide sp; when they don't, kv is expanded to
per-q-head form first (same policy as ring_attention — positional
pairing must stay aligned).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _attn_body(q, k, v, *, axis_name: str, causal: bool, scale: float):
    """Per-rank: seq-sharded -> head-sharded via all_to_all, full-seq
    attention on the local heads, then back."""
    # [b, s_local, h, d] -> [b, S, h_local, d]: split the HEAD axis
    # across ranks, concatenate the SEQ axis.
    qh = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    from skypilot_tpu.ops.attention import attention
    # attention() applies its own 1/sqrt(d); fold the CALLER's scale in
    # by pre-scaling q (keeps the auto flash-kernel dispatch, which has
    # no scale parameter at this layer).
    d = qh.shape[-1]
    qh = (qh * jnp.asarray(scale * d ** 0.5, qh.dtype))
    out = attention(qh, kh, vh, causal=causal, impl='auto')
    # [b, S, h_local, d] -> [b, s_local, h, d]
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(
    q: jax.Array,                      # [b, S, h, d] global (sharded)
    k: jax.Array,                      # [b, S, hkv, d]
    v: jax.Array,
    mesh: jax.sharding.Mesh,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    axis_name: str = 'sp',
    rules=None,
) -> jax.Array:
    """Exact attention with the sequence sharded over ``axis_name`` via
    head-scatter all-to-alls. Requires ``(n_heads / tp) % sp == 0``."""
    from skypilot_tpu.ops.ring_attention import seq_parallel_call
    sp = mesh.shape[axis_name]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if sp == 1:
        from skypilot_tpu.ops.attention import reference_attention
        return reference_attention(q, k, v, causal=causal, scale=scale)
    # Heads are ALSO tp-sharded entering the manual region, so the
    # all-to-all splits LOCAL head counts.
    tp = mesh.shape.get('tp', 1)
    if q.shape[2] % tp or (q.shape[2] // tp) % sp:
        raise ValueError(
            f'ulysses needs n_heads per tp shard ({q.shape[2]}/{tp}) '
            f'divisible by {axis_name}={sp}; use ring attention for '
            'head counts below tp*sp')
    body = functools.partial(_attn_body, axis_name=axis_name,
                             causal=causal, scale=scale)
    # GQA grouping survives the head scatter iff each kv head's whole
    # q-group lands on one (tp, sp) shard — hence the tp*sp modulus.
    return seq_parallel_call(q, k, v, mesh, body, axis_name=axis_name,
                             rules=rules, kv_head_modulus=tp * sp)
