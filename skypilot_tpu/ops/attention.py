"""Attention ops: reference jnp implementation + dispatcher.

The dispatcher routes to the Pallas flash-attention kernel on TPU for long
sequences (see ``skypilot_tpu/ops/flash_attention.py``) and falls back to the
XLA einsum path elsewhere (CPU tests, tiny shapes, decode).

Shapes follow the [batch, seq, heads, head_dim] convention throughout.
GQA: kv heads are broadcast to query heads here (the kernel keeps them
folded to save bandwidth).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """[b, s, kv_heads, d] -> [b, s, kv_heads * n_rep, d]."""
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, n_rep, d)
                            ).reshape(b, s, h * n_rep, d)


def reference_attention(
    q: jax.Array,                      # [b, sq, h, d]
    k: jax.Array,                      # [b, skv, hkv, d]
    v: jax.Array,                      # [b, skv, hkv, d]
    *,
    causal: bool = True,
    q_offset: Optional[jax.Array] = None,   # position of q[0] within kv seq
    kv_len: Optional[jax.Array] = None,     # valid kv length (decode masking)
    scale: Optional[float] = None,
) -> jax.Array:
    """Plain XLA attention in fp32 accumulation.

    GQA is computed in grouped form ([b, s, hkv, group, d] einsums) so kv is
    never materialized at query-head width — in decode the kv cache read IS
    the bandwidth bill, a 4x broadcast would quadruple it.

    ``q_offset``/``kv_len`` support the decode path: q positions are
    ``q_offset + [0..sq)``, kv positions beyond ``kv_len`` are masked out.
    """
    b, sq, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    scale = scale if scale is not None else d ** -0.5
    qg = q.reshape(b, sq, hkv, group, d)

    logits = jnp.einsum('bqhgd,bkhd->bhgqk', qg, k,
                        preferred_element_type=jnp.float32) * scale

    skv = k.shape[1]
    kv_pos = jnp.arange(skv)[None, None, None, None, :]        # [1,1,1,1,k]
    mask = jnp.ones((1, 1, 1, sq, skv), dtype=bool)
    if causal:
        q_pos = jnp.arange(sq)[None, None, None, :, None]      # [1,1,1,q,1]
        if q_offset is not None:
            q_pos = q_pos + jnp.reshape(q_offset, (-1, 1, 1, 1, 1))
        mask = mask & (kv_pos <= q_pos)
    if kv_len is not None:
        mask = mask & (kv_pos < jnp.reshape(kv_len, (-1, 1, 1, 1, 1)))
    logits = jnp.where(mask, logits, -1e30)

    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum('bhgqk,bkhd->bqhgd', probs, v)
    return out.reshape(b, sq, h, d)


@functools.partial(jax.jit, static_argnames=('causal', 'impl'))
def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    q_offset: Optional[jax.Array] = None,
    kv_len: Optional[jax.Array] = None,
    impl: str = 'auto',
) -> jax.Array:
    """Dispatching attention entry point used by the models.

    impl: 'auto' | 'xla' | 'flash'. 'auto' picks flash on TPU when the
    shape fits the kernel's tiling (training-style full-sequence causal
    attention); decode (sq==1) always uses the XLA path, which fuses into
    a single-pass softmax anyway.
    """
    use_flash = False
    if impl == 'flash':
        use_flash = True
    elif impl == 'auto':
        sq = q.shape[1]
        on_tpu = jax.default_backend() == 'tpu'
        use_flash = (on_tpu and causal and sq >= 256 and sq % 128 == 0
                     and q.shape[-1] % 128 == 0 and q_offset is None
                     and kv_len is None)
    if use_flash:
        from skypilot_tpu.ops import flash_attention
        return flash_attention.flash_attention(q, k, v, causal=causal)
    return reference_attention(q, k, v, causal=causal, q_offset=q_offset,
                               kv_len=kv_len)
