"""Attention ops: reference jnp implementation + dispatcher.

The dispatcher routes to the Pallas flash-attention kernel on TPU for long
sequences (see ``skypilot_tpu/ops/flash_attention.py``) and falls back to the
XLA einsum path elsewhere (CPU tests, tiny shapes, decode).

Shapes follow the [batch, seq, heads, head_dim] convention throughout.
GQA: kv heads are broadcast to query heads here (the kernel keeps them
folded to save bandwidth).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """[b, s, kv_heads, d] -> [b, s, kv_heads * n_rep, d]."""
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, n_rep, d)
                            ).reshape(b, s, h * n_rep, d)


def reference_attention(
    q: jax.Array,                      # [b, sq, h, d]
    k: jax.Array,                      # [b, skv, hkv, d]
    v: jax.Array,                      # [b, skv, hkv, d]
    *,
    causal: bool = True,
    q_offset: Optional[jax.Array] = None,   # position of q[0] within kv seq
    kv_len: Optional[jax.Array] = None,     # valid kv length (decode masking)
    scale: Optional[float] = None,
) -> jax.Array:
    """Plain XLA attention in fp32 accumulation.

    GQA is computed in grouped form ([b, s, hkv, group, d] einsums) so kv is
    never materialized at query-head width — in decode the kv cache read IS
    the bandwidth bill, a 4x broadcast would quadruple it.

    ``q_offset``/``kv_len`` support the decode path: q positions are
    ``q_offset + [0..sq)``, kv positions beyond ``kv_len`` are masked out.
    """
    b, sq, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    scale = scale if scale is not None else d ** -0.5
    qg = q.reshape(b, sq, hkv, group, d)

    logits = jnp.einsum('bqhgd,bkhd->bhgqk', qg, k,
                        preferred_element_type=jnp.float32) * scale

    skv = k.shape[1]
    kv_pos = jnp.arange(skv)[None, None, None, None, :]        # [1,1,1,1,k]
    mask = jnp.ones((1, 1, 1, sq, skv), dtype=bool)
    if causal:
        q_pos = jnp.arange(sq)[None, None, None, :, None]      # [1,1,1,q,1]
        if q_offset is not None:
            q_pos = q_pos + jnp.reshape(q_offset, (-1, 1, 1, 1, 1))
        mask = mask & (kv_pos <= q_pos)
    if kv_len is not None:
        mask = mask & (kv_pos < jnp.reshape(kv_len, (-1, 1, 1, 1, 1)))
    logits = jnp.where(mask, logits, -1e30)

    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum('bhgqk,bkhd->bqhgd', probs, v)
    return out.reshape(b, sq, h, d)


def _scale_bhk(s: Optional[jax.Array]) -> Optional[jax.Array]:
    """[b, S, hkv, 1] fp32 per-row KV scales -> [b, hkv, 1, 1, S] for
    folding into 'bhgqk' logits/probs."""
    if s is None:
        return None
    return jnp.transpose(s[..., 0], (0, 2, 1))[:, :, None, None, :]


def _unpack_kv(cache_k: jax.Array, cache_v: jax.Array):
    """int4 KV caches arrive as packed uint8 nibble rows ([..., d//2]);
    unpack to int8 CODES so the downstream contraction + scale-fold
    math is byte-for-byte the int8 path's (absmax/7 scales instead of
    absmax/127 — the fold is scale-agnostic). The unpack is VPU work
    XLA fuses into the operand read; the HBM stream stays packed."""
    if cache_k.dtype != jnp.uint8:
        return cache_k, cache_v
    from skypilot_tpu.models import quantization
    return (quantization.unpack_int4(cache_k, axis=-1),
            quantization.unpack_int4(cache_v, axis=-1))


def cached_attention(
    q: jax.Array,                      # [b, s, h, d] new-token queries
    k_new: jax.Array,                  # [b, s, hkv, d] new-token keys
    v_new: jax.Array,                  # [b, s, hkv, d]
    cache_k: jax.Array,                # [b, S, hkv, d] cache WITHOUT new rows
    cache_v: jax.Array,                # [b, S, hkv, d]
    cache_len: jax.Array,              # [b] valid cache entries
    *,
    scale: Optional[float] = None,
    k_scale: Optional[jax.Array] = None,   # [b, S, hkv, 1] fp32: cache_k/v
    v_scale: Optional[jax.Array] = None,   # are int8 CODES when given
) -> jax.Array:
    """Decode/prefill attention against a KV cache without materializing
    the concatenated [cache; new] sequence.

    Two score blocks share one numerically-stable softmax: the cache block
    (positions < cache_len; all strictly precede the new tokens, so only
    the length mask applies) and the new-token block (standard causal
    within the s new positions). The cache is only READ here — the caller
    scatters the new rows in afterwards — so a decode step's cache traffic
    is one streaming read plus an s-token write, not a full rewrite.
    fp32 logits/softmax; GQA stays in grouped form (no kv broadcast).

    int8 caches pass CODES + per-row scales: the codes are contracted
    directly (int8 stays int8 across HBM — a pre-dequantized operand
    streams ~30% slower, see quantization.qeinsum) and the row scales
    fold into the fp32 logits (K) / probabilities (V) exactly. int4
    caches pass PACKED uint8 nibble rows (see ``_unpack_kv``)."""
    cache_k, cache_v = _unpack_kv(cache_k, cache_v)
    b, s, h, d = q.shape
    hkv = k_new.shape[2]
    group = h // hkv
    scale = scale if scale is not None else d ** -0.5
    qg = q.reshape(b, s, hkv, group, d)

    lc = jnp.einsum('bqhgd,bkhd->bhgqk', qg, cache_k,
                    preferred_element_type=jnp.float32) * scale
    ks = _scale_bhk(k_scale)
    if ks is not None:
        lc = lc * ks
    ls = jnp.einsum('bqhgd,bkhd->bhgqk', qg, k_new,
                    preferred_element_type=jnp.float32) * scale

    S = cache_k.shape[1]
    kv_pos = jnp.arange(S)[None, None, None, None, :]
    lc = jnp.where(kv_pos < jnp.reshape(cache_len, (-1, 1, 1, 1, 1)),
                   lc, -1e30)
    q_pos = jnp.arange(s)[None, None, None, :, None]
    new_pos = jnp.arange(s)[None, None, None, None, :]
    ls = jnp.where(new_pos <= q_pos, ls, -1e30)

    m = jnp.maximum(jnp.max(lc, -1, keepdims=True),
                    jnp.max(ls, -1, keepdims=True))
    ec = jnp.exp(lc - m)
    es = jnp.exp(ls - m)
    denom = jnp.sum(ec, -1, keepdims=True) + jnp.sum(es, -1, keepdims=True)
    pc = ec / denom
    vs = _scale_bhk(v_scale)
    if vs is not None:
        pc = pc * vs
        out = jnp.einsum('bhgqk,bkhd->bqhgd', pc.astype(jnp.bfloat16),
                         cache_v, preferred_element_type=jnp.float32
                         ).astype(q.dtype)
    else:
        out = jnp.einsum('bhgqk,bkhd->bqhgd', pc.astype(cache_v.dtype),
                         cache_v)
    out = out + jnp.einsum('bhgqk,bkhd->bqhgd',
                           (es / denom).astype(v_new.dtype), v_new)
    return out.reshape(b, s, h, d)


def chunk_attention(
    q: jax.Array,                      # [b, s, h, d] chunk queries
    k_new: jax.Array,                  # [b, s, hkv, d] chunk keys
    v_new: jax.Array,                  # [b, s, hkv, d]
    cache_k: jax.Array,                # [b, S, hkv, d] cache WITHOUT chunk
    cache_v: jax.Array,
    cache_len: jax.Array,              # [b] valid cache rows (= chunk
                                       #     start position per row)
    *,
    impl: str = 'auto',
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Chunked-prefill attention: one chunk of new tokens against the
    rows already in the cache (nonzero cache offset) plus causal
    self-attention within the chunk.

    Dispatches to the Pallas flash kernel's chunk path on TPU when the
    shapes fit its tiling (bf16 caches only — int8 codes + scales fold
    into the XLA two-block softmax instead): the cache prefix and the
    chunk concatenate into one kv operand and the kernel masks by
    ``cache_len``. Everywhere else it is ``cached_attention`` — the
    same two-block stable softmax the decode path uses, so a chunk at
    offset 0 matches plain causal attention numerically."""
    s, d = q.shape[1], q.shape[-1]
    S = cache_k.shape[1]
    use_flash = (impl in ('auto', 'flash') and k_scale is None
                 and jax.default_backend() == 'tpu'
                 and s >= 128 and s % 128 == 0 and d % 128 == 0
                 and (S + s) % 128 == 0)
    if use_flash:
        from skypilot_tpu.ops import flash_attention as fa
        cat_k = jnp.concatenate([cache_k.astype(k_new.dtype), k_new],
                                axis=1)
        cat_v = jnp.concatenate([cache_v.astype(v_new.dtype), v_new],
                                axis=1)
        return fa.flash_attention(q, cat_k, cat_v, causal=True,
                                  cache_len=cache_len, kv_split=S)
    return cached_attention(q, k_new, v_new, cache_k, cache_v, cache_len,
                            k_scale=k_scale, v_scale=v_scale)


def ring_decode_attention(
    q: jax.Array,                      # [b, 1, h, d] current-token queries
    k_self: jax.Array,                 # [b, 1, hkv, d] current-token keys
    v_self: jax.Array,                 # [b, 1, hkv, d]
    cache_k: jax.Array,                # [b, S, hkv, d] read-only main cache
    cache_v: jax.Array,
    cache_len: jax.Array,              # [b] valid main-cache entries (fixed
                                       #     for the whole fused horizon)
    ring_k: jax.Array,                 # [b, H, hkv, d] this horizon's rows
    ring_v: jax.Array,
    ring_len: jax.Array,               # scalar: rows < ring_len are valid
    *,
    scale: Optional[float] = None,
    k_scale: Optional[jax.Array] = None,   # [b, S, hkv, 1] fp32: cache_k/v
    v_scale: Optional[jax.Array] = None,   # are int8 CODES when given
) -> jax.Array:
    """Single-token decode attention over three blocks sharing one
    softmax: the main cache (read-only inside a fused multi-step decode —
    its mask depends only on the horizon-start lengths), the ring of rows
    produced by the previous steps of this horizon, and the current
    token. Keeping the main cache out of the loop carry is the point:
    XLA then streams it instead of re-materializing it every step.
    int8 caches pass codes + scales (see cached_attention); int4
    caches pass packed uint8 nibble rows (see ``_unpack_kv``)."""
    cache_k, cache_v = _unpack_kv(cache_k, cache_v)
    b, _, h, d = q.shape
    hkv = k_self.shape[2]
    group = h // hkv
    scale = scale if scale is not None else d ** -0.5
    qg = q.reshape(b, 1, hkv, group, d)

    lc = jnp.einsum('bqhgd,bkhd->bhgqk', qg, cache_k,
                    preferred_element_type=jnp.float32) * scale
    ks = _scale_bhk(k_scale)
    if ks is not None:
        lc = lc * ks
    lr = jnp.einsum('bqhgd,bkhd->bhgqk', qg, ring_k,
                    preferred_element_type=jnp.float32) * scale
    lself = jnp.einsum('bqhgd,bqhd->bhgq', qg, k_self,
                       preferred_element_type=jnp.float32)[..., None] * scale

    S = cache_k.shape[1]
    pos = jnp.arange(S)[None, None, None, None, :]
    lc = jnp.where(pos < jnp.reshape(cache_len, (-1, 1, 1, 1, 1)), lc, -1e30)
    rpos = jnp.arange(ring_k.shape[1])[None, None, None, None, :]
    lr = jnp.where(rpos < ring_len, lr, -1e30)

    m = jnp.maximum(jnp.max(lc, -1, keepdims=True),
                    jnp.max(lr, -1, keepdims=True))
    m = jnp.maximum(m, lself)
    ec, er, es = jnp.exp(lc - m), jnp.exp(lr - m), jnp.exp(lself - m)
    denom = (jnp.sum(ec, -1, keepdims=True) +
             jnp.sum(er, -1, keepdims=True) + es)
    pc = ec / denom
    vs = _scale_bhk(v_scale)
    if vs is not None:
        out = jnp.einsum('bhgqk,bkhd->bqhgd',
                         (pc * vs).astype(jnp.bfloat16), cache_v,
                         preferred_element_type=jnp.float32
                         ).astype(q.dtype)
    else:
        out = jnp.einsum('bhgqk,bkhd->bqhgd', pc.astype(cache_v.dtype),
                         cache_v)
    out = out + jnp.einsum('bhgqk,bkhd->bqhgd',
                           (er / denom).astype(ring_v.dtype), ring_v)
    w_self = (es / denom)[..., 0].transpose(0, 3, 1, 2)   # [b, 1, hkv, g]
    out = out + w_self.astype(v_self.dtype)[..., None] * \
        v_self[:, :, :, None, :]
    return out.reshape(b, 1, h, d)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    q_offset: Optional[jax.Array] = None,
    kv_len: Optional[jax.Array] = None,
    impl: str = 'auto',
) -> jax.Array:
    """Dispatching attention entry point used by the models.

    impl: 'auto' | 'xla' | 'flash' | 'ring' | 'ulysses'. 'auto' picks
    flash on TPU when the shape fits the kernel's tiling (training-style
    full-sequence causal attention); decode (sq==1) always uses the XLA
    path, which fuses into a single-pass softmax anyway. 'ring' shards
    the sequence over the sp mesh axis with ppermute KV rotation;
    'ulysses' shards it with all-to-all head scatter — two collectives
    total; needs (n_heads/tp) divisible by sp.

    Deliberately NOT wrapped in jax.jit: the 'ring' dispatch reads the
    ambient mesh context at trace time, and a jit cache here is not keyed
    on that context — a cached no-mesh trace would silently serve the
    non-ring path inside a mesh. Callers jit the surrounding computation.
    """
    if impl in ('ring', 'ulysses'):
        # Sequence-parallel exact attention over the sp mesh axis
        # (training/prefill; decode never shards its single query).
        assert q_offset is None and kv_len is None, (
            'sequence-parallel attention is a full-sequence path; '
            'decode masking args are not supported')
        from skypilot_tpu.ops import ring_attention as ring
        mesh = ring.current_mesh()
        if mesh is not None and mesh.shape.get('sp', 1) > 1:
            if impl == 'ulysses':
                from skypilot_tpu.ops.ulysses import ulysses_attention
                return ulysses_attention(q, k, v, mesh, causal=causal)
            return ring.ring_attention(q, k, v, mesh, causal=causal)
        return reference_attention(q, k, v, causal=causal)
    if impl not in ('auto', 'xla', 'flash'):
        raise ValueError(f'unknown attention impl {impl!r}')
    use_flash = False
    if impl == 'flash':
        use_flash = True
    elif impl == 'auto':
        sq = q.shape[1]
        on_tpu = jax.default_backend() == 'tpu'
        use_flash = (on_tpu and causal and sq >= 256 and sq % 128 == 0
                     and q.shape[-1] % 128 == 0 and q_offset is None
                     and kv_len is None)
    if use_flash:
        from skypilot_tpu.ops import flash_attention
        return flash_attention.flash_attention(q, k, v, causal=causal)
    return reference_attention(q, k, v, causal=causal, q_offset=q_offset,
                               kv_len=kv_len)
