"""Ring attention: exact attention over a sequence sharded across the
``sp`` mesh axis (long-context / context parallelism).

SURVEY §5 mandate (absent from the reference, which delegates long-context
to external engines): each sp-rank holds one contiguous block of the
sequence; KV blocks rotate around the ring via ``lax.ppermute`` (ICI
neighbor hops) while a numerically-stable blockwise softmax accumulates —
the same online (m, l, acc) recurrence as flash attention, so the full
[s, s] score matrix never materializes and per-device memory stays
O(s_local). After sp_size hops every rank has attended to the whole
sequence exactly once.

Causal masking uses global positions (rank * s_local + local offset).
Blocks strictly in the future (fully masked) are SKIPPED via lax.cond —
roughly half the causal FLOPs. Work remains imbalanced across ranks
(rank r computes r+1 blocks); a striped/zigzag block layout would
balance it at the cost of a token-permutation contract with callers.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
import warnings as _warnings
with _warnings.catch_warnings():
    # jax >= 0.8 renames this to jax.shard_map but changes the kwarg
    # surface (check_rep -> check_vma); keep the stable experimental
    # import until the minimum jax is bumped.
    _warnings.simplefilter('ignore', DeprecationWarning)
    from jax.experimental.shard_map import shard_map

_NEG_INF = -1e30


def _ring_body(q: jax.Array, k: jax.Array, v: jax.Array, *,
               axis_name: str, axis_size: int, causal: bool,
               scale: float) -> jax.Array:
    """Per-shard computation (runs under shard_map).

    q: [b, s, h, d]; k, v: [b, s, hkv, d] — the LOCAL sequence blocks.
    """
    b, s, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    qg = (q.astype(jnp.float32) * scale).reshape(b, s, hkv, group, d)

    my_rank = lax.axis_index(axis_name)
    q_pos = my_rank * s + jnp.arange(s)                 # global q positions

    m = jnp.full((b, hkv, group, s, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, hkv, group, s, 1), jnp.float32)
    acc = jnp.zeros((b, s, hkv, group, d), jnp.float32)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, step_idx):
        m, l, acc, k_blk, v_blk = carry
        # After `step_idx` forward rotations we hold the block that
        # originated at rank (my_rank - step_idx).
        blk_rank = (my_rank - step_idx) % axis_size

        def compute(operand):
            m, l, acc, k_blk, v_blk = operand
            logits = jnp.einsum('bqhgd,bkhd->bhgqk', qg,
                                k_blk.astype(jnp.float32))
            if causal:
                k_pos = blk_rank * s + jnp.arange(s)
                mask = k_pos[None, None, None, None, :] <= \
                    q_pos[None, None, None, :, None]
                logits = jnp.where(mask, logits, _NEG_INF)
            m_new = jnp.maximum(m, jnp.max(logits, -1, keepdims=True))
            p = jnp.exp(logits - m_new)
            corr = jnp.exp(m - m_new)
            l_new = l * corr + jnp.sum(p, -1, keepdims=True)
            acc_new = acc * corr.transpose(0, 3, 1, 2, 4) + jnp.einsum(
                'bhgqk,bkhd->bqhgd', p, v_blk.astype(jnp.float32))
            return m_new, l_new, acc_new

        if causal:
            # Blocks from HIGHER ranks are entirely in the future: skip
            # their matmuls (lax.cond executes one branch) — the ring
            # still rotates, but ~half the causal FLOPs disappear. (The
            # permute below depends only on k/v, so XLA forwards blocks
            # through skipping ranks without waiting on compute.)
            m, l, acc = lax.cond(
                blk_rank <= my_rank, compute,
                lambda operand: (operand[0], operand[1], operand[2]),
                (m, l, acc, k_blk, v_blk))
        else:
            m, l, acc = compute((m, l, acc, k_blk, v_blk))
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (m, l, acc, k_nxt, v_nxt), None

    (m, l, acc, _, _), _ = lax.scan(
        step, (m, l, acc, k, v), jnp.arange(axis_size))
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2, 4)
    return out.reshape(b, s, h, d).astype(q.dtype)


def seq_parallel_call(q, k, v, mesh, body, *, axis_name: str = 'sp',
                      rules=None, kv_head_modulus: Optional[int] = None):
    """Shared scaffolding for sequence-parallel attention variants
    (ring, ulysses): seq-divisibility check, GQA kv expansion when local
    kv heads wouldn't pair positionally with local q heads, spec_for +
    shard_map plumbing. ``kv_head_modulus`` is what the GLOBAL kv head
    count must divide by to stay in grouped form (tp for ring, tp*sp
    for ulysses); ``body(q, k, v)`` runs in the manual region."""
    from skypilot_tpu.parallel.mesh import spec_for
    sp = mesh.shape[axis_name]
    if q.shape[1] % sp:
        raise ValueError(
            f'sequence-parallel attention needs seq ({q.shape[1]}) '
            f'divisible by {axis_name}={sp}')
    # The manual bodies pair local q heads with local kv heads
    # positionally, so kv heads must shard exactly like q heads. For
    # MQA/GQA below the modulus, materialize the per-q-head kv (repeat)
    # instead of replicating — replicated kv with sharded q would
    # silently mis-pair GQA groups.
    tp = mesh.shape.get('tp', 1)
    modulus = kv_head_modulus if kv_head_modulus is not None else tp
    if k.shape[2] % modulus:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qspec = spec_for(('batch', 'seq', 'heads', 'head_dim'), rules)
    kspec = (qspec if k.shape[2] == q.shape[2] else
             spec_for(('batch', 'seq', 'kv_heads', 'head_dim'), rules))
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(qspec, kspec, kspec),
        out_specs=qspec,
        check_rep=False,
    )
    return fn(q, k, v)


def ring_attention(
    q: jax.Array,                      # [b, S, h, d] global (sharded) arrays
    k: jax.Array,                      # [b, S, hkv, d]
    v: jax.Array,
    mesh: jax.sharding.Mesh,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    axis_name: str = 'sp',
    rules=None,
) -> jax.Array:
    """Exact attention with the sequence dimension sharded over
    ``axis_name``. Call inside (or outside) jit with a mesh whose
    ``axis_name`` size divides the sequence length."""
    sp = mesh.shape[axis_name]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if sp == 1:
        from skypilot_tpu.ops.attention import reference_attention
        return reference_attention(q, k, v, causal=causal, scale=scale)
    body = functools.partial(_ring_body, axis_name=axis_name,
                             axis_size=sp, causal=causal, scale=scale)
    return seq_parallel_call(q, k, v, mesh, body, axis_name=axis_name,
                             rules=rules)


def current_mesh() -> Optional[jax.sharding.Mesh]:
    """The active `with mesh:` context, if any (no public jax API; same
    probe as llama._in_mesh_context — fails open to None)."""
    try:
        from jax._src import mesh as mesh_src
        env_mesh = mesh_src.thread_resources.env.physical_mesh
        return None if env_mesh.empty else env_mesh
    except Exception:  # pylint: disable=broad-except
        return None
