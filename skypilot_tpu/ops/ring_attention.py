"""Ring attention: exact attention over a sequence sharded across the
``sp`` mesh axis (long-context / context parallelism).

SURVEY §5 mandate (absent from the reference, which delegates long-context
to external engines): each sp-rank holds one contiguous block of the
sequence; KV blocks rotate around the ring via ``lax.ppermute`` (ICI
neighbor hops) while a numerically-stable blockwise softmax accumulates —
the same online (m, l, acc) recurrence as flash attention, so the full
[s, s] score matrix never materializes and per-device memory stays
O(s_local). After sp_size hops every rank has attended to the whole
sequence exactly once.

Causal masking uses global positions (rank * s_local + local offset).
Blocks strictly in the future (fully masked) are SKIPPED via lax.cond —
roughly half the causal FLOPs.

**Zigzag layout** (``layout='zigzag'``, the default for causal): the
contiguous layout leaves rank r computing r+1 blocks — rank sp-1 does sp
times rank 0's work and sets the wall clock. Zigzag splits each local
block into two halves and re-deals them so rank r holds halves r and
2*sp-1-r (one early, one late): every rank then computes exactly 2
half-block pairs per ring step (+ the diagonal tick) — balanced to
within one diagonal. The re-deal happens INSIDE this op via two static
ppermute permutations (rope/positions are applied by the caller before
the ring, so no token-permutation contract leaks out).

**Flash block body** (``block_impl='flash'``, auto-selected on aligned
shapes): each (q-block, k-block) pair runs the Pallas FlashAttention
kernel, whose (out, lse) merges into the running softmax — block logits
never materialize in fp32. The kernel forward has no lse-cotangent
rule, so the block is wrapped in a custom_vjp whose backward
re-derives the block with the einsum reference (same rematerialization
trade flash itself makes).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
import warnings as _warnings
with _warnings.catch_warnings():
    # jax >= 0.8 renames this to jax.shard_map but changes the kwarg
    # surface (check_rep -> check_vma); keep the stable experimental
    # import until the minimum jax is bumped.
    _warnings.simplefilter('ignore', DeprecationWarning)
    from jax.experimental.shard_map import shard_map

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# Block bodies: einsum accumulate vs flash (out, lse) merge
# ---------------------------------------------------------------------------
def _block_ref(q, k, v, scale: float, causal: bool):
    """Reference block attention returning (normalized out, lse) — the
    differentiable twin of the flash kernel's forward contract."""
    b, sq, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    qg = (q.astype(jnp.float32) * scale).reshape(b, sq, hkv, group, d)
    logits = jnp.einsum('bqhgd,bkhd->bhgqk', qg, k.astype(jnp.float32))
    if causal:
        sk = k.shape[1]
        mask = jnp.arange(sk)[None, :] <= jnp.arange(sq)[:, None]
        logits = jnp.where(mask[None, None, None], logits, _NEG_INF)
    m = jnp.max(logits, -1, keepdims=True)
    p = jnp.exp(logits - m)
    l = jnp.sum(p, -1, keepdims=True)
    o = jnp.einsum('bhgqk,bkhd->bqhgd', p, v.astype(jnp.float32))
    o = (o / jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2, 4)
         ).reshape(b, sq, h, d)
    lse = (m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]   # [b,hkv,g,sq]
    return o, lse.reshape(b, hkv * group, sq)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_block(q, k, v, scale: float, causal: bool):
    """Pallas flash forward returning (out, lse); backward re-derives
    through the einsum reference (correct lse cotangents — the kernel's
    own vjp has none)."""
    from skypilot_tpu.ops import flash_attention as fa
    interpret = jax.default_backend() != 'tpu'
    sq, sk = q.shape[1], k.shape[1]
    bq = min(512, sq)
    bk = min(512, sk)
    out, lse = fa._fwd(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                       v.transpose(0, 2, 1, 3), scale=scale,
                       causal=causal, block_q=bq, block_k=bk,
                       interpret=interpret)
    return out.transpose(0, 2, 1, 3), lse


def _flash_block_fwd(q, k, v, scale, causal):
    return _flash_block(q, k, v, scale, causal), (q, k, v)


def _flash_block_bwd(scale, causal, res, cts):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _block_ref(q, k, v, scale, causal),
                     q, k, v)
    do, dlse = cts
    return vjp((do.astype(jnp.float32), dlse.astype(jnp.float32)))


_flash_block.defvjp(_flash_block_fwd, _flash_block_bwd)


def _merge_block(m, l, acc, q, k_blk, v_blk, *, scale, causal,
                 use_flash):
    """Merge one (q, k_blk) pair into the running (m, l, acc) softmax.
    m, l: [b, hkv, g, s, 1]; acc: [b, s, hkv, g, d]."""
    b, s, h, d = q.shape
    hkv = m.shape[1]
    group = h // hkv
    if use_flash:
        o_n, lse = _flash_block(q, k_blk, v_blk, scale, causal)
        lse = lse.reshape(b, hkv, group, s)[..., None]
        o_n = o_n.reshape(b, s, hkv, group, d).astype(jnp.float32)
        m_new = jnp.maximum(m, lse)
        corr = jnp.exp(m - m_new)
        w = jnp.exp(lse - m_new)                # block's Σexp rebased
        l_new = l * corr + w
        acc_new = (acc * corr.transpose(0, 3, 1, 2, 4)
                   + o_n * w.transpose(0, 3, 1, 2, 4))
        return m_new, l_new, acc_new
    qg = (q.astype(jnp.float32) * scale).reshape(b, s, hkv, group, d)
    logits = jnp.einsum('bqhgd,bkhd->bhgqk', qg,
                        k_blk.astype(jnp.float32))
    if causal:
        sk = k_blk.shape[1]
        mask = jnp.arange(sk)[None, :] <= jnp.arange(s)[:, None]
        logits = jnp.where(mask[None, None, None], logits, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(logits, -1, keepdims=True))
    p = jnp.exp(logits - m_new)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, -1, keepdims=True)
    acc_new = acc * corr.transpose(0, 3, 1, 2, 4) + jnp.einsum(
        'bhgqk,bkhd->bqhgd', p, v_blk.astype(jnp.float32))
    return m_new, l_new, acc_new


def _init_softmax_state(b, hkv, group, s, d):
    m = jnp.full((b, hkv, group, s, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, hkv, group, s, 1), jnp.float32)
    acc = jnp.zeros((b, s, hkv, group, d), jnp.float32)
    return m, l, acc


def _finish_softmax(m, l, acc, b, s, h, d, dtype):
    del m
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2, 4)
    return out.reshape(b, s, h, d).astype(dtype)


def _ring_body(q: jax.Array, k: jax.Array, v: jax.Array, *,
               axis_name: str, axis_size: int, causal: bool,
               scale: float, use_flash: bool) -> jax.Array:
    """Contiguous-layout per-shard computation (runs under shard_map).

    q: [b, s, h, d]; k, v: [b, s, hkv, d] — the LOCAL sequence blocks.
    """
    b, s, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    m, l, acc = _init_softmax_state(b, hkv, group, s, d)
    my_rank = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    # Step 0 hoisted: the diagonal block (own k/v) is plain causal.
    m, l, acc = _merge_block(m, l, acc, q, k, v, scale=scale,
                             causal=causal, use_flash=use_flash)
    k_blk = lax.ppermute(k, axis_name, perm)
    v_blk = lax.ppermute(v, axis_name, perm)

    def step(carry, step_idx):
        m, l, acc, k_blk, v_blk = carry
        # After `step_idx` forward rotations we hold the block that
        # originated at rank (my_rank - step_idx) — strictly past or
        # strictly future at block granularity, never diagonal.
        blk_rank = (my_rank - step_idx) % axis_size

        def compute(operand):
            m, l, acc, k_blk, v_blk = operand
            return _merge_block(m, l, acc, q, k_blk, v_blk, scale=scale,
                                causal=False, use_flash=use_flash)

        if causal:
            # Blocks from HIGHER ranks are entirely in the future: skip
            # their matmuls (lax.cond executes one branch) — the ring
            # still rotates, but ~half the causal FLOPs disappear. (The
            # permute below depends only on k/v, so XLA forwards blocks
            # through skipping ranks without waiting on compute.)
            m, l, acc = lax.cond(
                blk_rank < my_rank, compute,
                lambda operand: (operand[0], operand[1], operand[2]),
                (m, l, acc, k_blk, v_blk))
        else:
            m, l, acc = compute((m, l, acc, k_blk, v_blk))
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (m, l, acc, k_nxt, v_nxt), None

    if axis_size > 1:
        (m, l, acc, _, _), _ = lax.scan(
            step, (m, l, acc, k_blk, v_blk),
            jnp.arange(1, axis_size))
    return _finish_softmax(m, l, acc, b, s, h, d, q.dtype)


# ---------------------------------------------------------------------------
# Zigzag layout
# ---------------------------------------------------------------------------
def _zigzag_perms(sp: int):
    """Static ppermute permutations dealing contiguous half-blocks into
    the zigzag layout. Rank r's contiguous block = global halves
    (2r, 2r+1); zigzag wants halves (r, 2sp-1-r). Half h's target rank
    is min(h, 2sp-1-h); restricted to even (resp. odd) source halves
    this is a rank permutation, so two ppermutes re-deal everything."""
    t0 = {r: (2 * r if 2 * r < sp else 2 * sp - 1 - 2 * r)
          for r in range(sp)}
    t1 = {r: (2 * r + 1 if 2 * r + 1 < sp else 2 * sp - 2 - 2 * r)
          for r in range(sp)}
    perm0 = [(r, t0[r]) for r in range(sp)]
    perm1 = [(r, t1[r]) for r in range(sp)]
    inv0 = [(t0[r], r) for r in range(sp)]
    inv1 = [(t1[r], r) for r in range(sp)]
    return perm0, perm1, inv0, inv1


def _zigzag_deal(x, axis_name: str, sp: int, rank):
    """[b, s, ...] contiguous local block -> (lo, hi) zigzag halves
    ([b, s/2, ...] each): lo = global half `rank`, hi = `2sp-1-rank`."""
    half = x.shape[1] // 2
    perm0, perm1, _, _ = _zigzag_perms(sp)
    r0 = lax.ppermute(x[:, :half], axis_name, perm0)
    r1 = lax.ppermute(x[:, half:], axis_name, perm1)
    even = (rank % 2 == 0)
    lo = jnp.where(even, r0, r1)
    hi = jnp.where(even, r1, r0)
    return lo, hi


def _zigzag_undeal(lo, hi, axis_name: str, sp: int, rank):
    """Inverse of _zigzag_deal: back to the contiguous local block."""
    _, _, inv0, inv1 = _zigzag_perms(sp)
    even = (rank % 2 == 0)
    via0 = jnp.where(even, lo, hi)
    via1 = jnp.where(even, hi, lo)
    b0 = lax.ppermute(via0, axis_name, inv0)
    b1 = lax.ppermute(via1, axis_name, inv1)
    return jnp.concatenate([b0, b1], axis=1)


def _zigzag_body(q: jax.Array, k: jax.Array, v: jax.Array, *,
                 axis_name: str, axis_size: int, scale: float,
                 use_flash: bool) -> jax.Array:
    """Balanced causal ring: every rank computes exactly 2 half-block
    pairs per off-diagonal step (contiguous layout: rank r computes r+1
    — rank sp-1 sets the wall clock at sp*rank0's work). Causal only —
    non-causal is already balanced in the contiguous layout."""
    sp = axis_size
    b, s, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    half = s // 2
    rank = lax.axis_index(axis_name)

    q_lo, q_hi = _zigzag_deal(q, axis_name, sp, rank)
    k_lo, k_hi = _zigzag_deal(k, axis_name, sp, rank)
    v_lo, v_hi = _zigzag_deal(v, axis_name, sp, rank)

    m_lo, l_lo, a_lo = _init_softmax_state(b, hkv, group, half, d)
    m_hi, l_hi, a_hi = _init_softmax_state(b, hkv, group, half, d)

    def kw(causal):
        return dict(scale=scale, causal=causal, use_flash=use_flash)

    # Diagonal tick (src == rank): q_lo·k_lo diag, q_hi·k_lo full,
    # q_hi·k_hi diag.
    m_lo, l_lo, a_lo = _merge_block(m_lo, l_lo, a_lo, q_lo, k_lo, v_lo,
                                    **kw(True))
    m_hi, l_hi, a_hi = _merge_block(m_hi, l_hi, a_hi, q_hi, k_lo, v_lo,
                                    **kw(False))
    m_hi, l_hi, a_hi = _merge_block(m_hi, l_hi, a_hi, q_hi, k_hi, v_hi,
                                    **kw(True))

    perm = [(i, (i + 1) % sp) for i in range(sp)]
    kl = lax.ppermute(k_lo, axis_name, perm)
    vl = lax.ppermute(v_lo, axis_name, perm)
    kh = lax.ppermute(k_hi, axis_name, perm)
    vh = lax.ppermute(v_hi, axis_name, perm)

    def step(carry, t):
        m_lo, l_lo, a_lo, m_hi, l_hi, a_hi, kl, vl, kh, vh = carry
        src = (rank - t) % sp

        def past_src(op):
            # src < rank: its k_lo half (global idx src) is past BOTH
            # our halves; its k_hi half (2sp-1-src) is future for both.
            m_lo, l_lo, a_lo, m_hi, l_hi, a_hi = op
            m_lo, l_lo, a_lo = _merge_block(m_lo, l_lo, a_lo, q_lo,
                                            kl, vl, **kw(False))
            m_hi, l_hi, a_hi = _merge_block(m_hi, l_hi, a_hi, q_hi,
                                            kl, vl, **kw(False))
            return m_lo, l_lo, a_lo, m_hi, l_hi, a_hi

        def future_src(op):
            # src > rank: its k_lo half is future for q_lo but past for
            # q_hi; its k_hi half (2sp-1-src < 2sp-1-rank) is past for
            # q_hi only.
            m_lo, l_lo, a_lo, m_hi, l_hi, a_hi = op
            m_hi, l_hi, a_hi = _merge_block(m_hi, l_hi, a_hi, q_hi,
                                            kl, vl, **kw(False))
            m_hi, l_hi, a_hi = _merge_block(m_hi, l_hi, a_hi, q_hi,
                                            kh, vh, **kw(False))
            return m_lo, l_lo, a_lo, m_hi, l_hi, a_hi

        # Both branches: exactly 2 half-block fulls — balanced.
        m_lo, l_lo, a_lo, m_hi, l_hi, a_hi = lax.cond(
            src < rank, past_src, future_src,
            (m_lo, l_lo, a_lo, m_hi, l_hi, a_hi))
        kl2 = lax.ppermute(kl, axis_name, perm)
        vl2 = lax.ppermute(vl, axis_name, perm)
        kh2 = lax.ppermute(kh, axis_name, perm)
        vh2 = lax.ppermute(vh, axis_name, perm)
        return (m_lo, l_lo, a_lo, m_hi, l_hi, a_hi,
                kl2, vl2, kh2, vh2), None

    if sp > 1:
        (m_lo, l_lo, a_lo, m_hi, l_hi, a_hi, *_), _ = lax.scan(
            step, (m_lo, l_lo, a_lo, m_hi, l_hi, a_hi, kl, vl, kh, vh),
            jnp.arange(1, sp))

    out_lo = _finish_softmax(m_lo, l_lo, a_lo, b, half, h, d, q.dtype)
    out_hi = _finish_softmax(m_hi, l_hi, a_hi, b, half, h, d, q.dtype)
    return _zigzag_undeal(out_lo, out_hi, axis_name, sp, rank)


def ring_schedule_cost(sp: int, rank: int, layout: str) -> float:
    """Static per-rank compute cost in full-block-pair units (an s x s
    score block = 1.0; a half-pair = 0.25; a half-diag = 0.125) — what
    the balance tests assert on."""
    if layout == 'contiguous':
        return 0.5 + rank                      # diag + `rank` past blocks
    # zigzag: diagonal tick = 2 half-diags + 1 half-full = 0.5; every
    # other step = 2 half-fulls = 0.5. Rank-independent == balanced,
    # and equal to the ideal total/sp (sp^2/2 work over sp ranks).
    del rank
    return 0.5 + (sp - 1) * 0.5


def seq_parallel_call(q, k, v, mesh, body, *, axis_name: str = 'sp',
                      rules=None, kv_head_modulus: Optional[int] = None):
    """Shared scaffolding for sequence-parallel attention variants
    (ring, ulysses): seq-divisibility check, GQA kv expansion when local
    kv heads wouldn't pair positionally with local q heads, spec_for +
    shard_map plumbing. ``kv_head_modulus`` is what the GLOBAL kv head
    count must divide by to stay in grouped form (tp for ring, tp*sp
    for ulysses); ``body(q, k, v)`` runs in the manual region."""
    from skypilot_tpu.parallel.mesh import spec_for
    sp = mesh.shape[axis_name]
    if q.shape[1] % sp:
        raise ValueError(
            f'sequence-parallel attention needs seq ({q.shape[1]}) '
            f'divisible by {axis_name}={sp}')
    # The manual bodies pair local q heads with local kv heads
    # positionally, so kv heads must shard exactly like q heads. For
    # MQA/GQA below the modulus, materialize the per-q-head kv (repeat)
    # instead of replicating — replicated kv with sharded q would
    # silently mis-pair GQA groups.
    tp = mesh.shape.get('tp', 1)
    modulus = kv_head_modulus if kv_head_modulus is not None else tp
    if k.shape[2] % modulus:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qspec = spec_for(('batch', 'seq', 'heads', 'head_dim'), rules)
    kspec = (qspec if k.shape[2] == q.shape[2] else
             spec_for(('batch', 'seq', 'kv_heads', 'head_dim'), rules))
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(qspec, kspec, kspec),
        out_specs=qspec,
        check_rep=False,
    )
    return fn(q, k, v)


def ring_attention(
    q: jax.Array,                      # [b, S, h, d] global (sharded) arrays
    k: jax.Array,                      # [b, S, hkv, d]
    v: jax.Array,
    mesh: jax.sharding.Mesh,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    axis_name: str = 'sp',
    rules=None,
    layout: str = 'auto',              # 'auto' | 'zigzag' | 'contiguous'
    block_impl: str = 'auto',          # 'auto' | 'flash' | 'einsum'
) -> jax.Array:
    """Exact attention with the sequence dimension sharded over
    ``axis_name``. Call inside (or outside) jit with a mesh whose
    ``axis_name`` size divides the sequence length.

    ``layout='zigzag'`` (auto default for causal, sp>1, even local
    halves) balances causal work across ranks; ``block_impl='flash'``
    (auto on 128-aligned shapes) runs each block pair through the
    Pallas kernel."""
    sp = mesh.shape[axis_name]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if sp == 1:
        from skypilot_tpu.ops.attention import reference_attention
        return reference_attention(q, k, v, causal=causal, scale=scale)
    s_local = q.shape[1] // sp
    if layout == 'auto':
        layout = ('zigzag' if causal and s_local % 2 == 0 else
                  'contiguous')
    if block_impl == 'auto':
        blk = s_local // 2 if layout == 'zigzag' else s_local
        block_impl = ('flash' if blk % 128 == 0
                      and q.shape[3] % 128 == 0 else 'einsum')
    use_flash = block_impl == 'flash'
    if layout == 'zigzag' and causal:
        body = functools.partial(_zigzag_body, axis_name=axis_name,
                                 axis_size=sp, scale=scale,
                                 use_flash=use_flash)
    else:
        body = functools.partial(_ring_body, axis_name=axis_name,
                                 axis_size=sp, causal=causal,
                                 scale=scale, use_flash=use_flash)
    return seq_parallel_call(q, k, v, mesh, body, axis_name=axis_name,
                             rules=rules)


def current_mesh() -> Optional[jax.sharding.Mesh]:
    """The active mesh context, if any. Delegates to llama's probe:
    public ``jax.sharding.get_mesh`` first, then the private
    legacy-context locations, warning ONCE if every probe RAISES (a jax
    bump silently disabling sequence parallelism would otherwise have
    no signal; ``tests/test_aux_subsystems.py::test_ambient_mesh_probe``
    pins probe health on the in-repo jax)."""
    from skypilot_tpu.models.llama import _ambient_mesh
    return _ambient_mesh()
